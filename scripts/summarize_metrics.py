"""Render a --metrics_jsonl telemetry file: loss/throughput/MFU/memory
curves + a text summary. Replaces the old single-purpose loss plot
(utils/plotting.py) as the post-hoc view of a run — the JSONL is the
artifact, this is just one renderer over it.

  python scripts/summarize_metrics.py out/metrics.jsonl [--out out/metrics.png]

Prints the run header, per-event-kind counts, and final/peak numbers to
stdout; writes a 2x2 figure (train/val loss, tok/s, MFU, memory) when
matplotlib is available (text summary still works without it).
"""

import argparse
import json
import os
import sys


def load_rows(path):
    header, metrics, events = None, [], []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"warning: line {i + 1} unparseable ({e}); skipped",
                      file=sys.stderr)
                continue
            kind = row.get("type")
            if kind == "header":
                header = row
            elif kind == "metrics":
                metrics.append(row)
            elif kind == "event":
                events.append(row)
    return header, metrics, events


def column(rows, key):
    """(steps, values) for rows where ``key`` is a number."""
    pairs = [(r["step"], r[key]) for r in rows
             if isinstance(r.get(key), (int, float))]
    return [p[0] for p in pairs], [p[1] for p in pairs]


def summarize(header, metrics, events):
    if header:
        mesh = header.get("mesh_shape")
        model = (header.get("model") or {}).get("name", "?")
        print(f"run: model={model} jax={header.get('jax_version')} "
              f"devices={header.get('device_count')}x"
              f"{header.get('device_kind')} mesh={mesh}")
    print(f"{len(metrics)} metric rows, {len(events)} events")
    by_kind = {}
    for e in events:
        by_kind[e["event"]] = by_kind.get(e["event"], 0) + 1
    if by_kind:
        print("events:", ", ".join(f"{k} x{v}"
                                   for k, v in sorted(by_kind.items())))
    if not metrics:
        return
    last = metrics[-1]
    steps, tok_s = column(metrics, "tok_s")
    _, train = column(metrics, "train_loss")
    _, mfu = column(metrics, "mfu")
    _, hbm = column(metrics, "hbm_peak_bytes")
    print(f"final: step={last.get('step')} "
          f"tokens_seen={last.get('tokens_seen')} "
          f"train_loss={train[-1] if train else 'n/a'}")
    if tok_s:
        print(f"throughput: last={tok_s[-1]:.0f} tok/s "
              f"peak={max(tok_s):.0f} mean={sum(tok_s) / len(tok_s):.0f}")
    if mfu:
        print(f"mfu: last={100 * mfu[-1]:.1f}% peak={100 * max(mfu):.1f}%")
    else:
        print("mfu: n/a (no TPU peak-FLOPs entry for this device kind)")
    if hbm:
        print(f"peak HBM: {max(hbm) / 1024**3:.2f} GiB")
    ckpt = [e for e in events if e["event"] == "checkpoint_save"
            and isinstance(e.get("seconds"), (int, float))]
    if ckpt:
        secs = [e["seconds"] for e in ckpt]
        print(f"checkpoints: {len(ckpt)} saves, "
              f"mean {sum(secs) / len(secs):.2f}s, max {max(secs):.2f}s")


def plot(metrics, out_path):
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib unavailable; skipping figure", file=sys.stderr)
        return None
    fig, axes = plt.subplots(2, 2, figsize=(11, 7))
    (ax_loss, ax_tps), (ax_mfu, ax_mem) = axes

    s, train = column(metrics, "train_loss")
    sv, val = column(metrics, "val_loss")
    ax_loss.plot(s, train, label="train")
    ax_loss.plot(sv, val, linestyle="-.", label="val")
    ax_loss.set_title("loss")
    ax_loss.legend()

    s, tps = column(metrics, "tok_s")
    ax_tps.plot(s, tps)
    ax_tps.set_title("throughput (tok/s, non-step time excluded)")

    s, mfu = column(metrics, "mfu")
    if mfu:
        ax_mfu.plot(s, [100 * m for m in mfu])
        ax_mfu.set_title("MFU (%)")
    else:
        ax_mfu.set_title("MFU n/a (unknown device peak)")

    for key, label in (("hbm_bytes_in_use", "HBM in use"),
                       ("hbm_peak_bytes", "HBM peak"),
                       ("host_rss_bytes", "host RSS")):
        s, mem = column(metrics, key)
        if mem:
            ax_mem.plot(s, [m / 1024**3 for m in mem], label=label)
    ax_mem.set_title("memory (GiB)")
    ax_mem.legend()

    for ax in axes.flat:
        ax.set_xlabel("step")
    fig.tight_layout()
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    fig.savefig(out_path)
    plt.close(fig)
    print(f"figure written to {out_path}")
    return out_path


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("jsonl", help="metrics JSONL written by --metrics_jsonl")
    p.add_argument("--out", default=None,
                   help="figure path (default: <jsonl dir>/metrics.png)")
    args = p.parse_args(argv)
    header, metrics, events = load_rows(args.jsonl)
    summarize(header, metrics, events)
    if metrics:
        out = args.out or os.path.join(
            os.path.dirname(os.path.abspath(args.jsonl)), "metrics.png")
        plot(metrics, out)


if __name__ == "__main__":
    main()
