"""Render a --metrics_jsonl telemetry file: loss/throughput/MFU/memory
curves + a text summary. Replaces the old single-purpose loss plot
(utils/plotting.py) as the post-hoc view of a run — the JSONL is the
artifact, this is just one renderer over it.

  python scripts/summarize_metrics.py out/metrics.jsonl [--out out/metrics.png]

Prints the run header, per-event-kind counts, final/peak numbers, the
per-layer-group grad-norm trajectory (``health`` rows), the compile
telemetry (compile seconds, HLO FLOPs, HLO-vs-analytic MFU delta,
recompiles), the serving section (per-request latency percentiles, the
engine tick-phase breakdown + SLO burn, slot occupancy, queue depth —
``--mode serve`` runs) and the HBM budget breakdown to stdout;
``--trace out.json`` additionally exports the run as Perfetto-loadable
Chrome trace JSON (obs/trace.py); writes a 2x2 figure
(train/val loss, tok/s, MFU, memory) when matplotlib is available (text
summary still works without it).
"""

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the canonical schema registry (obs/schema.py): phase tables + event
# groups — no pinned fallback copy (the private-copy pattern is exactly
# the drift hazard graft-lint GL044 forbids). Loaded by FILE PATH so the
# renderer stays dependency-free: a package import of obs.schema would
# run obs/__init__ and hard-require jax, the exact breakage the old
# fallback existed to absorb.
from building_llm_from_scratch_tpu.analysis.base import load_schema_module

SCHEMA = load_schema_module()


def load_segments(path):
    """Parse one JSONL into per-run segments, split on ``header`` rows.

    Fleet worker files hold one header per incarnation (a restarted
    worker APPENDS to its file — serving/worker.py), so "one file = one
    run" is no longer true; a consumer that merges blindly attributes a
    whole restart history to one run and silently drops all but one
    header. Returns ``[(header, metrics, events, health), ...]``, one
    tuple per incarnation in file order (a headerless prefix becomes a
    synthetic first segment with ``header=None``).
    """
    segments = []
    current = None
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"warning: line {i + 1} unparseable ({e}); skipped",
                      file=sys.stderr)
                continue
            kind = row.get("type")
            if kind == "header":
                current = (row, [], [], [])
                segments.append(current)
                continue
            if current is None:
                current = (None, [], [], [])
                segments.append(current)
            if kind == "metrics":
                current[1].append(row)
            elif kind == "event":
                current[2].append(row)
            elif kind == "health":
                current[3].append(row)
    return segments


def load_rows(path):
    """(header, metrics, events, health) with every segment merged —
    the whole-file view. The header is the FIRST one (a worker file's
    later headers label incarnation segments, not the file)."""
    segs = load_segments(path)
    header = next((h for h, _m, _e, _h in segs if h is not None), None)
    metrics = [r for s in segs for r in s[1]]
    events = [r for s in segs for r in s[2]]
    health = [r for s in segs for r in s[3]]
    return header, metrics, events, health


def segment_label(header, ordinal):
    """Stable label for one incarnation segment: fleet worker headers
    carry replica/incarnation identity; anything else is run<N>."""
    if header and header.get("replica") is not None:
        return (f"replica{header['replica']}"
                f".inc{header.get('incarnation', ordinal)}")
    return f"run{ordinal}"


def column(rows, key):
    """(steps, values) for rows where ``key`` is a number."""
    pairs = [(r["step"], r[key]) for r in rows
             if isinstance(r.get(key), (int, float))]
    return [p[0] for p in pairs], [p[1] for p in pairs]


def summarize(header, metrics, events):
    if header:
        mesh = header.get("mesh_shape")
        model = (header.get("model") or {}).get("name", "?")
        print(f"run: model={model} jax={header.get('jax_version')} "
              f"devices={header.get('device_count')}x"
              f"{header.get('device_kind')} mesh={mesh}")
    print(f"{len(metrics)} metric rows, {len(events)} events")
    by_kind = {}
    for e in events:
        by_kind[e["event"]] = by_kind.get(e["event"], 0) + 1
    if by_kind:
        print("events:", ", ".join(f"{k} x{v}"
                                   for k, v in sorted(by_kind.items())))
    if not metrics:
        return
    last = metrics[-1]
    steps, tok_s = column(metrics, "tok_s")
    _, train = column(metrics, "train_loss")
    _, mfu = column(metrics, "mfu")
    _, hbm = column(metrics, "hbm_peak_bytes")
    print(f"final: step={last.get('step')} "
          f"tokens_seen={last.get('tokens_seen')} "
          f"train_loss={train[-1] if train else 'n/a'}")
    if tok_s:
        print(f"throughput: last={tok_s[-1]:.0f} tok/s "
              f"peak={max(tok_s):.0f} mean={sum(tok_s) / len(tok_s):.0f}")
    if mfu:
        print(f"mfu: last={100 * mfu[-1]:.1f}% peak={100 * max(mfu):.1f}%")
    else:
        print("mfu: n/a (no TPU peak-FLOPs entry for this device kind)")
    if hbm:
        print(f"peak HBM: {max(hbm) / 1024**3:.2f} GiB")
    ckpt = [e for e in events if e["event"] == "checkpoint_save"
            and isinstance(e.get("seconds"), (int, float))]
    if ckpt:
        secs = [e["seconds"] for e in ckpt]
        print(f"checkpoints: {len(ckpt)} saves, "
              f"mean {sum(secs) / len(secs):.2f}s, max {max(secs):.2f}s")
    summarize_overlap(metrics, events)


def summarize_overlap(metrics, events):
    """Host-overlap section: data_wait share of step time, prefetch
    stalls/fill (an underpowered host shows up HERE once prefetching makes
    data_wait itself near-zero), and async-checkpoint overlap seconds."""
    _, waits = column(metrics, "data_wait_s")
    steps_w = [r.get("steps_in_window") for r in metrics
               if isinstance(r.get("data_wait_s"), (int, float))]
    if waits:
        n_steps = sum(s for s in steps_w if isinstance(s, (int, float)))
        per_step = sum(waits) / max(n_steps, 1)
        print(f"data_wait: {1e3 * per_step:.2f} ms/step "
              f"({sum(waits):.2f}s total)")
    stalls = [r["prefetch_stall"] for r in metrics
              if isinstance(r.get("prefetch_stall"), (int, float))]
    if stalls:
        _, fills = column(metrics, "prefetch_fill_ratio")
        total = int(sum(stalls))
        fill_txt = (f", mean fill {sum(fills) / len(fills):.2f}"
                    if fills else "")
        print(f"prefetch: {total} stalls{fill_txt}"
              + ("" if total == 0 else
                 " — the HOST is the bottleneck (queue empty at pop): "
                 "raise --prefetch depth, or speed up the data pipeline"))
    async_saves = [e for e in events if e["event"] == "ckpt_async_save"
                   and isinstance(e.get("overlap_s"), (int, float))]
    if async_saves:
        ov = [e["overlap_s"] for e in async_saves]
        snap = [e.get("snapshot_s", 0) for e in async_saves]
        print(f"async checkpoints: {len(async_saves)} saves, "
              f"{sum(ov):.2f}s of write overlapped training "
              f"(step loop paid only {sum(snap):.2f}s of snapshots)")


def _fmt_bytes(n):
    return f"{n / 1024**2:.1f} MiB" if n < 1024**3 else f"{n / 1024**3:.2f} GiB"


def _pctile(values, p):
    """Nearest-rank percentile (no numpy dependency for the renderer)."""
    vals = sorted(values)
    if not vals:
        return None
    k = max(0, min(len(vals) - 1, round(p / 100 * (len(vals) - 1))))
    return vals[k]


def summarize_serving(metrics, events):
    """Serving section: per-request latency percentiles (queue wait, TTFT,
    TPOT, end-to-end) from ``request_done`` events, finish-reason and
    rejection counts, slot occupancy and queue depth from the engine's
    metric rows, and the decode token rate."""
    done = [e for e in events if e["event"] == "request_done"]
    rejected = [e for e in events if e["event"] == "request_rejected"]
    failed = [e for e in events if e["event"] == "request_failed"]
    shed = [e for e in events if e["event"] == "request_shed"]
    expired = [e for e in events if e["event"] == "request_expired"]
    # incident runs can restart/drain/die before ANY request completes —
    # those are exactly the files this section must explain, so lifecycle
    # events open the section too, not just request-level ones
    lifecycle = [e for e in events
                 if e["event"] in SCHEMA.SERVING_LIFECYCLE_EVENTS]
    if not (done or rejected or failed or shed or expired or lifecycle):
        return
    print("\n-- serving --")
    reasons = {}
    for e in done:
        reasons[e.get("finish_reason")] = reasons.get(
            e.get("finish_reason"), 0) + 1
    total_tok = sum(e.get("n_tokens", 0) for e in done)
    print(f"  {len(done)} requests done ({total_tok} tokens; "
          + ", ".join(f"{k} x{v}" for k, v in sorted(reasons.items()))
          + (f"; {len(rejected)} REJECTED over capacity" if rejected
             else "") + ")")
    summarize_serving_resilience(failed, shed, expired, events)
    summarize_serving_fleet(done, metrics, events)
    summarize_worker_lifecycle(events)
    summarize_fleet_observability(events)
    summarize_adapters(done, failed, events)
    summarize_prefix_kv(metrics, events)
    summarize_spec(done, metrics, events)
    summarize_longctx(done, metrics, events)
    for key, label in (("queue_wait_s", "queue wait"), ("ttft_s", "TTFT"),
                       ("tpot_s", "TPOT"), ("e2e_s", "end-to-end")):
        vals = [e[key] for e in done
                if isinstance(e.get(key), (int, float))]
        if vals:
            print(f"  {label:<12} p50 {1e3 * _pctile(vals, 50):8.2f} ms   "
                  f"p95 {1e3 * _pctile(vals, 95):8.2f} ms   "
                  f"p99 {1e3 * _pctile(vals, 99):8.2f} ms")
    summarize_ticks(metrics, events)
    occ = [r["slot_occupancy"] for r in metrics
           if isinstance(r.get("slot_occupancy"), (int, float))]
    if occ:
        print(f"  slot occupancy: mean {sum(occ) / len(occ):.2f}, "
              f"min {min(occ):.2f} (idle slots = unused compute — "
              "lower --serve_slots or raise offered load)")
    depth = [r["queue_depth"] for r in metrics
             if isinstance(r.get("queue_depth"), (int, float))]
    if depth:
        print(f"  queue depth: peak {int(max(depth))}")
    _, rate = column(metrics, "serve_tok_s")
    if rate:
        print(f"  decode rate: last {rate[-1]:.0f} tok/s, "
              f"peak {max(rate):.0f} tok/s")
    summaries = [e for e in events if e["event"] == "serve_summary"]
    if summaries and summaries[-1].get("n_recompiles"):
        print(f"  !! {summaries[-1]['n_recompiles']} RECOMPILES after "
              "warmup — prompt lengths outside the warmed bucket set "
              "(see the recompile events' leaf diffs)")


def summarize_serving_fleet(done, metrics, events):
    """Scale-out serving fleet section (serving/router.py): replica
    count, per-replica request/token split, routing counters (affinity
    ratio from the replica-attributed ``request_done`` rows), replica
    drains with their re-dispatched queued work, and restarts."""
    fleet = [e for e in events if e["event"] == "serve_fleet"]
    drains = [e for e in events if e["event"] == "replica_drain"]
    restarts = [e for e in events if e["event"] == "replica_restart"]
    redis = [e for e in events if e["event"] == "router_redispatch"]
    with_replica = [e for e in done if e.get("replica") is not None]
    if not (fleet or drains or redis or restarts or with_replica):
        return
    print("  -- scale-out serving fleet --")
    build = next((e for e in fleet if e.get("phase") == "build"), None)
    if build:
        print(f"    {build.get('n_replicas')} replica(s) x "
              f"tp={build.get('tp')} "
              f"({'disjoint' if build.get('disjoint_devices') else 'SHARED'}"
              f" device slices), {build.get('n_adapters', 0)} adapter(s)")
    per = {}
    for e in with_replica:
        c = per.setdefault(e["replica"], {"done": 0, "tokens": 0})
        c["done"] += 1
        c["tokens"] += e.get("n_tokens", 0)
    for rep in sorted(per):
        c = per[rep]
        print(f"    replica {rep}: {c['done']} done, "
              f"{c['tokens']} tokens")
    if drains:
        moved = sum(e.get("n_redispatched") or 0 for e in drains
                    if e.get("phase") == "end")
        preempted = sum(e.get("n_preempted") or 0 for e in drains
                        if e.get("phase") == "end")
        which = sorted({e.get("replica") for e in drains})
        print(f"    replica drains: {which} — {moved} queued "
              f"re-dispatched ({len(redis)} redispatch events), "
              f"{preempted} preempted")
    if restarts:
        print(f"    replica restarts: "
              f"{sorted({e.get('replica') for e in restarts})}")


def summarize_worker_lifecycle(events):
    """Cross-process fleet section (serving/fleet.py): worker-process
    spawn/death/restart timeline (relative seconds from the first spawn),
    death reasons with how much queued work was re-dispatched vs failed
    in-flight, missed-heartbeat detections, and prefix-pane handoffs with
    their byte volume."""
    kinds = ("worker_spawn", "worker_heartbeat_missed", "worker_dead",
             "worker_restart", "pane_handoff")
    rows = [e for e in events if e["event"] in kinds]
    if not rows:
        return
    print("  -- cross-process fleet workers --")
    spawns = [e for e in rows if e["event"] == "worker_spawn"]
    deaths = [e for e in rows if e["event"] == "worker_dead"]
    restarts = [e for e in rows if e["event"] == "worker_restart"]
    missed = [e for e in rows if e["event"] == "worker_heartbeat_missed"]
    handoffs = [e for e in rows if e["event"] == "pane_handoff"]
    replicas = sorted({e.get("replica") for e in spawns})
    print(f"    {len(spawns)} worker spawn(s) across replicas {replicas}"
          f" — {len(deaths)} death(s), {len(restarts)} restart(s)"
          + (f", {len(missed)} missed-heartbeat detection(s)"
             if missed else ""))
    t0 = min((e.get("time", 0.0) for e in rows), default=0.0)
    for e in rows:                       # rows keep file (= time) order
        t = e.get("time", 0.0) - t0
        if e["event"] == "worker_spawn":
            print(f"    t+{t:7.2f}s  replica {e.get('replica')} spawned "
                  f"pid {e.get('pid')}"
                  + (f" (restart #{e.get('restarts')})"
                     if e.get("restarts") else ""))
        elif e["event"] == "worker_dead":
            print(f"    t+{t:7.2f}s  replica {e.get('replica')} DIED "
                  f"({e.get('reason')}): "
                  f"{e.get('queued_redispatched', 0)} queued re-dispatched"
                  f", {e.get('inflight_failed', 0)} in-flight failed typed")
        elif e["event"] == "worker_restart":
            down = e.get("downtime_s")
            print(f"    t+{t:7.2f}s  replica {e.get('replica')} restarted"
                  f" (#{e.get('restarts')}"
                  + (f", {down:.2f}s downtime" if down is not None else "")
                  + ")")
        elif e["event"] == "pane_handoff":
            print(f"    t+{t:7.2f}s  panes {e.get('from_replica')} -> "
                  f"{e.get('to_replica')}: {e.get('imported', 0)}/"
                  f"{e.get('entries', 0)} entries, "
                  f"{e.get('bytes', 0):,} bytes")
    if handoffs:
        total = sum(e.get("bytes") or 0 for e in handoffs)
        print(f"    pane handoff total: {len(handoffs)} transfer(s), "
              f"{total:,} bytes (adoptees serve shared prefixes "
              "without recompute)")


def _clock_table(events):
    """(replica, incarnation) -> (offset_s, uncertainty_s, n_samples)
    from ``clock_sync`` events — the lowest-uncertainty sample wins per
    worker incarnation (serving/fleet.py emits one whenever the RPC
    round-trip tightens the estimate). Subtracting ``offset_s`` from a
    worker-file timestamp lands it on the fleet's wall clock."""
    best = {}
    for e in events:
        if e.get("event") != "clock_sync":
            continue
        key = (e.get("replica"), e.get("incarnation", 0))
        unc = e.get("uncertainty_s")
        if not isinstance(unc, (int, float)):
            unc = float("inf")
        if key not in best or unc <= best[key][1]:
            best[key] = (e.get("offset_s") or 0.0, unc,
                         e.get("n_samples"))
    return best


def summarize_fleet_observability(events):
    """Fleet observatory section: per-incarnation clock offsets with
    their round-trip uncertainty bound, and any incident-ring snapshots
    the fleet wrote on worker death / restart-budget exhaustion."""
    table = _clock_table(events)
    snaps = [e for e in events if e.get("event") == "incident_snapshot"]
    if not (table or snaps):
        return
    print("  -- fleet observability --")
    for rep, inc in sorted(table, key=lambda k: (str(k[0]), str(k[1]))):
        off, unc, n = table[(rep, inc)]
        unc_txt = "inf" if unc == float("inf") else f"{1e6 * unc:.0f}"
        print(f"    clock: replica {rep} inc {inc}: offset "
              f"{1e6 * off:+.0f} us +/- {unc_txt} us"
              + (f" ({n} samples)" if n else "")
              + " (worker wall minus fleet wall)")
    for e in snaps:
        print(f"    incident snapshot ({e.get('reason')}): "
              f"{e.get('n_events', '?')} ring events -> {e.get('path')}")


def summarize_fleet_files(paths, trace=None):
    """Cross-file fleet view: one fleet JSONL plus N append-mode worker
    files (one header per incarnation each). Prints each file's
    identity, a merged worker-lifecycle incident timeline with worker
    rows shifted onto the fleet clock via the fleet file's
    ``clock_sync`` offsets, the observability table, and then the full
    single-run rendering of the fleet file itself."""
    loaded = [(p,) + load_rows(p) for p in paths]

    def _is_fleet(events):
        return any(e.get("event") in ("worker_spawn", "clock_sync")
                   for e in events)

    fleet = next((t for t in loaded if _is_fleet(t[3])), loaded[0])
    fpath = fleet[0]
    offsets = _clock_table(fleet[3])
    print(f"== fleet view: {len(paths)} file(s) ==")
    merged = []                       # (fleet-clock time, source tag, event)
    for p, _h, _m, ev, _hl in loaded:
        segs = load_segments(p)
        hdr = next((s[0] for s in segs if s[0]), None) or {}
        if p == fpath:
            merged += [(e.get("time", 0.0), "fleet", e) for e in ev]
            detail = ""
        else:
            parts = []
            for i, (sh, _sm, sev, _shl) in enumerate(segs):
                rep = (sh or {}).get("replica")
                inc = (sh or {}).get("incarnation", i)
                off = offsets.get((rep, inc), (0.0,))[0]
                tag = f"w{rep}.i{inc}"
                merged += [(e.get("time", 0.0) - off, tag, e)
                           for e in sev]
                n_done = sum(1 for e in sev
                             if e.get("event") == "request_done")
                parts.append(f"inc{inc}: {n_done} done")
            detail = f" ({len(segs)} incarnation(s): " + ", ".join(
                parts) + ")"
        role = hdr.get("role", "run")
        rep = hdr.get("replica")
        print(f"  {p}: {role}"
              + (f" replica {rep}" if rep is not None else "") + detail)
    incidents = sorted(
        (t for t in merged if t[2].get("event") in SCHEMA.INCIDENT_EVENTS),
        key=lambda t: t[0])
    if incidents:
        t0 = incidents[0][0]
        print("  -- merged incident timeline (fleet clock, "
              "skew-corrected) --")
        for t, tag, e in incidents:
            extra = e.get("reason") or e.get("phase") or ""
            print(f"    t+{t - t0:7.2f}s  [{tag:<7}] {e['event']}"
                  + (f" replica {e.get('replica')}"
                     if e.get("replica") is not None else "")
                  + (f" ({extra})" if extra else ""))
    summarize_fleet_observability(fleet[3])
    # merged memory view: one line per ledger (the router has none; each
    # worker incarnation observes its own), then the fleet device total
    mem_last = []                     # (tag, last snapshot, n snapshots)
    for p, _h, _m, ev, _hl in loaded:
        snaps = [e for e in ev if e.get("event") == "memory_snapshot"]
        if snaps:
            tag = "fleet" if p == fpath else os.path.basename(p)
            mem_last.append((tag, snaps[-1], len(snaps)))
    if mem_last:
        print("  -- merged memory (per-worker ledgers) --")
        for tag, last, n in mem_last:
            comps = last.get("components") or {}
            top = sorted(comps.items(), key=lambda kv: -kv[1])[:3]
            print(f"    {tag:<28} device "
                  f"{_fmt_bytes(last.get('device_bytes', 0)):>10} "
                  f"({n} snapshot(s): "
                  + ", ".join(f"{k} {_fmt_bytes(v)}" for k, v in top)
                  + ")")
        workers_total = sum(last.get("device_bytes", 0)
                            for tag, last, _n in mem_last
                            if tag != "fleet")
        if workers_total:
            print("    fleet device total (workers): "
                  f"{_fmt_bytes(workers_total)}")
    print(f"\n== fleet file: {fpath} ==")
    _p, header, metrics, events, health = fleet
    summarize(header, metrics, events)
    summarize_compile(metrics, events)
    summarize_fleet(metrics, events, health)
    summarize_serving(metrics, events)
    summarize_memory(metrics, events)
    summarize_health(health)
    if trace:
        # lazy: obs pulls in jax; only the trace path needs it
        from building_llm_from_scratch_tpu.obs.fleetview import (
            export_fleet_trace)
        workers = [p for p, *_ in loaded if p != fpath]
        meta = export_fleet_trace(fpath, trace, workers)
        print(f"\nfleet chrome trace written to {trace} "
              f"({meta.get('n_request_spans', 0)} fleet spans, "
              f"{meta.get('n_worker_spans', 0)} worker spans, "
              f"{meta.get('n_flow_edges', 0)} flow edges across "
              f"{meta.get('n_incarnations', 0)} incarnation(s)) — open in "
              "https://ui.perfetto.dev")


def summarize_adapters(done, failed, events):
    """Multi-tenant LoRA lines: per-adapter request/token/latency
    aggregates from the ``adapter`` field of request events, plus the
    registry's hot-load/evict history."""
    loads = [e for e in events if e["event"] == "adapter_load"]
    evicts = [e for e in events if e["event"] == "adapter_evict"]
    tenants = {}
    for e in done:
        nm = e.get("adapter", "base")
        t = tenants.setdefault(nm, {"done": 0, "tokens": 0, "failed": 0,
                                    "e2e": []})
        t["done"] += 1
        t["tokens"] += e.get("n_tokens", 0)
        if isinstance(e.get("e2e_s"), (int, float)):
            t["e2e"].append(e["e2e_s"])
    for e in failed:
        nm = e.get("adapter", "base")
        tenants.setdefault(nm, {"done": 0, "tokens": 0, "failed": 0,
                                "e2e": []})["failed"] += 1
    if not (loads or evicts or len(tenants) > 1
            or (tenants and "base" not in tenants)):
        return                   # single-tenant base-only run: stay quiet
    print(f"  adapters: {len(loads)} load(s), {len(evicts)} evict(s)"
          + ("" if not loads else " ("
             + ", ".join(f"{e.get('name')} r{e.get('rank')}"
                         for e in loads) + ")"))
    for nm in sorted(tenants):
        t = tenants[nm]
        line = (f"    {nm:<12} {t['done']:4d} done  {t['tokens']:6d} tok")
        if t["failed"]:
            line += f"  {t['failed']} failed"
        if t["e2e"]:
            line += (f"  e2e p50 {1e3 * _pctile(t['e2e'], 50):8.2f} ms  "
                     f"p95 {1e3 * _pctile(t['e2e'], 95):8.2f} ms")
        print(line)


def summarize_longctx(done, metrics, events):
    """Long-context tier section (--serve_sp): the seq-sharded prefill
    geometry from ``serve_warmup`` (sp x per-device pane = the lifted
    admission ceiling), the ``prefill_shard`` share of tick wall (what
    sequence-sharding the chunk pump actually costs per tick), and the
    long-vs-short TTFT split from the ``long_prompt``-flagged
    ``request_done`` rows — the number that says what a beyond-one-pane
    prompt pays over a short one."""
    warm = [e for e in events if e["event"] == "serve_warmup"
            and isinstance(e.get("sp"), (int, float)) and e["sp"] > 1]
    long_done = [e for e in done if e.get("long_prompt")]
    if not (warm or long_done):
        return
    print("  -- long context (seq-sharded prefill) --")
    if warm:
        w = warm[-1]
        print(f"    sp={int(w['sp'])} x {w.get('prompt_pane_tokens')} "
              f"tokens/device pane -> prompt ceiling "
              f"{w.get('max_prompt')}")
    rows = [r for r in metrics
            if isinstance(r.get("tick_prefill_shard_s"), (int, float))
            and isinstance(r.get("tick_total_s"), (int, float))]
    shard = sum(r["tick_prefill_shard_s"] for r in rows)
    total = sum(r["tick_total_s"] for r in rows)
    if total > 0 and shard > 0:
        print(f"    prefill_shard: {100 * shard / total:.1f}% of tick "
              "time (the seq-sharded chunk pump)")
    short_done = [e for e in done if not e.get("long_prompt")]
    for label, grp in (("long (> pane)", long_done),
                       ("short", short_done)):
        ttfts = [e["ttft_s"] for e in grp
                 if isinstance(e.get("ttft_s"), (int, float))]
        if ttfts:
            print(f"    {label:<14} {len(grp):3d} req   TTFT p50 "
                  f"{1e3 * _pctile(ttfts, 50):8.2f} ms   p95 "
                  f"{1e3 * _pctile(ttfts, 95):8.2f} ms")


def summarize_spec(done, metrics, events):
    """Speculative-decoding section (serving/spec.py): the drafter
    config from ``serve_warmup``, the fleet-wide acceptance ratio
    (accepted/drafted — the drafter-quality dial: low ratio means the
    k-wide verify positions are wasted compute, so shrink k or opt the
    workload out), drafted-vs-accepted per cadence window, and the
    per-request acceptance spread + TPOT next to it (TPOT is the
    latency speculation attacks — compare a spec-off run of the same
    workload for the delta)."""
    warm = [e for e in events if e["event"] == "serve_warmup"]
    spec_k = (warm[-1].get("spec_k") if warm else None) or 0
    drafted = sum(e.get("spec_drafted", 0) for e in done)
    if not spec_k and not drafted:
        return
    print("  -- speculative decoding --")
    if warm and spec_k:
        print(f"  config: k={spec_k}, drafter="
              f"{warm[-1].get('drafter', '?')}")
    accepted = sum(e.get("spec_accepted", 0) for e in done)
    if drafted:
        print(f"  acceptance: {accepted}/{drafted} drafted tokens "
              f"accepted ({100 * accepted / drafted:.0f}%) across "
              f"{sum(1 for e in done if e.get('spec_drafted'))} "
              "request(s)")
        ratios = [e["spec_accepted"] / e["spec_drafted"] for e in done
                  if e.get("spec_drafted")]
        if ratios:
            print(f"  per-request acceptance: p50 "
                  f"{100 * _pctile(ratios, 50):.0f}%  p95 "
                  f"{100 * _pctile(ratios, 95):.0f}%  min "
                  f"{100 * min(ratios):.0f}% (persistently-low tenants "
                  "are 'spec': false candidates)")
        tpots = [e["tpot_s"] for e in done
                 if e.get("spec_drafted")
                 and isinstance(e.get("tpot_s"), (int, float))]
        if tpots:
            print(f"  TPOT under speculation: p50 "
                  f"{1e3 * _pctile(tpots, 50):.2f} ms (A/B a spec-off "
                  "run — bench.py serve_spec — for the delta)")
    rows = [r for r in metrics if r.get("spec_drafted")]
    if rows:
        worst = sorted(rows, key=lambda r: r.get("spec_accepted", 0)
                       / max(r.get("spec_drafted", 1), 1))[:3]
        print(f"  windows: {len(rows)} cadence window(s) drafted; "
              "lowest-acceptance windows: "
              + ", ".join(
                  f"step {r.get('step', '?')} "
                  f"{100 * r.get('spec_accepted', 0) / max(r.get('spec_drafted', 1), 1):.0f}%"
                  for r in worst))


def summarize_prefix_kv(metrics, events):
    """KV memory-engine section (serving/kvcache.py): prefix-cache hit
    ratio and bytes of prefill compute saved, the KV quant/chunk policy
    from ``serve_warmup``, store churn (inserts/evictions), and the
    chunk-stall table — the per-window prefill share of tick time that
    chunked prefill exists to bound."""
    hits = [e for e in events if e["event"] == "prefix_hit"]
    misses = [e for e in events if e["event"] == "prefix_miss"]
    evicts = [e for e in events if e["event"] == "prefix_evict"]
    inserts = [e for e in events if e["event"] == "prefix_insert"]
    warm = [e for e in events if e["event"] == "serve_warmup"]
    policy = warm[-1] if warm else {}
    chunked = bool(policy.get("prefill_chunk"))
    if not (hits or misses or evicts or chunked
            or policy.get("kv_quant", "model") != "model"):
        return
    print("  -- KV memory engine --")
    print("  policy: kv_quant=" + str(policy.get("kv_quant", "model"))
          + f", prefill_chunk={policy.get('prefill_chunk', 0)}"
          + f", prefix_cache={policy.get('prefix_cache', False)}"
          + (f", {policy.get('kv_bytes_per_slot', 0) / 1024 ** 2:.2f} "
             "MiB KV/slot" if policy.get("kv_bytes_per_slot") else ""))
    n_lookups = len(hits) + len(misses)
    if n_lookups:
        spans = [e.get("span_tokens", 0) for e in hits]
        bps = policy.get("kv_bytes_per_slot")
        max_len = policy.get("max_len")
        saved = ""
        if bps and max_len and spans:
            # bytes of slot KV the hits filled by COPY instead of
            # forward compute — the prefill work the cache deleted
            saved_bytes = sum(spans) * (bps / max_len)
            saved = f", ~{_fmt_bytes(int(saved_bytes))} of prefill KV " \
                    "filled by copy"
        print(f"  prefix cache: {len(hits)}/{n_lookups} lookups hit "
              f"({100 * len(hits) / n_lookups:.0f}%), "
              f"{sum(spans)} cached-span tokens skipped prefill{saved}")
        print(f"  store churn: {len(inserts)} insert(s), "
              f"{len(evicts)} eviction(s)"
              + (f" ({_fmt_bytes(sum(e.get('bytes', 0) for e in evicts))}"
                 " evicted)" if evicts else ""))
    # chunk-stall table: windows where prefill dominated the tick —
    # under chunking each entry is bounded by ~one chunk's wall
    rows = [r for r in metrics
            if isinstance(r.get("tick_prefill_s"), (int, float))
            and isinstance(r.get("ticks_in_window"), (int, float))
            and r["ticks_in_window"] > 0 and r.get("tick_prefill_s", 0) > 0]
    if rows and chunked:
        worst = sorted(rows, reverse=True,
                       key=lambda r: r["tick_prefill_s"]
                       / r["ticks_in_window"])[:5]
        n_chunks = sum(r.get("prefill_chunks", 0) for r in rows)
        print(f"  chunked prefill: {n_chunks} chunk(s) over "
              f"{len(rows)} window(s); worst prefill-stall windows "
              "(s/tick):")
        for r in worst:
            val = r["tick_prefill_s"] / r["ticks_in_window"]
            share = (100 * r["tick_prefill_s"] / r["tick_total_s"]
                     if r.get("tick_total_s") else 0.0)
            print(f"    step {r.get('step', '?'):>8}  "
                  f"{1e3 * val:8.3f} ms/tick  "
                  f"({share:.0f}% of tick wall, "
                  f"{r.get('prefill_chunks', 0)} chunks)")


def summarize_memory(metrics, events):
    """Memory observatory section (obs/memory.py): the ledger's
    composition table (per-component resident bytes + run high
    watermark) per source (engine/trainer), attribution peaks from the
    labeled series (per-tenant live KV, per-namespace prefix bytes,
    per-tenant adapter rows), request-level KV peaks and prefix
    savings, and every drift/pressure incident the detectors fired."""
    snaps = [e for e in events if e["event"] == "memory_snapshot"]
    drift = [e for e in events if e["event"] == "memory_drift"]
    pressure = [e for e in events if e["event"] == "memory_pressure"]
    if not (snaps or drift or pressure):
        return
    print("\n-- memory --")
    by_src = {}
    for e in snaps:
        by_src.setdefault(e.get("source", "?"), []).append(e)
    for src, rows in sorted(by_src.items()):
        last = rows[-1]
        comps = last.get("components") or {}
        peaks = {}
        for r in rows:
            for name, size in (r.get("components") or {}).items():
                if size > peaks.get(name, -1):
                    peaks[name] = size
        line = (f"  {src}: {len(rows)} snapshot(s), "
                f"last total {_fmt_bytes(last.get('total_bytes', 0))}")
        if isinstance(last.get("headroom_bytes"), (int, float)):
            line += (f", headroom {_fmt_bytes(last['headroom_bytes'])}"
                     f" of {_fmt_bytes(last.get('capacity_bytes', 0))}")
        print(line)
        for name in sorted(comps, key=lambda n: -comps[n]):
            print(f"    {name:<16} {_fmt_bytes(comps[name]):>12}"
                  f"   peak {_fmt_bytes(peaks.get(name, comps[name]))}")
        # attribution: per-label high watermark over the whole run
        lab_peaks = {}
        for r in rows:
            for series, sizes in (r.get("labeled") or {}).items():
                d = lab_peaks.setdefault(series, {})
                for key, size in sizes.items():
                    if size > d.get(key, -1):
                        d[key] = size
        for series, d in sorted(lab_peaks.items()):
            top = sorted(d.items(), key=lambda kv: -kv[1])[:6]
            print(f"    {series} peak: "
                  + ", ".join(f"{k}={_fmt_bytes(v)}" for k, v in top)
                  + (f" (+{len(d) - len(top)} more)"
                     if len(d) > len(top) else ""))
    done = [e for e in events if e["event"] == "request_done"]
    kv_peaks = [e["kv_bytes_peak"] for e in done
                if isinstance(e.get("kv_bytes_peak"), (int, float))]
    saved = sum(e.get("prefix_bytes_saved", 0) for e in done
                if isinstance(e.get("prefix_bytes_saved"), (int, float)))
    if kv_peaks:
        print(f"  request KV: peak {_fmt_bytes(max(kv_peaks))}/req, "
              f"p95 {_fmt_bytes(_pctile(kv_peaks, 95))}"
              + (f"; {_fmt_bytes(saved)} of prefill KV saved by prefix "
                 "hits" if saved else ""))
    for e in drift:
        extra = ""
        if isinstance(e.get("delta_bytes"), (int, float)):
            extra = f", delta {_fmt_bytes(abs(e['delta_bytes']))}"
        elif isinstance(e.get("pinned_bytes"), (int, float)):
            extra = f", {_fmt_bytes(e['pinned_bytes'])} pinned"
        print(f"  !! memory_drift [{e.get('component')}] "
              f"{e.get('reason')}{extra} — the ledger disagrees with "
              "the live arrays; suspect a leak in this component")
    for e in pressure:
        print(f"  !! memory_pressure at "
              f"{100 * e.get('used_frac', 0):.1f}% of "
              f"{_fmt_bytes(e.get('capacity_bytes', 0))} "
              f"(headroom {_fmt_bytes(e.get('headroom_bytes', 0))}) — "
              "full breakdown rides the event")


def summarize_ticks(metrics, events):
    """Tick-breakdown + SLO-burn section: per-tick p50/p95 for each engine
    phase (admit/prefill/decode_dispatch/host_fetch/sample_commit/
    callback_detok), the prefill-vs-decode share of tick time (prefill
    head-of-line blocking shows up HERE as a fat prefill share), and the
    run's deadline-miss ratio."""
    rows = [r for r in metrics
            if isinstance(r.get("tick_total_s"), (int, float))
            and isinstance(r.get("ticks_in_window"), (int, float))
            and r["ticks_in_window"] > 0]
    if rows:
        print("  tick breakdown (per-tick, over "
              f"{int(sum(r['ticks_in_window'] for r in rows))} ticks):")
        sums = {}
        for ph in SCHEMA.TICK_PHASES:
            per_tick = [r[f"tick_{ph}_s"] / r["ticks_in_window"]
                        for r in rows
                        if isinstance(r.get(f"tick_{ph}_s"), (int, float))]
            sums[ph] = sum(r.get(f"tick_{ph}_s", 0) for r in rows
                           if isinstance(r.get(f"tick_{ph}_s"),
                                         (int, float)))
            if per_tick:
                print(f"    {ph:<16} p50 {1e3 * _pctile(per_tick, 50):8.3f}"
                      f" ms   p95 {1e3 * _pctile(per_tick, 95):8.3f} ms")
        total = sum(r["tick_total_s"] for r in rows)
        if total > 0:
            # prefill_shard is the seq-sharded chunk pump (--serve_sp):
            # same head-of-line economics, booked under its own phase
            pf = sums.get("prefill", 0) + sums.get("prefill_shard", 0)
            dec = sums.get("decode_dispatch", 0)
            line = (f"    prefill {100 * pf / total:.1f}% vs decode "
                    f"{100 * dec / total:.1f}% of tick time")
            if pf > dec:
                line += (" — PREFILL-DOMINATED: long prompts are blocking "
                         "decode ticks (head-of-line); consider chunked "
                         "prefill / smaller prompt buckets")
            print(line)
    # deadline-miss (SLO burn) over the whole run, from request events:
    # done-with-deadline (miss when e2e blew it) + expired + shed
    done = [e for e in events if e["event"] == "request_done"
            and isinstance(e.get("deadline_s"), (int, float))]
    late = [e for e in done
            if isinstance(e.get("e2e_s"), (int, float))
            and e["e2e_s"] > e["deadline_s"]]
    shed = [e for e in events if e["event"] == "request_shed"]
    expired = [e for e in events if e["event"] == "request_expired"]
    n_slo = len(done) + len(shed) + len(expired)
    if n_slo:
        misses = len(late) + len(shed) + len(expired)
        print(f"  SLO burn: {misses}/{n_slo} deadline-carrying requests "
              f"missed ({100 * misses / n_slo:.1f}%: {len(late)} finished "
              f"late, {len(shed)} shed, {len(expired)} expired)")


def summarize_serving_resilience(failed, shed, expired, events):
    """Resilience telemetry: per-reason request failures (fault isolation
    — a poison request fails ALONE), SLO sheds + queue TTL expiries
    (deadline-aware admission), supervisor restarts, and drain summaries.
    """
    if failed:
        by_reason = {}
        for e in failed:
            by_reason[e.get("reason")] = by_reason.get(
                e.get("reason"), 0) + 1
        print(f"  {len(failed)} requests FAILED: "
              + ", ".join(f"{k} x{v}"
                          for k, v in sorted(by_reason.items())))
    if shed or expired:
        parts = []
        if shed:
            ests = [e["estimated_e2e_s"] for e in shed
                    if isinstance(e.get("estimated_e2e_s"), (int, float))]
            parts.append(f"{len(shed)} shed at submit (SLO)"
                         + (f", est e2e up to {max(ests):.2f}s"
                            if ests else ""))
        if expired:
            waits = [e["queue_wait_s"] for e in expired
                     if isinstance(e.get("queue_wait_s"), (int, float))]
            parts.append(f"{len(expired)} expired in queue (TTL)"
                         + (f", waited up to {max(waits):.2f}s"
                            if waits else ""))
        print("  deadline admission: " + "; ".join(parts)
              + " — clients got fast 429/504s instead of stale results")
    restarts = [e for e in events if e["event"] == "engine_restart"]
    if restarts:
        last = restarts[-1]
        print(f"  !! {len(restarts)} ENGINE RESTART(S) "
              f"(last: {last.get('reason')}, "
              f"{last.get('n_inflight_failed', 0)} in-flight failed, "
              f"restart {last.get('n_restart')}/"
              f"{last.get('max_restarts')}) — see the stall events' "
              "flight records (thread stacks + device memory)")
    drains = [e for e in events if e["event"] == "drain"
              and e.get("phase") == "end"]
    if drains:
        d = drains[-1]
        print(f"  drain: completed in {d.get('seconds')}s, "
              f"{d.get('n_preempted', 0)} preempted "
              f"({d.get('requests_finished', '?')} requests finished "
              "before stop)")
    errors = [e for e in events if e["event"] == "serve_error"]
    if errors:
        print(f"  !! ENGINE DIED: {errors[-1].get('error')} "
              f"({errors[-1].get('n_failed', 0)} requests failed)")


def summarize_compile(metrics, events):
    """Compile-telemetry section: per-compile cost, HBM budget breakdown,
    HLO-vs-analytic MFU delta, and any recompiles (with their shape diff)."""
    compiles = [e for e in events if e["event"] == "compile"]
    recompiles = [e for e in events if e["event"] == "recompile"]
    if not (compiles or recompiles):
        return
    print("\n-- compile telemetry --")
    for e in compiles:
        flops = e.get("flops")
        parts = [f"{e.get('label', '?')}: "
                 f"{e.get('compile_seconds', 0):.2f}s compile"]
        if isinstance(flops, (int, float)):
            parts.append(f"{flops:.3g} HLO flops/step")
        if isinstance(e.get("tokens_per_step"), (int, float)) and flops:
            parts.append(f"{flops / e['tokens_per_step']:.3g} flops/token")
        if "cache_hit" in e:
            parts.append("cache HIT" if e["cache_hit"] else "cache miss")
        print("  " + ", ".join(parts))
        mem = e.get("memory")
        if isinstance(mem, dict) and mem:
            hbm = "\n".join(
                f"    {k:<22} {_fmt_bytes(v)}" for k, v in mem.items()
                if isinstance(v, (int, float)))
            print("  HBM budget:\n" + hbm)
            cap = e.get("hbm_capacity_bytes")
            if isinstance(cap, (int, float)) and cap:
                print(f"    {'device capacity':<22} {_fmt_bytes(cap)} "
                      f"({100 * e.get('hbm_budget_frac', 0):.1f}% used)")
    deltas = [r["mfu_delta"] for r in metrics
              if isinstance(r.get("mfu_delta"), (int, float))]
    if deltas:
        print(f"  HLO-vs-analytic MFU delta: last {deltas[-1]:+.4f}, "
              f"max |{max(abs(d) for d in deltas):.4f}| "
              "(HLO counts what XLA built; a drifting delta means the "
              "analytic formula no longer matches the graph)")
    if recompiles:
        print(f"  RECOMPILES: {len(recompiles)} — every one stalls the "
              "step loop for a full XLA compile")
        for e in recompiles:
            for d in e.get("diff", [])[:4]:
                print(f"    {d.get('leaf')}: {d.get('was')} -> {d.get('now')}")


def summarize_fleet(metrics, events, health):
    """Fused multi-LoRA finetuning section (training/lora_fusion.py):
    per-job loss trajectory, job completion/failure summary, the
    adapter-export timeline (each tenant's deployment unblocks at ITS
    job's finish, not run end), and the fused-step FLOPs split — how much
    of each step is the shared frozen base vs the per-job adapters."""
    fleet_ev = [e for e in events if e["event"] == "finetune_fleet"]
    starts = [e for e in events if e["event"] == "finetune_job_start"]
    dones = [e for e in events if e["event"] == "finetune_job_done"]
    fails = [e for e in events if e["event"] == "finetune_job_failed"]
    saves = [e for e in events
             if e["event"] == "adapter_save" and e.get("job_id")]
    if not (fleet_ev or starts or dones or fails):
        return
    print("\n-- fused multi-LoRA finetuning --")
    start_ev = next((e for e in fleet_ev if e.get("phase") == "start"),
                    None)
    end_ev = next((e for e in fleet_ev if e.get("phase") == "end"), None)
    if start_ev:
        print(f"  fleet: {start_ev.get('n_jobs', '?')} job(s) on "
              f"{start_ev.get('capacity', '?')} slot(s), rank "
              f"{start_ev.get('rank', '?')}, "
              f"{start_ev.get('rows_per_job', '?')} rows/job/step")
    if end_ev:
        print(f"  outcome: {end_ev.get('jobs_done', 0)} done, "
              f"{end_ev.get('jobs_failed', 0)} failed in "
              f"{end_ev.get('seconds', 0):.1f}s")
    fleet_rows = [m for m in metrics if m.get("fleet")]
    if fleet_rows:
        steps, tok = column(fleet_rows, "tok_s")
        if tok:
            print(f"  throughput: {sum(tok) / len(tok):,.0f} tok/s mean "
                  f"over {len(tok)} cadence window(s)")
    # per-job loss trajectory from the fleet's health rows (groups =
    # slot/job names; a job's column tracks it while it occupies a slot)
    loss_rows = [h for h in health
                 if h.get("fleet") and isinstance(h.get("loss"), list)
                 and isinstance(h.get("groups"), list)
                 and len(h["loss"]) == len(h["groups"])]
    by_job = {}
    free_slot = re.compile(r"slot\d+")   # the engine's free-slot
    # placeholder (job names matching it are refused at add_job)
    for h in loss_rows:
        for name, loss in zip(h["groups"], h["loss"]):
            if free_slot.fullmatch(name):
                continue
            if isinstance(loss, (int, float)):
                by_job.setdefault(name, []).append((h["step"], loss))
    if by_job:
        print("  per-job loss (first -> last):")
        for name in sorted(by_job):
            tr = by_job[name]
            print(f"    {name:<14} {tr[0][1]:8.4f} -> {tr[-1][1]:8.4f} "
                  f"over steps {tr[0][0]}..{tr[-1][0]}")
    # export timeline: when each tenant's artifact became deployable,
    # relative to the fleet start (slow jobs must not gate fast ones)
    t0 = start_ev.get("time") if start_ev else (
        saves[0].get("time") if saves else None)
    if saves and t0:
        print("  adapter exports (deployment-ready):")
        for e in sorted(saves, key=lambda e: e.get("time", 0)):
            done_ev = next((d for d in dones
                            if d.get("job_id") == e.get("job_id")), {})
            dep = ", hot-deployed" if done_ev.get("deployed") else ""
            print(f"    +{e.get('time', 0) - t0:7.2f}s  "
                  f"{e.get('job_id', '?'):<14} {e.get('path', '')}{dep}")
    for e in fails:
        print(f"  !! job {e.get('job_id')} retired at step "
              f"{e.get('steps', '?')}: {e.get('reason')} "
              f"(loss={e.get('loss')}, grad_norm={e.get('grad_norm')}) "
              "— co-trained jobs unaffected")
    # FLOPs split: analytic base-vs-adapter share + the fused step's
    # HLO-counted total (compile event label fused_step)
    if start_ev and isinstance(start_ev.get("flops_per_token_base"),
                               (int, float)):
        base = start_ev["flops_per_token_base"]
        adp = start_ev.get("flops_per_token_adapter", 0) or 0
        share = adp / (base + adp) if base + adp else 0.0
        line = (f"  fused-step FLOPs/token (analytic): base "
                f"{base:.3g} + adapters {adp:.3g} "
                f"({100 * share:.1f}% adapter share)")
        comp = next((e for e in events if e["event"] == "compile"
                     and e.get("label") == "fused_step"
                     and isinstance(e.get("flops"), (int, float))), None)
        if comp:
            line += f"; HLO {comp['flops']:.3g} flops/step"
        print(line)


def summarize_health(health, top_k: int = 6):
    """Per-layer-group grad-norm trajectory table: one row per health
    cadence, one column per group (widest-swinging ``top_k`` groups when
    there are too many to print)."""
    rows = [h for h in health
            if isinstance(h.get("groups"), list)
            and isinstance(h.get("grad_norm"), list)
            and len(h["grad_norm"]) == len(h["groups"])]
    if not rows:
        return
    groups = rows[0]["groups"]
    # concatenated/rotated telemetry can mix runs with different model
    # depths; render the first run's shape and skip the rest instead of
    # indexing past a shorter row
    consistent = [h for h in rows if h["groups"] == groups]
    dropped = len(rows) - len(consistent)
    rows = consistent
    print(f"\n-- per-layer-group grad norms ({len(rows)} health rows) --")
    if dropped:
        print(f"  ({dropped} rows with a different group layout skipped)")
    bad = [(h["step"], h["first_nonfinite"]) for h in rows
           if h.get("first_nonfinite")]
    if bad:
        for step, grp in bad:
            print(f"  !! step {step}: first non-finite group = {grp}")
    cols = list(range(len(groups)))
    if len(groups) > top_k:
        # rank groups by grad-norm dynamic range so the table shows the
        # layers that MOVED, not an arbitrary prefix
        def swing(i):
            vals = [h["grad_norm"][i] for h in rows
                    if isinstance(h["grad_norm"][i], (int, float))]
            return (max(vals) - min(vals)) if vals else 0.0
        cols = sorted(sorted(cols, key=swing)[-top_k:])
        print(f"  (showing {top_k}/{len(groups)} widest-swinging groups)")
    head = "  " + f"{'step':>8}" + "".join(
        f"{groups[i][:12]:>14}" for i in cols)
    print(head)
    for h in rows:
        cells = []
        for i in cols:
            v = h["grad_norm"][i]
            cells.append(f"{v:>14.4g}" if isinstance(v, (int, float))
                         else f"{str(v):>14}")
        print("  " + f"{h['step']:>8}" + "".join(cells))
    last = rows[-1]
    ratios = last.get("update_ratio")
    if isinstance(ratios, list) and len(ratios) == len(groups):
        finite = [(g, r) for g, r in zip(groups, ratios)
                  if isinstance(r, (int, float))]
        if finite:
            g_hi, r_hi = max(finite, key=lambda t: t[1])
            g_lo, r_lo = min(finite, key=lambda t: t[1])
            print(f"  update/param ratio (last row): max {g_hi} {r_hi:.2e}, "
                  f"min {g_lo} {r_lo:.2e}")


# ---------------------------------------------------------------------------
# Paired A/B compare (--compare a.jsonl b.jsonl): the delta view the perf
# gate's differential diagnosis reuses (scripts/perf_gate.py)
# ---------------------------------------------------------------------------

def run_stats(path):
    """Comparable summary statistics of one metrics JSONL: train
    step-timeline segments (s/step), engine tick phases (s/tick p50/p95),
    request-latency percentiles, throughput, compile totals. Only
    sections the file actually has appear — a train run compares on
    segments, a serve run on tick phases and latencies. Files holding
    several incarnations (append-mode fleet workers) additionally get an
    ``incarnations`` dict of per-segment sub-stats keyed by
    ``replicaR.incK`` so restart histories never blur into one run."""
    segments = load_segments(path)
    metrics = [r for s in segments for r in s[1]]
    events = [r for s in segments for r in s[2]]
    stats = {"path": path}
    stats.update(_stats_from_rows(metrics, events))
    if len(segments) > 1:
        stats["n_incarnations"] = len(segments)
        stats["incarnations"] = {
            segment_label(h, i): _stats_from_rows(m, ev)
            for i, (h, m, ev, _hl) in enumerate(segments)}
    return stats


def _stats_from_rows(metrics, events):
    stats = {"n_metric_rows": len(metrics), "n_events": len(events)}
    segs = {}
    for seg in SCHEMA.TRAIN_SEGMENTS:
        rows = [r for r in metrics
                if isinstance(r.get(f"{seg}_s"), (int, float))]
        if rows:
            total = sum(r[f"{seg}_s"] for r in rows)
            steps = sum(r["steps_in_window"] for r in rows
                        if isinstance(r.get("steps_in_window"),
                                      (int, float)))
            segs[seg] = total / max(steps, 1)
    if segs:
        stats["train_segments_s_per_step"] = segs
    _, tok = column(metrics, "tok_s")
    if tok:
        stats["tok_s_mean"] = sum(tok) / len(tok)
    tick_rows = [r for r in metrics
                 if isinstance(r.get("ticks_in_window"), (int, float))
                 and r["ticks_in_window"] > 0]
    ticks = {}
    for ph in tuple(SCHEMA.TICK_PHASES) + ("total",):
        key = "tick_total_s" if ph == "total" else f"tick_{ph}_s"
        per_tick = [r[key] / r["ticks_in_window"] for r in tick_rows
                    if isinstance(r.get(key), (int, float))]
        if per_tick:
            ticks[ph] = {"p50": _pctile(per_tick, 50),
                         "p95": _pctile(per_tick, 95),
                         "mean": sum(per_tick) / len(per_tick)}
    if ticks:
        stats["tick_phases_s_per_tick"] = ticks
        stats["n_ticks"] = int(sum(r["ticks_in_window"] for r in tick_rows))
    done = [e for e in events if e.get("event") == "request_done"]
    lat = {}
    for key in ("queue_wait_s", "ttft_s", "tpot_s", "e2e_s"):
        vals = [e[key] for e in done
                if isinstance(e.get(key), (int, float))]
        if vals:
            lat[key] = {"p50": _pctile(vals, 50), "p95": _pctile(vals, 95),
                        "p99": _pctile(vals, 99)}
    if lat:
        stats["latency"] = lat
        stats["n_done"] = len(done)
    compiles = [e for e in events if e.get("event") == "compile"
                and isinstance(e.get("compile_seconds"), (int, float))]
    if compiles:
        stats["compile_seconds_total"] = sum(e["compile_seconds"]
                                             for e in compiles)
        stats["n_compiles"] = len(compiles)
    stats["n_recompiles"] = sum(1 for e in events
                                if e.get("event") == "recompile")
    return stats


def _delta_txt(a, b):
    if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
        return "n/a"
    if a == 0:
        return f"{b - a:+.4g}"
    return f"{100 * (b - a) / a:+.1f}%"


def compare_runs(a_path, b_path, out=None):
    """Paired A/B delta view over two metrics JSONLs. Returns
    {"a": stats, "b": stats}; prints the aligned delta table (B relative
    to A) for every section both files carry."""
    write = (out or sys.stdout).write
    A, B = run_stats(a_path), run_stats(b_path)
    write(f"== A/B compare ==\n  A: {a_path}\n  B: {b_path}\n")
    if "tok_s_mean" in A or "tok_s_mean" in B:
        a, b = A.get("tok_s_mean"), B.get("tok_s_mean")
        write(f"  throughput mean: A {a and round(a, 1)} "
              f"B {b and round(b, 1)} tok/s  {_delta_txt(a, b)}\n")
    seg_a = A.get("train_segments_s_per_step", {})
    seg_b = B.get("train_segments_s_per_step", {})
    if seg_a or seg_b:
        write("  -- train step segments (ms/step) --\n")
        for seg in SCHEMA.TRAIN_SEGMENTS:
            a, b = seg_a.get(seg), seg_b.get(seg)
            if a is None and b is None:
                continue
            write(f"    {seg:<12} A {1e3 * a:9.3f}  B {1e3 * b:9.3f}  "
                  f"{_delta_txt(a, b)}\n"
                  if a is not None and b is not None else
                  f"    {seg:<12} A {a}  B {b}\n")
    tick_a = A.get("tick_phases_s_per_tick", {})
    tick_b = B.get("tick_phases_s_per_tick", {})
    if tick_a or tick_b:
        write(f"  -- engine tick phases (ms/tick p50; A {A.get('n_ticks')}"
              f" ticks, B {B.get('n_ticks')} ticks) --\n")
        for ph in tuple(SCHEMA.TICK_PHASES) + ("total",):
            a, b = tick_a.get(ph), tick_b.get(ph)
            if a is None and b is None:
                continue
            if a is not None and b is not None:
                write(f"    {ph:<16} A {1e3 * a['p50']:9.3f}  "
                      f"B {1e3 * b['p50']:9.3f}  "
                      f"{_delta_txt(a['p50'], b['p50'])}"
                      f"   (p95 {_delta_txt(a['p95'], b['p95'])})\n")
            else:
                write(f"    {ph:<16} only in "
                      f"{'A' if a is not None else 'B'}\n")
    lat_a, lat_b = A.get("latency", {}), B.get("latency", {})
    if lat_a or lat_b:
        write(f"  -- request latency (ms; A {A.get('n_done')} done, "
              f"B {B.get('n_done')} done) --\n")
        for key in ("queue_wait_s", "ttft_s", "tpot_s", "e2e_s"):
            a, b = lat_a.get(key), lat_b.get(key)
            if a is None or b is None:
                continue
            write(f"    {key:<12} p50 A {1e3 * a['p50']:9.2f}  "
                  f"B {1e3 * b['p50']:9.2f}  "
                  f"{_delta_txt(a['p50'], b['p50'])}"
                  f"   (p95 {_delta_txt(a['p95'], b['p95'])}, "
                  f"p99 {_delta_txt(a['p99'], b['p99'])})\n")
    if A.get("n_compiles") or B.get("n_compiles"):
        write(f"  compiles: A {A.get('n_compiles', 0)} "
              f"({A.get('compile_seconds_total', 0):.2f}s)  "
              f"B {B.get('n_compiles', 0)} "
              f"({B.get('compile_seconds_total', 0):.2f}s)\n")
    if A.get("n_recompiles") or B.get("n_recompiles"):
        write(f"  !! recompiles: A {A.get('n_recompiles', 0)}  "
              f"B {B.get('n_recompiles', 0)}\n")
    return {"a": A, "b": B}


def plot(metrics, out_path):
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib unavailable; skipping figure", file=sys.stderr)
        return None
    fig, axes = plt.subplots(2, 2, figsize=(11, 7))
    (ax_loss, ax_tps), (ax_mfu, ax_mem) = axes

    s, train = column(metrics, "train_loss")
    sv, val = column(metrics, "val_loss")
    ax_loss.plot(s, train, label="train")
    ax_loss.plot(sv, val, linestyle="-.", label="val")
    ax_loss.set_title("loss")
    ax_loss.legend()

    s, tps = column(metrics, "tok_s")
    ax_tps.plot(s, tps)
    ax_tps.set_title("throughput (tok/s, non-step time excluded)")

    s, mfu = column(metrics, "mfu")
    if mfu:
        ax_mfu.plot(s, [100 * m for m in mfu])
        ax_mfu.set_title("MFU (%)")
    else:
        ax_mfu.set_title("MFU n/a (unknown device peak)")

    for key, label in (("hbm_bytes_in_use", "HBM in use"),
                       ("hbm_peak_bytes", "HBM peak"),
                       ("host_rss_bytes", "host RSS")):
        s, mem = column(metrics, key)
        if mem:
            ax_mem.plot(s, [m / 1024**3 for m in mem], label=label)
    ax_mem.set_title("memory (GiB)")
    ax_mem.legend()

    for ax in axes.flat:
        ax.set_xlabel("step")
    fig.tight_layout()
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    fig.savefig(out_path)
    plt.close(fig)
    print(f"figure written to {out_path}")
    return out_path


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("jsonl", nargs="*", default=None,
                   help="metrics JSONL written by --metrics_jsonl; pass "
                        "several (fleet file + its .workerN.jsonl files) "
                        "for the merged skew-corrected fleet view")
    p.add_argument("--fleet-dir", default=None, metavar="DIR",
                   help="summarize every *.jsonl in DIR as one fleet "
                        "(equivalent to listing them positionally)")
    p.add_argument("--out", default=None,
                   help="figure path (default: <jsonl dir>/metrics.png)")
    p.add_argument("--trace", default=None, metavar="TRACE_JSON",
                   help="also export the run as Chrome trace-event JSON "
                        "(request span trees, engine tick windows, train "
                        "step windows, incidents) — load it at "
                        "https://ui.perfetto.dev")
    p.add_argument("--compare", nargs=2, default=None,
                   metavar=("A_JSONL", "B_JSONL"),
                   help="paired A/B delta view over two runs: train "
                        "step-timeline segments, engine tick phases, "
                        "request-latency percentiles (the view the perf "
                        "gate's differential diagnosis reuses)")
    args = p.parse_args(argv)
    if args.compare:
        compare_runs(*args.compare)
        return
    paths = list(args.jsonl or [])
    if args.fleet_dir:
        paths += sorted(glob.glob(os.path.join(args.fleet_dir, "*.jsonl")))
    if not paths:
        p.error("a metrics JSONL path is required (or use --fleet-dir / "
                "--compare A B)")
    if len(paths) > 1:
        summarize_fleet_files(paths, trace=args.trace)
        return
    path = paths[0]
    header, metrics, events, health = load_rows(path)
    summarize(header, metrics, events)
    summarize_compile(metrics, events)
    summarize_fleet(metrics, events, health)
    summarize_serving(metrics, events)
    summarize_memory(metrics, events)
    summarize_health(health)
    if args.trace:
        from building_llm_from_scratch_tpu.obs.trace import (
            export_chrome_trace,
        )

        meta = export_chrome_trace(path, args.trace)
        print(f"trace written to {args.trace} "
              f"({meta['n_request_spans']} request spans, "
              f"{meta['n_tick_windows']} tick windows, "
              f"{meta['n_train_windows']} train windows)")
    if metrics:
        out = args.out or os.path.join(
            os.path.dirname(os.path.abspath(path)), "metrics.png")
        plot(metrics, out)


if __name__ == "__main__":
    main()
