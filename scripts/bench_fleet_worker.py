"""Worker process for ``bench.py serve_fleet`` (one replica-count arm).

Runs an open-loop Poisson offered-load sweep against an ``EngineRouter``
with ``--replicas`` engine replicas, each pinned to its OWN forced-host
CPU device (``--xla_force_host_platform_device_count``, set HERE before
jax imports — which is why this is a subprocess: the parent bench
process's device count is pinned by the perf-gate baselines). Replicas
execute concurrently (XLA releases the GIL; per-device execution threads
are independent), so aggregate completed-throughput scales with the
replica count — the curve this worker measures.

Prints ONE JSON line: capacity (measured when ``--cap_rps 0``), and per
offered-load arm the offered/completed rps, shed/rejected counts and
TTFT/TPOT/e2e percentiles.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, required=True)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--cap_rps", type=float, default=0.0,
                    help="single-replica capacity (requests/sec) measured "
                         "by the replicas=1 arm; 0 = measure it here")
    ap.add_argument("--requests_per_replica", type=int, default=32)
    ap.add_argument("--max_new", type=int, default=24)
    ap.add_argument("--prompt_len", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--loads", type=str, default="0.75,1.25")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import time

    import jax
    import numpy as np

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import _bucket
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        EngineRouter,
        QueueFullError,
        SLOShedError,
        SamplingParams,
    )

    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    R = args.replicas
    n_requests = args.requests_per_replica * R
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, args.prompt_len)).astype(np.int32)

    def new_router():
        r = EngineRouter.build(
            cfg, params, n_replicas=R, tp=args.tp,
            n_slots=args.slots,
            max_len=_bucket(args.prompt_len + args.max_new),
            max_queue=max(2 * args.slots, 16),
            warmup_prompt_cap=args.prompt_len, metrics_every=8)
        r.warmup()
        return r

    out = {"replicas": R, "tp": args.tp,
           "devices": jax.device_count(), "arms": {}}

    cap_rps = args.cap_rps
    if cap_rps <= 0:
        # closed-loop single-replica capacity: one replica's slots
        # decoded flat out — the per-replica saturation point every
        # arm's offered load is expressed against
        router = new_router()
        eng = router.engines[0]
        sp = SamplingParams(max_new_tokens=args.max_new, ignore_eos=True)
        t0 = time.perf_counter()
        for p in prompts[: args.slots]:
            eng.submit(p, sp, block=True)
        eng.run_until_idle()
        cap_tok_s = (args.slots * args.max_new
                     / (time.perf_counter() - t0))
        cap_rps = cap_tok_s / args.max_new
        out["capacity"] = {"tok_s": round(cap_tok_s, 1),
                           "rps": round(cap_rps, 4)}
        router.shutdown()
    out["cap_rps"] = round(cap_rps, 4)

    for load in (float(x) for x in args.loads.split(",")):
        lam = load * cap_rps * R             # offered vs FLEET capacity
        arrivals = np.cumsum(rng.exponential(1.0 / lam, n_requests))
        router = new_router()
        router.start()
        handles, shed, rejected = [], 0, 0
        t0 = time.perf_counter()
        for i, (p, at) in enumerate(zip(prompts, arrivals)):
            delay = at - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            try:
                handles.append(router.submit(p, SamplingParams(
                    max_new_tokens=args.max_new, ignore_eos=True,
                    seed=i)))
            except SLOShedError:
                shed += 1
            except QueueFullError:
                rejected += 1
        done = 0
        for h in handles:
            try:
                h.result(timeout=600)
                done += 1
            except RuntimeError:
                pass
        dt = time.perf_counter() - t0
        router.shutdown()
        stats = router.stats()
        arm = {
            "offered_rps": round(lam, 4),
            "completed_rps": round(done / dt, 4),
            "completed_tok_s": round(done * args.max_new / dt, 1),
            "done": done, "shed": shed, "rejected": rejected,
            "shed_rate": round((shed + rejected) / n_requests, 3),
            "recompiles": stats["n_recompiles"],
            "routed_total": stats["routed_total"],
            "routed_spill": stats["routed_spill"],
        }
        for rep in stats["replicas"]:
            for key in ("ttft_s", "tpot_s", "e2e_s"):
                if key in rep:
                    arm.setdefault(key, rep[key])    # replica-0 view
        out["arms"][f"load_{load:g}x"] = arm
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    sys.exit(main())
