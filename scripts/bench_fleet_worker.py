"""Worker process for ``bench.py serve_fleet`` (one replica-count arm).

Runs an open-loop Poisson offered-load sweep against a fleet of
``--replicas`` replicas, in one of two transports:

  - ``--transport inproc``: an ``EngineRouter`` with every replica
    pinned to its OWN forced-host CPU device (set before jax imports —
    which is why this is a subprocess: the parent bench process's
    device count is pinned by the perf-gate baselines);
  - ``--transport process``: a ``ProcessFleet`` of supervised worker
    SUBPROCESSES over the unix-socket RPC transport
    (``serving/worker.py`` — the production cross-process path). Each
    worker rebuilds GPT2-124M from the seed-deterministic spec, so the
    arm measures transport + supervision overhead against the identical
    in-process workload.

The engine/host scaffolding lives in ``serving/worker.py``
(``apply_host_env`` / ``EngineSpec``) — one worker implementation for
bench and production.

Prints ONE JSON line: capacity (measured when ``--cap_rps 0``), and per
offered-load arm the offered/completed rps, shed/rejected counts and
TTFT/TPOT/e2e percentiles.
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, required=True)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--transport", choices=("inproc", "process"),
                    default="inproc")
    ap.add_argument("--cap_rps", type=float, default=0.0,
                    help="single-replica capacity (requests/sec) measured "
                         "by the replicas=1 arm; 0 = measure it here")
    ap.add_argument("--requests_per_replica", type=int, default=32)
    ap.add_argument("--max_new", type=int, default=24)
    ap.add_argument("--prompt_len", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--loads", type=str, default="0.75,1.25")
    args = ap.parse_args()

    from building_llm_from_scratch_tpu.serving.worker import apply_host_env

    # in-process replicas share this process -> one forced-host device
    # per replica; cross-process workers pin their own host env
    apply_host_env(args.devices if args.transport == "inproc" else 1)

    import time

    import jax
    import numpy as np

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import _bucket
    from building_llm_from_scratch_tpu.serving import (
        EngineSpec,
        ProcessFleet,
        QueueFullError,
        SLOShedError,
        SamplingParams,
    )

    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    R = args.replicas
    n_requests = args.requests_per_replica * R
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, args.prompt_len)).astype(np.int32)
    max_len = _bucket(args.prompt_len + args.max_new)
    max_queue = max(2 * args.slots, 16)

    def new_fleet(n: int):
        if args.transport == "process":
            spec = EngineSpec(
                model="GPT2", size="124M", dtype=dtype, seed=0,
                tp=args.tp,
                engine=dict(n_slots=args.slots, max_len=max_len,
                            max_queue=max_queue,
                            warmup_prompt_cap=args.prompt_len,
                            metrics_every=8))
            return ProcessFleet(spec, n,
                                default_max_new_tokens=args.max_new
                                ).start()
        from building_llm_from_scratch_tpu.models import init_params
        from building_llm_from_scratch_tpu.serving import EngineRouter

        params = init_params(cfg, jax.random.PRNGKey(0))
        r = EngineRouter.build(
            cfg, params, n_replicas=n, tp=args.tp,
            n_slots=args.slots, max_len=max_len, max_queue=max_queue,
            warmup_prompt_cap=args.prompt_len, metrics_every=8)
        r.warmup()
        r.start()
        return r

    out = {"replicas": R, "tp": args.tp, "transport": args.transport,
           "devices": jax.device_count(), "arms": {}}

    cap_rps = args.cap_rps
    if cap_rps <= 0:
        # closed-loop single-replica capacity: one replica's slots
        # decoded flat out — the per-replica saturation point every
        # arm's offered load is expressed against
        fleet = new_fleet(1)
        sp = SamplingParams(max_new_tokens=args.max_new, ignore_eos=True)
        t0 = time.perf_counter()
        handles = [fleet.submit(p, sp, block=True)
                   for p in prompts[: args.slots]]
        for h in handles:
            h.result(timeout=600)
        cap_tok_s = (args.slots * args.max_new
                     / (time.perf_counter() - t0))
        cap_rps = cap_tok_s / args.max_new
        out["capacity"] = {"tok_s": round(cap_tok_s, 1),
                           "rps": round(cap_rps, 4)}
        fleet.shutdown()
    out["cap_rps"] = round(cap_rps, 4)

    for load in (float(x) for x in args.loads.split(",")):
        lam = load * cap_rps * R             # offered vs FLEET capacity
        arrivals = np.cumsum(rng.exponential(1.0 / lam, n_requests))
        fleet = new_fleet(R)
        handles, shed, rejected = [], 0, 0
        t0 = time.perf_counter()
        for i, (p, at) in enumerate(zip(prompts, arrivals)):
            delay = at - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            try:
                handles.append(fleet.submit(p, SamplingParams(
                    max_new_tokens=args.max_new, ignore_eos=True,
                    seed=i)))
            except SLOShedError:
                shed += 1
            except QueueFullError:
                rejected += 1
        done = 0
        for h in handles:
            try:
                h.result(timeout=600)
                done += 1
            except RuntimeError:
                pass
        dt = time.perf_counter() - t0
        stats = fleet.stats()
        fleet.shutdown()
        arm = {
            "offered_rps": round(lam, 4),
            "completed_rps": round(done / dt, 4),
            "completed_tok_s": round(done * args.max_new / dt, 1),
            "done": done, "shed": shed, "rejected": rejected,
            "shed_rate": round((shed + rejected) / n_requests, 3),
            "recompiles": stats.get("n_recompiles", 0),
            "routed_total": stats.get("routed_total", 0),
            "routed_spill": stats.get("routed_spill", 0),
        }
        for rep in stats.get("replicas", []):
            for key in ("ttft_s", "tpot_s", "e2e_s"):
                if key in rep:
                    arm.setdefault(key, rep[key])    # replica-0 view
        out["arms"][f"load_{load:g}x"] = arm
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    sys.exit(main())
