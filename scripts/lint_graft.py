#!/usr/bin/env python
"""graft-lint CLI shim: static invariant analysis for this repo.

    python scripts/lint_graft.py                 # scan vs baseline
    python scripts/lint_graft.py --rules         # rule catalog
    python scripts/lint_graft.py --update-baseline
    python scripts/lint_graft.py --json -        # machine-readable

Equivalent to ``python -m building_llm_from_scratch_tpu.analysis``; see
``building_llm_from_scratch_tpu/analysis/`` for the checkers and
``analysis/baseline.json`` for the accepted-debt ledger.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from building_llm_from_scratch_tpu.analysis.runner import main

if __name__ == "__main__":
    raise SystemExit(main())
