"""On-chip timing of the fused attention kernels and the stock pallas
flash kernel, with an in-jit scan loop so the remote tunnel's dispatch
latency amortizes away.

CAVEAT (r5): the per-rep numbers include the carry reduction over the
(B, H, T, D) output (~6M-element fp32 sum per rep), which dominates the
kernels themselves at these shapes — treat the output as RELATIVE between
configurations sharing a loop shape, and use the xplane profile
(scripts/profile_xplane.py) for absolute per-kernel times. The r5 sweep's
relative result: 512/512 blocks remain best for fwd+bwd with dropout;
bq=1024/bk=512 ties within noise.

  python scripts/bench_attn_kernels.py [--sweep]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

B, T, H, D = 8, 1024, 12, 64
R = 30


def timed(make_fn, *args):
    f = jax.jit(make_fn)
    out = f(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    out = f(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
    dt = time.perf_counter() - t0
    return dt / R * 1e3  # ms per rep


def main(sweep=False):
    from building_llm_from_scratch_tpu.ops import fused_attention as fa

    k = jax.random.PRNGKey(0)
    q = jax.random.normal(k, (B, H, T, D), jnp.bfloat16)
    kk = jax.random.normal(jax.random.fold_in(k, 1), (B, H, T, D),
                           jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(k, 2), (B, H, T, D),
                          jnp.bfloat16)
    do = jax.random.normal(jax.random.fold_in(k, 3), (B, H, T, D),
                           jnp.bfloat16)
    seed = jnp.zeros((1, 2), jnp.int32)
    scale = 1.0 / D ** 0.5

    combos = [(512, 512)]
    if sweep:
        combos = [(512, 512), (1024, 512), (512, 1024), (1024, 1024),
                  (256, 512), (512, 256), (256, 1024), (1024, 256)]

    for rate in (0.0, 0.1):
        for bq, bk in combos:
            def fwd_loop(q, kk, v):
                def body(c, _):
                    o, l = fa._fwd(q, kk, v, seed, scale=scale, rate=rate,
                                   bq=bq, bk=bk)
                    return c + jnp.sum(o.astype(jnp.float32)), None
                c, _ = jax.lax.scan(body, jnp.zeros(()), None, length=R)
                return c

            def bwd_loop(q, kk, v, do):
                o, lse = fa._fwd(q, kk, v, seed, scale=scale, rate=rate,
                                 bq=bq, bk=bk)

                def body(c, _):
                    dq, dk, dv = fa._bwd(q, kk, v, seed, o, lse, do,
                                         scale=scale, rate=rate, bq=bq,
                                         bk=bk)
                    return c + jnp.sum(dq.astype(jnp.float32)), None
                c, _ = jax.lax.scan(body, jnp.zeros(()), None, length=R)
                return c

            t_f = timed(fwd_loop, q, kk, v)
            t_b = timed(bwd_loop, q, kk, v, do)
            print(f"rate={rate} bq={bq:4d} bk={bk:4d}: "
                  f"fwd {t_f:6.3f} ms  bwd(dq+dkv) {t_b:6.3f} ms  "
                  f"total {t_f + t_b:6.3f}", flush=True)

    # stock pallas flash (no dropout) for reference
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    bs = BlockSizes(block_q=512, block_k_major=512, block_k=512, block_b=1,
                    block_q_major_dkv=512, block_k_major_dkv=512,
                    block_k_dkv=512, block_q_dkv=512,
                    block_k_major_dq=512, block_k_dq=512, block_q_dq=512)

    def stock_loop(q, kk, v, do):
        def f(q, kk, v):
            return jnp.sum(flash_attention(
                q, kk, v, causal=True, sm_scale=scale,
                block_sizes=bs).astype(jnp.float32) * do.astype(jnp.float32))

        def body(c, _):
            l, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, kk, v)
            return c + jnp.sum(grads[0].astype(jnp.float32)), None
        c, _ = jax.lax.scan(body, jnp.zeros(()), None, length=R)
        return c

    t_s = timed(stock_loop, q, kk, v, do)
    print(f"stock flash fwd+bwd (no dropout): {t_s:6.3f} ms", flush=True)


if __name__ == "__main__":
    main(sweep="--sweep" in sys.argv)
