"""Trace the headline train step on the real chip and print the HLO-op
time breakdown from the xplane proto (round-5 VERDICT #2: attack the
non-MXU residue with data, not guesses).

  python scripts/profile_xplane.py [--bs 8] [--parse /tmp/prof_headline]
"""

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def trace(outdir: str, bs: int = 8):
    import jax
    import numpy as np

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        get_policy,
        init_train_state,
        make_train_step,
    )
    from building_llm_from_scratch_tpu.utils.seeding import (
        configure_default_prng,
    )

    configure_default_prng()
    cfg = get_config("GPT2", "124M", dtype="fp32")
    policy = get_policy("bf16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=40)
    state = init_train_state(params, opt, jax.random.PRNGKey(0), policy=policy)
    rng = np.random.default_rng(0)
    T = cfg.context_length
    batch = {
        "inputs": np.asarray(rng.integers(0, cfg.vocab_size, (bs, T)), np.int32),
        "targets": np.asarray(rng.integers(0, cfg.vocab_size, (bs, T)), np.int32),
        "weights": np.ones((bs, T), np.float32),
    }
    step = make_train_step(cfg, opt, policy=policy)
    for _ in range(5):
        state, m = step(state, batch)
    float(m["loss"])
    jax.profiler.start_trace(outdir)
    for _ in range(10):
        state, m = step(state, batch)
    float(m["loss"])
    jax.profiler.stop_trace()
    print("trace written to", outdir, flush=True)


def parse(outdir: str, top: int = 50):
    """Direct xplane parse: sum event durations per op name on the device
    plane's op timeline (tbp's converter is broken against this TF build)."""
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from collections import defaultdict

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xplanes = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                        recursive=True)
    assert xplanes, f"no xplane.pb under {outdir}"
    space = xplane_pb2.XSpace()
    with open(sorted(xplanes)[-1], "rb") as f:
        space.ParseFromString(f.read())
    for plane in space.planes:
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        print(f"=== plane: {plane.name}")
        for line in plane.lines:
            tot = defaultdict(int)
            cnt = defaultdict(int)
            for ev in line.events:
                name = meta.get(ev.metadata_id, str(ev.metadata_id))
                tot[name] += ev.duration_ps
                cnt[name] += 1
            if not tot:
                continue
            total_ps = sum(tot.values())
            print(f"--- line: {line.name} (total {total_ps / 1e9:.3f} ms)")
            for name, ps in sorted(tot.items(), key=lambda kv: -kv[1])[:top]:
                print(f"  {ps / 1e9:9.3f} ms  {100 * ps / total_ps:5.1f}%  "
                      f"x{cnt[name]:<5d} {name[:110]}")


if __name__ == "__main__":
    args = sys.argv[1:]
    if args[:1] == ["--parse"]:
        parse(args[1])
    else:
        bs = int(args[args.index("--bs") + 1]) if "--bs" in args else 8
        outdir = "/tmp/prof_headline"
        trace(outdir, bs)
        parse(outdir)
