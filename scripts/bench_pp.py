"""Pipeline v2 vs v1 (GPipe + forced remat) step-time comparison.

Runs on the 8-device virtual CPU mesh (S=4 stages x data=2, M=8
microbatches) — single-chip TPU cannot host 4 stages, and the v1->v2 delta
is schedule-relative, not hardware-absolute: v1 forced remat of every
stage body, so each backward tick recomputed the stage forward; v2 saves
activations unless --use_actv_ckpt asks for remat.

  python scripts/bench_pp.py
"""

import os
import time

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from building_llm_from_scratch_tpu.configs import get_config  # noqa: E402
from building_llm_from_scratch_tpu.models import init_params  # noqa: E402
from building_llm_from_scratch_tpu.parallel.pipeline import (  # noqa: E402
    make_pp_mesh,
    make_pp_train_step,
)
from building_llm_from_scratch_tpu.training import (  # noqa: E402
    build_optimizer,
    init_train_state,
)


def run(cfg, tag, iters=12):
    mesh = make_pp_mesh(4)                      # (data=2, stage=4)
    opt = build_optimizer(total_steps=iters + 8)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)), opt,
                             jax.random.PRNGKey(1))
    step = make_pp_train_step(cfg, opt, mesh, n_micro=8)
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size,
                     (16, cfg.context_length)).astype(np.int32)
    batch = {"inputs": x, "targets": np.roll(x, -1, 1).astype(np.int32),
             "weights": np.ones_like(x, np.float32)}
    state, m = step(state, batch)
    float(m["loss"])
    for _ in range(3):
        state, m = step(state, batch)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / iters
    print(f"{tag}: {dt * 1e3:8.1f} ms/step")
    return dt


def main():
    import building_llm_from_scratch_tpu.parallel.pipeline as pp

    cfg = get_config("llama3_2", "1B", debug=True).replace(
        emb_dim=256, hidden_dim=1024, vocab_size=2048, context_length=256,
        n_heads=8, n_kv_groups=4, n_layers=8, drop_rate=0.0, dtype="fp32")
    # r3 baseline: forced remat AND every stage computing on every tick
    pp.GATE_INVALID_TICKS = False
    r3 = run(cfg.replace(use_actv_ckpt=True),
             "r3  S=4 M=8 (remat forced, ungated ticks)")
    pp.GATE_INVALID_TICKS = True
    v2r = run(cfg.replace(use_actv_ckpt=True),
              "v2  S=4 M=8 (remat, gated ticks)       ")
    v2 = run(cfg, "v2  S=4 M=8 (saved actvs, gated ticks) ")
    print(f"v2(remat) speedup over r3: {r3 / v2r:.2f}x")
    print(f"v2(saved) speedup over r3: {r3 / v2:.2f}x")
    print("NOTE: virtual-CPU-mesh timing — all 8 devices share the host "
          "cores, so tick gating (less total work) measures, while the "
          "remat<->saved-activation trade (TPU HBM vs MXU) does not; on "
          "real TPU stages saved activations avoid a full recomputed "
          "stage forward per backward tick.")


if __name__ == "__main__":
    main()
