#!/usr/bin/env bash
# Quick CI gate: lint (when ruff is installed) + the tier-1 test command
# from ROADMAP.md. Keeps the obs/ package and the metrics JSONL schema
# importable and lint-clean on every change.
#
#   bash scripts/ci_quick.sh [extra pytest args...]
set -uo pipefail

cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check =="
    # full pyflakes over the whole package now lives in pyproject's
    # [tool.ruff.lint] select — one invocation, no scoped second pass
    ruff check . || exit 1
else
    echo "== ruff not installed; skipping lint =="
fi

echo "== graft-lint (static invariant analysis) =="
# GL01x host-sync / GL02x jit-purity / GL03x lock-discipline / GL04x
# telemetry-schema checkers vs analysis/baseline.json: any NON-BASELINED
# finding fails the gate before the test suite spins up. The runner
# prints per-rule counts, so two gate logs diff cleanly.
python scripts/lint_graft.py || exit 1

echo "== import smoke =="
JAX_PLATFORMS=cpu python -c "
import building_llm_from_scratch_tpu.obs as obs
from building_llm_from_scratch_tpu.obs.metrics import SCHEMA_VERSION
from building_llm_from_scratch_tpu.args import get_args
print('obs import ok, metrics schema v%d' % SCHEMA_VERSION)
" || exit 1

echo "== summarize_metrics renderer smoke (fixture JSONL) =="
# capture-then-grep: grep -q would close the pipe early and fail the
# renderer with BrokenPipeError under pipefail
render_out=$(JAX_PLATFORMS=cpu python scripts/summarize_metrics.py \
    tests/fixtures/metrics_fixture.jsonl --out /tmp/_ci_metrics.png) \
    || exit 1
echo "$render_out" | grep -q "per-layer-group grad norms" || exit 1
echo "$render_out" | grep -q "compile telemetry" || exit 1
echo "renderer ok"

echo "== host-overlap smoke (prefetch + async checkpoint, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, tempfile
d = tempfile.mkdtemp()
data = os.path.join(d, "data"); os.makedirs(data)
# tiny corpus: a couple of debug-context batches — just enough steps for
# one async periodic save to commit while training continues
open(os.path.join(data, "corpus.txt"), "w").write("tiny smoke corpus. " * 160)
out = os.path.join(d, "out")
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
trainer = main(get_args([
    "--data_dir", data, "--output_dir", out, "--debug", "--byte_tokenizer",
    "--n_epochs", "1", "--batch_size", "4", "--eval_freq", "1000",
    "--log_every", "1", "--print_sample_iter", "100000",
    "--save_ckpt_freq", "1", "--warmup_steps", "1",
    "--prefetch", "2", "--async_ckpt", "on",
    "--metrics_jsonl", os.path.join(out, "metrics.jsonl"),
]))
assert trainer.global_step >= 2, trainer.global_step
rows = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
async_saves = [r for r in rows if r.get("event") == "ckpt_async_save"]
assert async_saves, "no ckpt_async_save event in the JSONL"
stalls = sum(r.get("prefetch_stall", 0) for r in rows
             if r.get("type") == "metrics")
assert stalls == 0, f"prefetch stalled {stalls}x on the smoke workload"
print(f"overlap smoke ok: {trainer.global_step} steps, "
      f"{len(async_saves)} async saves, 0 prefetch stalls")
EOF

echo "== serving smoke (continuous-batching engine, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, tempfile
d = tempfile.mkdtemp()
# 8 concurrent JSONL requests against the tiny --debug GPT-2 (ctx 16):
# short byte prompts + small budgets fit the slot capacity
reqs = os.path.join(d, "requests.jsonl")
with open(reqs, "w") as f:
    for i in range(8):
        f.write(json.dumps({"prompt": "abcd"[: 1 + i % 4],
                            "max_new_tokens": 4 + i % 4,
                            "temperature": 0.8 if i % 2 else 0.0,
                            "top_k": 8 if i % 2 else None,
                            "seed": i}) + "\n")
out = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
engine = main(get_args([
    "--mode", "serve", "--debug", "--byte_tokenizer",
    "--data_dir", d,                      # unused in serve mode
    "--serve_prompts", reqs, "--serve_out", out,
    "--serve_slots", "4", "--serve_max_queue", "8",
    "--serve_metrics_every", "4",         # tick-breakdown cadence rows
    "--metrics_jsonl", mj,
]))
results = [json.loads(l) for l in open(out)]
assert len(results) == 8, f"expected 8 results, got {len(results)}"
assert all(r["finish_reason"] in ("eos", "length") for r in results), results
rows = [json.loads(l) for l in open(mj)]
done = [r for r in rows if r.get("event") == "request_done"]
assert len(done) >= 1, "no request_done event in the JSONL"
spans = [r for r in rows if r.get("type") == "span"]
assert len(spans) == 8, f"expected one span tree per request: {len(spans)}"
recompiles = [r for r in rows if r.get("event") == "recompile"]
assert not recompiles, f"recompile after warmup: {recompiles}"
assert engine.n_recompiles == 0
# memory observatory: the ledger's slot-KV component (measured from the
# live cache pytree) must equal the policy's byte-exact per-slot budget
# x n_slots — the reconcile invariant obs/memory.py re-checks at every
# cadence — and the cadence must have emitted snapshot rows with no
# drift incident on a healthy run
snap = engine.memory_ledger.snapshot()
bps = engine.kv_policy.bytes_per_slot(engine.cfg, engine.max_len)
slot_kv = snap["slot_kv"] + snap.get("kv_scales", 0)
assert slot_kv == bps["total_bytes"] * 4, (snap, bps)
mem_snaps = [r for r in rows if r.get("event") == "memory_snapshot"]
assert len(mem_snaps) >= 1, "no memory_snapshot row at cadence"
assert mem_snaps[-1]["components"]["slot_kv"] == snap["slot_kv"]
drift = [r for r in rows if r.get("event") == "memory_drift"]
assert not drift, f"spurious memory_drift on a healthy run: {drift}"
# trace exporter round-trip on the smoke's JSONL: Perfetto-loadable
# Chrome trace with per-request span trees AND tick windows
from building_llm_from_scratch_tpu.obs.trace import export_chrome_trace
trace_path = os.path.join(d, "trace.json")
meta = export_chrome_trace(mj, trace_path)
assert meta["n_request_spans"] == 8, meta
assert meta["n_tick_windows"] >= 1, meta
json.load(open(trace_path))               # valid JSON
import shutil
shutil.copy(mj, "/tmp/_ci_serve_metrics.jsonl")
print(f"serving smoke ok: {len(results)} requests, "
      f"{sum(r['n_tokens'] for r in results)} tokens, "
      f"{len(done)} request_done events, {len(mem_snaps)} memory "
      f"snapshots (slot_kv {slot_kv}B byte-exact), 0 recompiles, "
      f"{meta['n_request_spans']} trace spans, "
      f"{meta['n_tick_windows']} tick windows")
EOF
# renderer grows a memory-observatory section: composition table,
# per-request KV peaks — assert it opens on the smoke's telemetry
render_out=$(JAX_PLATFORMS=cpu python scripts/summarize_metrics.py \
    /tmp/_ci_serve_metrics.jsonl --out /tmp/_ci_serve_metrics.png) \
    || exit 1
echo "$render_out" | grep -q -- "-- memory --" || exit 1
echo "$render_out" | grep -q "request KV: peak" || exit 1
echo "memory renderer ok"

echo "== multi-tenant LoRA serving smoke (train-export -> serve, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, tempfile
d = tempfile.mkdtemp()
data = os.path.join(d, "data"); os.makedirs(data)
open(os.path.join(data, "corpus.txt"), "w").write("lora smoke corpus. " * 120)
out = os.path.join(d, "out")
a1 = os.path.join(d, "adapter_one.npz")
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
# the REAL export path: a short LoRA training run writes artifact #1
trainer = main(get_args([
    "--data_dir", data, "--output_dir", out, "--debug", "--byte_tokenizer",
    "--n_epochs", "1", "--batch_size", "4", "--eval_freq", "1000",
    "--print_sample_iter", "100000", "--save_ckpt_freq", "100000",
    "--warmup_steps", "1", "--use_lora", "--lora_rank", "4",
    "--lora_alpha", "8", "--save_adapter", a1,
]))
assert os.path.isfile(a1), "--save_adapter wrote nothing"
# artifact #2 from the same base config (a second tenant)
import jax
from building_llm_from_scratch_tpu.models.lora import (
    init_lora_params, save_adapter)
a2 = os.path.join(d, "adapter_two.npz")
lora2 = init_lora_params(trainer.cfg, trainer.state["frozen"],
                         jax.random.PRNGKey(7), rank=4)
lora2 = jax.tree_util.tree_map(
    lambda a: a + 0.05 * jax.random.normal(jax.random.PRNGKey(8),
                                           a.shape, a.dtype), lora2)
save_adapter(a2, lora2, rank=4, alpha=8.0, cfg=trainer.cfg)
# serve 2 adapters + base traffic CONCURRENTLY on 4 slots
reqs = os.path.join(d, "requests.jsonl")
with open(reqs, "w") as f:
    for i in range(9):
        f.write(json.dumps({"prompt": "abcd"[: 1 + i % 4],
                            "max_new_tokens": 4 + i % 3,
                            "ignore_eos": True, "seed": i,
                            "adapter": [None, "one", "two"][i % 3]}) + "\n")
res = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
engine = main(get_args([
    "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
    "--serve_prompts", reqs, "--serve_out", res,
    "--serve_slots", "4", "--serve_max_queue", "9",
    "--serve_adapters", f"one={a1},two={a2}",
    "--metrics_jsonl", mj,
]))
results = [json.loads(l) for l in open(res)]
assert len(results) == 9, f"expected 9 results, got {len(results)}"
assert all(r["finish_reason"] == "length" for r in results), results
by_adapter = sorted(r.get("adapter", "base") for r in results)
assert by_adapter == ["base"] * 3 + ["one"] * 3 + ["two"] * 3, by_adapter
rows = [json.loads(l) for l in open(mj)]
loads = [r for r in rows if r.get("event") == "adapter_load"]
assert len(loads) == 2, f"expected 2 adapter_load events: {loads}"
recompiles = [r for r in rows if r.get("event") == "recompile"]
assert not recompiles, f"mixed-adapter traffic recompiled: {recompiles}"
assert engine.n_recompiles == 0
stats = engine.stats()
assert stats["per_adapter"]["one"]["finished"] == 3, stats
print(f"lora serving smoke ok: 9/9 requests ({by_adapter.count('base')} "
      f"base + 6 adapter), {len(loads)} adapter_loads, 0 recompiles")
EOF

echo "== fused multi-LoRA finetune smoke (fleet train -> serve, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, tempfile
d = tempfile.mkdtemp()
# 2 debug-size tenant jobs trained FUSED through one base forward/backward
# (--mode finetune_fleet), then their exported artifacts served as mixed
# multi-tenant traffic — the whole train->deploy hop, zero recompiles in
# both processes. 'plain' style: the Alpaca template alone would overflow
# the --debug 16-token context and zero every loss weight.
jobs = {}
for name, vocab in (("joba", "abcd"), ("jobb", "wxyz")):
    path = os.path.join(d, f"{name}.json")
    with open(path, "w") as f:
        json.dump([{"instruction": vocab[i % 4] * 2, "input": "",
                    "output": vocab[(i + 1) % 4] * 3} for i in range(8)], f)
    jobs[name] = path
out = os.path.join(d, "out")
mj = os.path.join(d, "fleet_metrics.jsonl")
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
fleet = main(get_args([
    "--mode", "finetune_fleet", "--debug", "--byte_tokenizer",
    "--output_dir", out,
    "--fleet_jobs", ",".join(f"{n}={p}" for n, p in jobs.items()),
    "--fleet_rows_per_job", "2", "--fleet_style", "plain",
    "--n_epochs", "2", "--lora_rank", "4", "--lora_alpha", "8",
    "--warmup_steps", "2", "--log_every", "2",
    "--metrics_jsonl", mj,
]))
assert all(j.status == "done" for j in fleet.jobs), fleet.stats()
arts = {j.name: j.artifact for j in fleet.jobs}
assert all(os.path.isfile(p) for p in arts.values()), arts
assert fleet.n_recompiles == 0, "fleet join/finish recompiled"
rows = [json.loads(l) for l in open(mj)]
saves = [r for r in rows if r.get("event") == "adapter_save"]
assert len(saves) >= 2, f"expected >=2 adapter_save events: {saves}"
assert {s.get("job_id") for s in saves} == set(jobs), saves
assert not [r for r in rows if r.get("event") == "recompile"], "recompile"
dones = [r for r in rows if r.get("event") == "finetune_job_done"]
assert len(dones) == 2, dones
# deploy hop: serve BOTH fresh artifacts + base traffic concurrently
reqs = os.path.join(d, "requests.jsonl")
with open(reqs, "w") as f:
    for i in range(9):
        f.write(json.dumps({"prompt": "abcd"[: 1 + i % 4],
                            "max_new_tokens": 4, "ignore_eos": True,
                            "seed": i,
                            "adapter": [None, "joba", "jobb"][i % 3]})
                + "\n")
res = os.path.join(d, "results.jsonl")
mj2 = os.path.join(d, "serve_metrics.jsonl")
engine = main(get_args([
    "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
    "--serve_prompts", reqs, "--serve_out", res,
    "--serve_slots", "4", "--serve_max_queue", "9",
    "--serve_adapters", f"joba={arts['joba']},jobb={arts['jobb']}",
    "--metrics_jsonl", mj2,
]))
results = [json.loads(l) for l in open(res)]
assert len(results) == 9, f"expected 9 results, got {len(results)}"
assert all(r["finish_reason"] == "length" for r in results), results
by_adapter = sorted(r.get("adapter", "base") for r in results)
assert by_adapter == ["base"] * 3 + ["joba"] * 3 + ["jobb"] * 3, by_adapter
rows2 = [json.loads(l) for l in open(mj2)]
loads = [r for r in rows2 if r.get("event") == "adapter_load"]
assert len(loads) >= 2, f"expected >=2 adapter_load events: {loads}"
assert not [r for r in rows2 if r.get("event") == "recompile"], "recompile"
assert engine.n_recompiles == 0
import shutil
shutil.copy(mj, "/tmp/_ci_fleet_metrics.jsonl")
print(f"fused finetune smoke ok: 2 jobs fused ({fleet.global_step} fused "
      f"steps), {len(saves)} adapter_saves -> {len(loads)} adapter_loads, "
      f"9/9 mixed requests, 0 recompiles across train->deploy")
EOF
# renderer grows a fused-finetune section: per-job losses, export
# timeline, FLOPs split — assert it opens on the smoke's telemetry
render_out=$(JAX_PLATFORMS=cpu python scripts/summarize_metrics.py \
    /tmp/_ci_fleet_metrics.jsonl --out /tmp/_ci_fleet_metrics.png) \
    || exit 1
echo "$render_out" | grep -q "fused multi-LoRA finetuning" || exit 1
echo "$render_out" | grep -q "adapter exports" || exit 1
echo "fleet renderer ok"

echo "== KV memory engine smoke (prefix cache + chunked prefill + int8, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, tempfile
d = tempfile.mkdtemp()
# 8 requests sharing ONE system prompt, served through the CLI with the
# full KV memory engine on: prefix cache + chunked prefill + int8 slot
# KV. The --debug model's context is 16 tokens, so the chunk is 8 (the
# 64-token variant is exercised in tests/test_kvcache.py with a larger
# test model): the shared 8-byte prefix is chunk-aligned, so request 1
# prefills + stores it and requests 2..8 must all HIT.
reqs = os.path.join(d, "requests.jsonl")
system = "abcdefgh"                       # 8 shared prefix tokens (bytes)
with open(reqs, "w") as f:
    for i in range(8):
        f.write(json.dumps({"prompt": system + "ij"[i % 2],
                            "max_new_tokens": 4,
                            "ignore_eos": True, "seed": i}) + "\n")
out = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
engine = main(get_args([
    "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
    "--serve_prompts", reqs, "--serve_out", out,
    "--serve_slots", "4", "--serve_max_queue", "8",
    "--serve_prefix_cache", "on", "--serve_prefill_chunk", "8",
    "--serve_kv_quant", "int8",
    "--metrics_jsonl", mj,
]))
results = [json.loads(l) for l in open(out)]
assert len(results) == 8, f"expected 8 results, got {len(results)}"
assert all(r["finish_reason"] == "length" for r in results), results
rows = [json.loads(l) for l in open(mj)]
hits = [r for r in rows if r.get("event") == "prefix_hit"]
misses = [r for r in rows if r.get("event") == "prefix_miss"]
assert len(hits) >= 7, f"expected >=7 prefix hits, got {len(hits)} " \
    f"(misses: {len(misses)})"
recompiles = [r for r in rows if r.get("event") == "recompile"]
assert not recompiles, f"KV-engine traffic recompiled: {recompiles}"
assert engine.n_recompiles == 0
stats = engine.stats()
assert stats["prefix_store"]["hits"] >= 7, stats
warm = [r for r in rows if r.get("event") == "serve_warmup"][0]
assert warm["kv_quant"] == "int8" and warm["prefill_chunk"] == 8, warm
print(f"kv memory engine smoke ok: 8/8 requests, "
      f"{len(hits)} prefix hits / {len(misses)} miss, int8 KV "
      f"({warm['kv_bytes_per_slot']}B/slot), 0 recompiles")
EOF

echo "== paged KV smoke (page-table engine + shared pages, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, tempfile
d = tempfile.mkdtemp()
# The same shared-prefix workload through the REAL CLI with the paged
# KV engine on: the 8-byte system prompt is ONE 8-token page, so
# requests 2..8 must hit the store and land SHARED page-table entries
# (zero pane-copy bytes), the allocator must recycle retired slots'
# pages, and the ledger must reconcile the pool byte-exact.
reqs = os.path.join(d, "requests.jsonl")
system = "abcdefgh"
with open(reqs, "w") as f:
    for i in range(8):
        f.write(json.dumps({"prompt": system + "ij"[i % 2],
                            "max_new_tokens": 4,
                            "ignore_eos": True, "seed": i}) + "\n")
out = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
engine = main(get_args([
    "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
    "--serve_prompts", reqs, "--serve_out", out,
    "--serve_slots", "4", "--serve_max_queue", "8",
    "--serve_kv_paged", "on", "--serve_kv_page_tokens", "8",
    "--serve_prefix_cache", "on", "--serve_prefill_chunk", "8",
    "--metrics_jsonl", mj,
]))
results = [json.loads(l) for l in open(out)]
assert len(results) == 8, f"expected 8 results, got {len(results)}"
assert all(r["finish_reason"] == "length" for r in results), results
rows = [json.loads(l) for l in open(mj)]
shares = [r for r in rows if r.get("event") == "page_share"]
assert len(shares) >= 7, f"expected >=7 shared-page hits: {len(shares)}"
assert all(r["n_pages"] >= 1 for r in shares), shares
stats = engine.stats()
assert stats["pane_copies"] == 0, "paged hit copied panes"
pool = stats["page_pool"]
assert pool["frees"] > 0, f"no page recycling: {pool}"
assert pool["reserved"] == 0 and pool["used"] == 1, pool  # store's page
# ledger: page_pool component == the allocator's own arithmetic, exact
engine.memory_ledger.observe(engine.n_ticks)
mem = engine.memory_ledger.describe()
expect = engine.page_pool.n_pages * engine.page_pool.page_bytes
assert mem["components"]["page_pool"] == expect, (mem, expect)
assert mem["n_drift_events"] == 0, mem
assert not [r for r in rows if r.get("event") == "recompile"]
assert engine.n_recompiles == 0
warm = [r for r in rows if r.get("event") == "serve_warmup"][0]
assert warm["kv_paged"] is True and warm["page_tokens"] == 8, warm
print(f"paged KV smoke ok: 8/8 requests, {len(shares)} shared-page "
      f"hits, 0 pane copies, pool peak {pool['peak_used']}/"
      f"{pool['n_pages']} pages, ledger exact, 0 recompiles")
EOF

echo "== paged flag guard (stray --serve_kv_paged outside serve mode) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import tempfile
from building_llm_from_scratch_tpu.args import get_args
try:
    get_args(["--debug", "--data_dir", tempfile.mkdtemp(),
              "--serve_kv_paged", "on"])
except ValueError as e:
    assert "--serve_kv_paged" in str(e) and "--mode serve" in str(e), e
    print("stray --serve_kv_paged rejected outside serve mode")
else:
    raise SystemExit("stray --serve_kv_paged on was silently accepted")
EOF

echo "== speculative decoding smoke (train repetitive -> spec serve, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, tempfile
d = tempfile.mkdtemp()
data = os.path.join(d, "data"); os.makedirs(data)
# REAL CLI path end-to-end: a short debug train run on a strongly
# repetitive byte corpus (so greedy decode actually continues the cycle
# — an untrained model's output is positional noise no self-history
# drafter can predict), exported via model_pg_final.npz, then served
# with --serve_spec_k 4: the n-gram drafter must earn acceptance on the
# workload prompt-lookup decoding exists for.
open(os.path.join(data, "corpus.txt"), "w").write("abcdefgh" * 400)
out = os.path.join(d, "out")
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
main(get_args([
    "--data_dir", data, "--output_dir", out, "--debug", "--byte_tokenizer",
    "--n_epochs", "2", "--batch_size", "8", "--eval_freq", "100000",
    "--print_sample_iter", "100000", "--save_ckpt_freq", "100000",
    "--warmup_steps", "2",
]))
final = os.path.join(out, "model_pg_final.npz")
assert os.path.isfile(final), "train run exported no final params"
reqs = os.path.join(d, "requests.jsonl")
with open(reqs, "w") as f:
    for i in range(8):
        f.write(json.dumps({"prompt": ("abcdefgh" * 2)[i: i + 6],
                            "max_new_tokens": 8,
                            "ignore_eos": True, "seed": i}) + "\n")
res = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
engine = main(get_args([
    "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
    "--init_params_from", final,
    "--serve_prompts", reqs, "--serve_out", res,
    "--serve_slots", "4", "--serve_max_queue", "8",
    "--serve_spec_k", "4", "--serve_metrics_every", "2",
    "--metrics_jsonl", mj,
]))
results = [json.loads(l) for l in open(res)]
assert len(results) == 8, f"expected 8 results, got {len(results)}"
assert all(r["finish_reason"] == "length" for r in results), results
rows = [json.loads(l) for l in open(mj)]
done = [r for r in rows if r.get("event") == "request_done"]
accepted = sum(r.get("spec_accepted", 0) for r in done)
drafted = sum(r.get("spec_drafted", 0) for r in done)
assert accepted > 0, f"no accepted draft tokens ({drafted} drafted)"
acc_windows = [r for r in rows if r.get("type") == "metrics"
               and r.get("spec_accepted", 0) > 0]
assert acc_windows, "no tick window with accepted > 0"
recompiles = [r for r in rows if r.get("event") == "recompile"]
assert not recompiles, f"spec traffic recompiled: {recompiles}"
assert engine.n_recompiles == 0
warm = [r for r in rows if r.get("event") == "serve_warmup"][0]
assert warm["spec_k"] == 4, warm
print(f"spec smoke ok: 8/8 requests, {accepted}/{drafted} drafts "
      f"accepted ({100*accepted/max(drafted,1):.0f}%), "
      f"{len(acc_windows)} accepting windows, 0 recompiles")
EOF

echo "== serving drain smoke (SIGTERM + mid-run /metrics scrape, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.request
d = tempfile.mkdtemp()
# 8 requests on 2 slots: when the SIGTERM lands after the first result
# line, most of the batch is still in flight/queued — the drain must
# finish ALL of it (generous --drain_timeout) and exit 0. The HTTP
# endpoint rides along so /metrics can be scraped MID-RUN (the server
# thread serves concurrently with the JSONL pump).
reqs = os.path.join(d, "requests.jsonl")
with open(reqs, "w") as f:
    for i in range(8):
        f.write(json.dumps({"prompt": "abcd"[: 1 + i % 4],
                            "max_new_tokens": 6 + i % 4,
                            "ignore_eos": True, "seed": i}) + "\n")
out = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "building_llm_from_scratch_tpu",
     "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
     "--serve_prompts", reqs, "--serve_out", out,
     "--serve_slots", "2", "--serve_max_queue", "8",
     "--serve_port", str(port), "--serve_metrics_every", "4",
     "--drain_timeout", "120", "--metrics_jsonl", mj],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    env=dict(os.environ, JAX_PLATFORMS="cpu"))
deadline = time.monotonic() + 300
signaled = False
scraped = None
while time.monotonic() < deadline:
    if proc.poll() is not None:
        break                      # finished before we could preempt it
    if os.path.exists(out) and open(out).read().count("\n") >= 1:
        try:
            # mid-run scrape: >=1 request finished, most still in flight
            scraped = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=60
            ).read().decode()
        except OSError as e:
            print(f"note: mid-run /metrics scrape failed ({e})")
        proc.send_signal(signal.SIGTERM)   # preempt mid-serve
        signaled = True
        break
    time.sleep(0.05)
stdout, _ = proc.communicate(timeout=300)
assert proc.returncode == 0, f"serve rc={proc.returncode}:\n{stdout}"
results = [json.loads(l) for l in open(out)]
assert len(results) == 8, f"expected 8 result lines, got {len(results)}"
bad = [r for r in results if "error" in r]
assert not bad, f"drain lost/preempted requests: {bad}"
rows = [json.loads(l) for l in open(mj)]
events = [r.get("event") for r in rows if r.get("type") == "event"]
if signaled:
    assert "preemption_signal" in events, events
    assert "drain" in events, "no drain event in the JSONL"
else:
    # rare: all 8 requests finished between two 0.05s polls, so no
    # SIGTERM landed — the completeness + zero-recompile asserts above
    # still hold; skip only the signal-dependent ones
    print("note: serve finished before SIGTERM could land; "
          "drain-event asserts skipped this run")
if scraped is not None:
    # exposition parses: every sample line is "name[{labels}] value"
    samples = {}
    for line in scraped.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        samples[name] = float(value)
    ttft = samples.get("bllm_serve_ttft_seconds_count", 0)
    assert ttft >= 1, f"ttft histogram empty mid-run: {ttft}"
    assert "bllm_serve_slot_occupancy" in samples, sorted(samples)[:20]
    assert samples.get("bllm_serve_engine_up") == 1.0
    print(f"mid-run /metrics scrape ok: {len(samples)} samples, "
          f"ttft_count={ttft:g}, "
          f"occupancy={samples['bllm_serve_slot_occupancy']:g}")
recompiles = [r for r in rows if r.get("event") == "recompile"]
assert not recompiles, f"recompile during drained serve: {recompiles}"
print(f"drain smoke ok (signaled={signaled}): {len(results)} results all "
      "complete, clean exit 0, 0 recompiles")
EOF

echo "== fleet router smoke (2 replicas, mixed adapters, SIGTERM rolling drain, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, signal, subprocess, sys, tempfile, time
d = tempfile.mkdtemp()
# two adapter artifacts against the --debug base (same cfg the serve
# subprocess builds), then REAL CLI serve with --serve_replicas 2 and
# mixed base/tenant traffic; a SIGTERM lands mid-run — the router's
# ROLLING drain takes replica 0 out first (its queued work re-dispatched
# to replica 1), then drains replica 1: every request completes.
from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.build_components import build_components
import jax
from building_llm_from_scratch_tpu.models.lora import (
    init_lora_params, save_adapter)
comps = build_components(get_args(
    ["--data_dir", d, "--debug", "--byte_tokenizer"]))
arts = {}
for i, name in enumerate(("ta", "tb")):
    lora = init_lora_params(comps.cfg, comps.params,
                            jax.random.PRNGKey(7 + i), rank=4)
    p = os.path.join(d, f"{name}.npz")
    save_adapter(p, lora, rank=4, alpha=8.0, cfg=comps.cfg)
    arts[name] = p
reqs = os.path.join(d, "requests.jsonl")
with open(reqs, "w") as f:
    for i in range(10):
        f.write(json.dumps({"prompt": "abcd"[: 1 + i % 4],
                            "max_new_tokens": 6, "ignore_eos": True,
                            "seed": i,
                            "adapter": [None, "ta", "tb"][i % 3]}) + "\n")
out = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
proc = subprocess.Popen(
    [sys.executable, "-m", "building_llm_from_scratch_tpu",
     "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
     "--serve_prompts", reqs, "--serve_out", out,
     "--serve_replicas", "2", "--serve_slots", "2",
     "--serve_max_queue", "10",
     "--serve_adapters", f"ta={arts['ta']},tb={arts['tb']}",
     "--drain_timeout", "120", "--metrics_jsonl", mj],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    env=dict(os.environ, JAX_PLATFORMS="cpu"))
deadline = time.monotonic() + 300
signaled = False
while time.monotonic() < deadline:
    if proc.poll() is not None:
        break
    if os.path.exists(out) and open(out).read().count("\n") >= 1:
        proc.send_signal(signal.SIGTERM)   # preempt mid-serve
        signaled = True
        break
    time.sleep(0.05)
stdout, _ = proc.communicate(timeout=300)
assert proc.returncode == 0, f"serve rc={proc.returncode}:\n{stdout}"
results = [json.loads(l) for l in open(out)]
assert len(results) == 10, f"expected 10 result lines, got {len(results)}"
bad = [r for r in results if "error" in r]
assert not bad, f"rolling drain lost requests: {bad}"
by_adapter = sorted(r.get("adapter", "base") for r in results)
assert by_adapter.count("ta") == 3 and by_adapter.count("tb") == 3
rows = [json.loads(l) for l in open(mj)]
fleet = [r for r in rows if r.get("event") == "serve_fleet"]
assert any(f.get("phase") == "build" and f.get("n_replicas") == 2
           for f in fleet), fleet
done = [r for r in rows if r.get("event") == "request_done"]
replicas = {r.get("replica") for r in done}
assert replicas <= {0, 1} and len(done) == 10, (replicas, len(done))
recompiles = [r for r in rows if r.get("event") == "recompile"]
assert not recompiles, f"fleet traffic recompiled: {recompiles}"
redis = [r for r in rows if r.get("event") == "router_redispatch"]
if signaled:
    drains = [r for r in rows if r.get("event") == "replica_drain"]
    assert drains, "no replica_drain event after SIGTERM"
    # affinity measurably routed: tenant traffic on its resident replica
else:
    print("note: serve finished before SIGTERM could land; "
          "drain-event asserts skipped this run")
spans = [r for r in rows if r.get("type") == "span"]
assert len(spans) == 10, f"expected one span tree per request: {len(spans)}"
for s in spans:
    assert s["children"][0]["name"] == "router", s
import shutil
shutil.copy(mj, "/tmp/_ci_fleet_serve_metrics.jsonl")
print(f"fleet router smoke ok (signaled={signaled}): 10/10 requests "
      f"across replicas {sorted(replicas)}, {len(redis)} re-dispatched, "
      f"0 recompiles, 10 routed span trees")
EOF
# renderer grows a scale-out fleet section: per-replica split, drains,
# re-dispatches — assert it opens on the smoke's telemetry
render_out=$(JAX_PLATFORMS=cpu python scripts/summarize_metrics.py \
    /tmp/_ci_fleet_serve_metrics.jsonl --out /tmp/_ci_fleet_serve.png) \
    || exit 1
echo "$render_out" | grep -q "scale-out serving fleet" || exit 1
echo "fleet renderer ok"

echo "== cross-process fleet smoke (2 worker processes, kill -9, restart, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, signal, socket, subprocess, sys, tempfile, time, glob
import threading, urllib.request, urllib.error
d = tempfile.mkdtemp()
# REAL CLI serve with --serve_workers 2: two supervised worker PROCESSES
# (each rebuilding the --debug engine from its EngineSpec) behind the
# unix-socket RPC transport. Mid-run, one worker takes a kill -9 (pid
# straight from /healthz): every HTTP request must come back 200 or
# typed worker_dead (zero silently lost), the survivor must serve with
# ZERO recompiles, and the dead worker must restart, rejoin dispatch,
# and serve again before the clean SIGTERM exit.
mj = os.path.join(d, "metrics.jsonl")
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "building_llm_from_scratch_tpu",
     "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
     "--serve_workers", "2", "--serve_slots", "2",
     "--serve_max_queue", "16", "--serve_port", str(port),
     "--serve_max_new_tokens", "8",
     "--drain_timeout", "120", "--metrics_jsonl", mj],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    env=dict(os.environ, JAX_PLATFORMS="cpu"))

def healthz(timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=timeout) as r:
        return json.loads(r.read().decode())

def wait_fleet(pred, what, deadline_s=300):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        assert proc.poll() is None, (
            f"serve exited rc={proc.returncode} waiting for {what}:\n"
            + proc.stdout.read())
        try:
            hz = healthz()
            if pred(hz):
                return hz
        except (OSError, urllib.error.URLError):
            pass
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")

hz = wait_fleet(lambda h: h.get("status") == "serving"
                and h.get("workers_up") == 2, "2 workers serving")
pids = {r["replica"]: r["pid"] for r in hz["replicas"]}
# prime the aggregated-metrics cache while both workers are healthy:
# the mid-outage scrape below must serve the victim's CACHED series
with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
    prom = r.read().decode()
assert 'worker="0"' in prom and 'worker="1"' in prom, (
    "per-worker label passthrough missing")

def post(rec, out, i):
    body = json.dumps(rec).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=300) as r:
            out[i] = (r.status, json.loads(r.read().decode()))
    except urllib.error.HTTPError as e:
        out[i] = (e.code, json.loads(e.read().decode()))
    except Exception as e:                      # noqa: BLE001
        out[i] = (None, {"error": f"LOST: {e!r}"})

# phase 1: 10 concurrent requests, then kill -9 one worker mid-decode
results = {}
threads = [threading.Thread(target=post, args=(
    {"prompt": "abcd"[: 1 + i % 4], "max_new_tokens": 8,
     "ignore_eos": True, "seed": i}, results, i), daemon=True)
    for i in range(10)]
for t in threads:
    t.start()
time.sleep(0.15)                                # let decode start
victim = next(r["replica"] for r in healthz()["replicas"]
              if r["status"] == "serving")
os.kill(pids[victim], signal.SIGKILL)
# mid-outage aggregated /metrics: the victim's cached series keep being
# served (marked STALE), the endpoint answers fast and never raises —
# the real-engine respawn takes seconds, so 1.2s after the kill the
# victim is reliably down and past the staleness bar
time.sleep(1.2)
t0 = time.monotonic()
with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
    prom = r.read().decode()
scrape_s = time.monotonic() - t0
assert scrape_s < 1.0, f"/metrics took {scrape_s:.2f}s mid-outage"
for line in prom.splitlines():                   # parseable exposition
    assert line.startswith("#") or " " in line, f"bad prom line: {line}"
import re as _re
assert _re.search(
    r'fleet_worker_metrics_stale\{worker="%d",incarnation="0"\} 1'
    % victim, prom), "victim's staleness gauge not set"
assert _re.search(r'worker="%d"' % victim, prom.replace(
    "fleet_worker_metrics", "")), "victim's cached series dropped"
assert "fleet_rpc_client_latency_seconds" in prom
print(f"mid-outage /metrics ok in {scrape_s * 1e3:.0f} ms "
      "(victim cached+stale)")
for t in threads:
    t.join(timeout=300)
assert len(results) == 10, f"lost responses: {sorted(results)}"
ok = [i for i, (st, _) in results.items() if st == 200]
died = [i for i, (st, b) in results.items()
        if st != 200 and "worker_dead" in str(b.get("error", ""))]
other = [results[i] for i in results if i not in ok and i not in died]
assert not other, f"untyped failures: {other}"
for i in ok:
    assert results[i][1].get("token_ids"), results[i]

# phase 2: the dead worker restarts, rejoins, and the fleet serves again
hz = wait_fleet(lambda h: h.get("workers_up") == 2
                and h.get("status") == "serving",
                "killed worker to restart and rejoin")
row = next(r for r in hz["replicas"] if r["replica"] == victim)
assert row["status"] == "serving" and row["restarts"] >= 1, row
assert row["pid"] != pids[victim], "healthz still shows the dead pid"
post_res = {}
post({"prompt": "abc", "max_new_tokens": 8, "ignore_eos": True},
     post_res, 0)
assert post_res[0][0] == 200, f"post-restart request failed: {post_res}"

proc.send_signal(signal.SIGTERM)
stdout, _ = proc.communicate(timeout=300)
assert proc.returncode == 0, f"serve rc={proc.returncode}:\n{stdout}"

rows = [json.loads(l) for l in open(mj)]
events = [r for r in rows if r.get("type") == "event"]
kinds = [e["event"] for e in events]
assert kinds.count("worker_dead") == 1, kinds
assert "worker_restart" in kinds, kinds
assert kinds.count("worker_spawn") >= 3, kinds   # 2 boots + 1 restart
dead = next(e for e in events if e["event"] == "worker_dead")
assert dead["replica"] == victim and dead["pid"] == pids[victim], dead
# zero recompiles anywhere: scan every worker's own metrics JSONL
# (append-mode, so worker <victim>'s file holds both incarnations —
# neither the survivor, the victim, nor its replacement may recompile
# after their own warmups)
recompiles = []
for wf in sorted(glob.glob(mj + ".worker*.jsonl")):
    wrows = [json.loads(l) for l in open(wf)]
    recompiles += [r for r in wrows if r.get("event") == "recompile"]
assert not recompiles, f"worker recompiled: {recompiles}"
import shutil
os.makedirs("/tmp/_ci_crossproc", exist_ok=True)
shutil.copy(mj, "/tmp/_ci_crossproc/metrics.jsonl")
for wf in glob.glob(mj + ".worker*.jsonl"):
    shutil.copy(wf, "/tmp/_ci_crossproc/" + os.path.basename(
        wf).replace(os.path.basename(mj), "metrics.jsonl"))
shutil.copy(mj, "/tmp/_ci_crossproc_metrics.jsonl")
print(f"cross-process fleet smoke ok: {len(ok)}/10 completed, "
      f"{len(died)} failed typed worker_dead, 0 lost; worker {victim} "
      f"kill -9 -> restarted pid {row['pid']} and served again; "
      "0 worker recompiles")
EOF
# renderer grows the worker-lifecycle section on the smoke's telemetry
render_out=$(JAX_PLATFORMS=cpu python scripts/summarize_metrics.py \
    /tmp/_ci_crossproc_metrics.jsonl) || exit 1
echo "$render_out" | grep -q "cross-process fleet workers" || exit 1
echo "worker-lifecycle renderer ok"
# multi-file fleet view: fleet + worker JSONLs merged on the fleet
# clock (clock_sync offsets), incarnations labeled per header
render_out=$(JAX_PLATFORMS=cpu python scripts/summarize_metrics.py \
    --fleet-dir /tmp/_ci_crossproc) || exit 1
echo "$render_out" | grep -q "merged incident timeline" || exit 1
echo "$render_out" | grep -q "fleet observability" || exit 1
echo "fleet-dir renderer ok"
# fleet observatory exporter: ONE merged skew-corrected Perfetto
# timeline — every submitted request has exactly one closed span tree
# spanning router+worker, rpc child spans ride along, and the victim's
# death + restart incidents are visible on the merged timeline
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json
from building_llm_from_scratch_tpu.obs.fleetview import (
    export_fleet_trace,
)
meta = export_fleet_trace("/tmp/_ci_crossproc/metrics.jsonl",
                          "/tmp/_ci_crossproc/fleet_trace.json")
assert meta["n_request_spans"] >= 11, meta   # 10 + the post-restart one
assert meta["n_worker_files"] == 2, meta
assert meta["n_incarnations"] >= 3, meta     # 2 boots + 1 restart
assert meta["n_flow_edges"] >= 1, meta       # cross-process span trees
trace = json.load(open("/tmp/_ci_crossproc/fleet_trace.json"))
# pid 1 = the fleet's request track; worker tracks (pid 10+) also carry
# the engines' own local-id request spans, which are a different view
req = [e for e in trace["traceEvents"] if e.get("ph") == "X"
       and e.get("name") == "request" and e.get("pid") == 1]
ids = [e["args"]["request_id"] for e in req]
assert len(ids) == len(set(ids)), "a request emitted >1 span tree"
assert all("outcome" in e["args"] and "worker" in e["args"]
           for e in req)
assert any(e.get("name", "").startswith("rpc:")
           for e in trace["traceEvents"] if e.get("ph") == "X"), (
    "no rpc child spans in the merged trace")
names = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "i"}
assert "worker_dead" in names and "worker_restart" in names, names
print(f"fleet exporter ok: {meta['n_request_spans']} request trees, "
      f"{meta['n_worker_spans']} worker spans, "
      f"{meta['n_flow_edges']} rpc edges across "
      f"{meta['n_incarnations']} incarnations")
EOF
echo "fleet exporter ok"

echo "== long-prompt serve smoke (seq-sharded prefill, sp=2, CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF' || exit 1
import json, os, subprocess, sys, tempfile
d = tempfile.mkdtemp()
# Seq-sharded prefill through the REAL CLI. The --debug model's context
# is 16 tokens, so with --serve_sp 2 each device owns an 8-token pane:
# the 12-byte prompts below exceed one pane and are only admissible
# because prefill chunks are sharded across the seq mesh axis. A
# subprocess (unlike the in-process smokes above) because the seq axis
# needs an 8-device forced host, set via XLA_FLAGS before jax imports.
reqs = os.path.join(d, "requests.jsonl")
with open(reqs, "w") as f:
    for i in range(6):
        f.write(json.dumps({"prompt": "hello world!" if i % 2 else "hi",
                            "max_new_tokens": 3,
                            "ignore_eos": True, "seed": i}) + "\n")
out = os.path.join(d, "results.jsonl")
mj = os.path.join(d, "metrics.jsonl")
env = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS="--xla_force_host_platform_device_count=8")
proc = subprocess.run(
    [sys.executable, "-m", "building_llm_from_scratch_tpu",
     "--mode", "serve", "--debug", "--byte_tokenizer", "--data_dir", d,
     "--serve_prompts", reqs, "--serve_out", out,
     "--serve_slots", "2", "--serve_max_queue", "6",
     "--serve_sp", "2", "--serve_prefill_chunk", "8",
     "--metrics_jsonl", mj],
    env=env, capture_output=True, text=True, timeout=600)
assert proc.returncode == 0, f"serve rc={proc.returncode}:\n" \
    f"{proc.stdout}\n{proc.stderr}"
results = [json.loads(l) for l in open(out)]
assert len(results) == 6, f"expected 6 results, got {len(results)}"
assert all(r["finish_reason"] == "length" for r in results), results
rows = [json.loads(l) for l in open(mj)]
warm = [r for r in rows if r.get("event") == "serve_warmup"][0]
assert warm["sp"] == 2 and warm["prompt_pane_tokens"] == 8, warm
assert warm["max_prompt"] == 15, warm
done = [r for r in rows if r.get("event") == "request_done"]
longs = [r for r in done if r.get("long_prompt")]
assert len(longs) == 3, f"expected 3 long-prompt requests: {done}"
assert not [r for r in rows if r.get("event") == "recompile"], "recompile"
print(f"long-prompt serve smoke ok: 6/6 requests (3 beyond one "
      f"device's {warm['prompt_pane_tokens']}-token pane), sp=2 x 8 "
      f"devices, prompt ceiling {warm['max_prompt']}, 0 recompiles")
EOF

echo "== perf observatory gate (structural, timing-free, CPU) =="
# The three debug-size micro-benches' structural HLO fingerprints —
# per-program cost-analysis FLOPs, compiled-program count, arg
# signatures, recompile count, HBM breakdown — must match the checked-in
# PERF_BASELINE.json exactly. Deterministic on CPU (no timing enters the
# comparison), so a forced recompile or FLOP growth in the train step /
# serving engine fails CI with the offending program named. Re-baseline
# (with a reason) via: scripts/perf_gate.py --update-baseline --reason …
JAX_PLATFORMS=cpu python scripts/perf_gate.py || exit 1

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
