"""Dump the optimized HLO of the headline train step (for profiling work:
map xplane fusion names back to source ops).

  python scripts/dump_hlo.py /tmp/headline_hlo.txt [--unroll]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def main(out_path: str):
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        get_policy,
        init_train_state,
        make_train_step,
    )

    cfg = get_config("GPT2", "124M", dtype="fp32")
    policy = get_policy("bf16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=40)
    state = init_train_state(params, opt, jax.random.PRNGKey(0), policy=policy)
    rng = np.random.default_rng(0)
    T = cfg.context_length
    batch = {
        "inputs": np.asarray(rng.integers(0, cfg.vocab_size, (8, T)), np.int32),
        "targets": np.asarray(rng.integers(0, cfg.vocab_size, (8, T)), np.int32),
        "weights": np.ones((8, T), np.float32),
    }
    step = make_train_step(cfg, opt, policy=policy)
    compiled = step.lower(state, batch).compile()
    txt = compiled.as_text()
    with open(out_path, "w") as f:
        f.write(txt)
    print(f"wrote {len(txt)} bytes to {out_path}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/headline_hlo.txt")
