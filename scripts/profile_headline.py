"""Breakdown timing for the headline config (GPT2-124M bf16 bs4 ctx1024).

Times (axon-sync via device_get, bench.py note): fwd-only, fwd+bwd,
full step; each with dropout on/off; plus attention micro-bench per impl
at the headline shape with/without dropout. Run on the real chip:

  python scripts/profile_headline.py
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.models.transformer import forward
from building_llm_from_scratch_tpu.training import (
    build_optimizer, get_policy, init_train_state, make_train_step,
)
from building_llm_from_scratch_tpu.training.train_step import (
    cross_entropy_loss, make_full_params_fn,
)
from building_llm_from_scratch_tpu.utils.seeding import configure_default_prng

configure_default_prng()

B, T = 4, 1024
ITERS = 20


def sync(x):
    return float(jnp.sum(jax.tree_util.tree_leaves(x)[0].astype(jnp.float32)))


def timeit(fn, *args):
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / ITERS * 1e3  # ms


def bench_model(drop):
    cfg = get_config("GPT2", "124M", dtype="fp32")
    if not drop:
        cfg = cfg.replace(drop_rate=0.0)
    policy = get_policy("bf16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {
        "inputs": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
        "weights": jnp.ones((B, T), jnp.float32),
    }
    full = make_full_params_fn(cfg, policy=policy)
    key = jax.random.PRNGKey(1)

    @jax.jit
    def fwd(p):
        pp = full(p, {})
        logits = forward(pp, cfg, batch["inputs"], rng=key,
                        deterministic=(cfg.drop_rate <= 0.0))
        return cross_entropy_loss(logits, batch["targets"], batch["weights"])

    grad = jax.jit(jax.value_and_grad(fwd))

    opt = build_optimizer(total_steps=ITERS + 5)
    state = init_train_state(params, opt, jax.random.PRNGKey(0), policy=policy)
    step = make_train_step(cfg, opt, policy=policy)

    t_fwd = timeit(fwd, params)
    t_grad = timeit(lambda p: grad(p)[0], params)

    def run_step(s, b):
        s2, m = step(s, b)
        return m["loss"], s2
    # step donates; keep threading state
    out = step(state, batch); sync(out[1]["loss"]); state = out[0]
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, m = step(state, batch)
    sync(m["loss"])
    t_step = (time.perf_counter() - t0) / ITERS * 1e3

    tag = "drop0.1" if drop else "drop0.0"
    tok = B * T
    print(f"[{tag}] fwd {t_fwd:7.2f} ms | fwd+bwd {t_grad:7.2f} ms | "
          f"step {t_step:7.2f} ms | {tok / t_step * 1e3:8.0f} tok/s")


def bench_attn():
    from building_llm_from_scratch_tpu.ops.attention import causal_attention
    H, D = 12, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, T, H, D), jnp.bfloat16)
    rng = jax.random.PRNGKey(3)

    for impl in ("xla", "flash", "pallas", "fused"):
        for drop in (0.0, 0.1):
            if impl == "pallas" and drop > 0:
                continue

            def f(q, k, v):
                def g(q, k, v):
                    o = causal_attention(q, k, v, dropout_rate=drop,
                                         dropout_rng=rng,
                                         deterministic=(drop == 0.0), impl=impl)
                    return jnp.sum(o.astype(jnp.float32) ** 2)
                return jax.grad(g, argnums=(0, 1, 2))(q, k, v)

            jf = jax.jit(f)
            try:
                t = timeit(jf, q, k, v)
                print(f"attn {impl:7s} drop={drop}: {t:6.2f} ms (fwd+bwd)")
            except Exception as e:
                print(f"attn {impl:7s} drop={drop}: FAILED {type(e).__name__}: {e}")


if __name__ == "__main__":
    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    bench_model(drop=True)
    bench_model(drop=False)
    bench_attn()
