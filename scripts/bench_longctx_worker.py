"""Worker process for ``bench.py pretrain_longctx`` / ``serve_longctx``.

A subprocess because both arms need a multi-device host
(``--xla_force_host_platform_device_count``, set BEFORE jax imports) and
the parent bench process's device count is pinned by the perf-gate
baselines. The engine/host scaffolding lives in ``serving/worker.py``
(``apply_host_env``) — one worker implementation for bench and fleet.

Two arms, selected by ``--arm``:

  - ``train``: the long-context pretrain A/B. The SAME batches run
    through an unsharded reference ``make_train_step`` and a
    sequence-sharded one (``build_mesh_plan("dp", sp=N)`` routes
    attention through the ring schedule, ops/ring_attention.py). Prints
    both loss trajectories and both CompileWatcher recompile counts so
    the parent can assert parity and compile stability. The losses are
    NOT bit-identical: the ring's online-softmax reduces KV panes in
    ring order while the dense oracle reduces the full row at once, a
    floating-point reassociation — the parent pins rtol 2e-4 (the same
    tolerance tests/test_ring_attention.py pins), not equality.
  - ``serve``: seq-sharded prefill under mixed traffic. One sp=N engine
    serves interleaved long prompts (> one device's pane) and short
    ones; prints the TTFT split, the post-warmup recompile count (must
    be 0 — the sharding constraint is static) and aggregate tok/s.

Prints ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys


def _train(args) -> dict:
    import time

    import jax
    import numpy as np

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.obs.compile import CompileWatcher
    from building_llm_from_scratch_tpu.parallel import build_mesh_plan
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )

    # the longctx-32k architecture (GQA + rope 500k + swiglu) scaled to
    # CPU A/B size: the 32k context itself is the TPU workload — here the
    # ring schedule, the mesh and the step graph are what's exercised.
    # fp32 so the parity bound is the ring REASSOCIATION, not bf16 eps.
    cfg = get_config("longctx", "32k", target_context_length=None).replace(
        context_length=args.ctx, emb_dim=64, n_layers=2, n_heads=4,
        n_kv_groups=2, hidden_dim=128, vocab_size=512, drop_rate=0.0,
        dtype="fp32")
    rng = np.random.default_rng(0)
    batches = []
    for _ in range(args.steps):
        x = rng.integers(0, cfg.vocab_size,
                         (args.batch, cfg.context_length)).astype(np.int32)
        batches.append({"inputs": x, "targets": np.roll(x, -1, 1),
                        "weights": np.ones_like(x, np.float32)})

    def run(sp):
        opt = build_optimizer(peak_lr=1e-3, warmup_steps=2,
                              total_steps=args.steps + 2)
        state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                                 opt, jax.random.PRNGKey(0))
        if sp > 1:
            plan = build_mesh_plan("dp", sp=sp)
            state = plan.shard_state(state)
            step = CompileWatcher(
                make_train_step(cfg, opt, sp_mesh=plan.sp_mesh),
                label=f"longctx_sp{sp}")
            shard = plan.shard_batch
        else:
            step = CompileWatcher(make_train_step(cfg, opt),
                                  label="longctx_ref")
            shard = lambda b: b               # noqa: E731
        losses, t0 = [], None
        for i, b in enumerate(batches):
            state, m = step(state, shard(b))
            losses.append(float(m["loss"]))   # blocks on the step
            if i == 0:
                t0 = time.perf_counter()      # steps 2..N: steady state
        dt = time.perf_counter() - t0
        toks = args.batch * cfg.context_length * (args.steps - 1)
        return losses, step.n_recompiles, toks / dt if dt > 0 else 0.0

    losses_ref, rec_ref, tps_ref = run(1)
    losses_sp, rec_sp, tps_sp = run(args.sp)
    return {
        "ctx": cfg.context_length, "sp": args.sp, "batch": args.batch,
        "steps": args.steps, "devices": jax.device_count(),
        "losses_ref": losses_ref, "losses_sp": losses_sp,
        "recompiles_ref": rec_ref, "recompiles_sp": rec_sp,
        "tok_s_ref": round(tps_ref, 1), "tok_s_sp": round(tps_sp, 1),
    }


def _serve(args) -> dict:
    import time

    import jax
    import numpy as np

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.parallel.sharding import (
        serve_mesh_plan,
    )
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        KVCachePolicy,
        SamplingParams,
    )

    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    plan = serve_mesh_plan(sp=args.sp)
    pane = -(-args.max_len // args.sp)
    engine = DecodeEngine(
        cfg, params, n_slots=args.slots, max_len=args.max_len,
        max_queue=args.n_long + args.n_short, mesh_plan=plan,
        kv_policy=KVCachePolicy(prefill_chunk=args.chunk),
        metrics_every=8)
    engine.warmup()
    engine.start()
    rng = np.random.default_rng(0)
    # long prompts exceed one device's pane (the admission the sp tier
    # exists for); shorts interleave so the TTFT split is apples-to-
    # apples within one mixed-traffic run
    sizes = []
    for i in range(args.n_long + args.n_short):
        sizes.append(args.long_len if i % 2 == 0 and
                     sizes.count(args.long_len) < args.n_long
                     else args.short_len)
    assert max(sizes) > pane, (sizes, pane)
    sp_params = SamplingParams(max_new_tokens=args.max_new, ignore_eos=True)
    t0 = time.perf_counter()
    handles = [engine.submit(
        rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32), sp_params,
        block=True) for n in sizes]
    engine.run_until_idle()
    dt = time.perf_counter() - t0
    long_ttft, short_ttft, n_tokens = [], [], 0
    for h, n in zip(handles, sizes):
        assert len(h.output_ids) == args.max_new, h.finish_reason
        n_tokens += len(h.output_ids)
        s = h.summary()
        (long_ttft if n > pane else short_ttft).append(s["ttft_s"])
        assert bool(s.get("long_prompt")) == (n > pane), s
    recompiles = engine.n_recompiles
    engine.shutdown()
    return {
        "sp": args.sp, "pane": pane, "max_prompt": engine.max_prompt,
        "max_len": args.max_len, "devices": jax.device_count(),
        "n_long": len(long_ttft), "n_short": len(short_ttft),
        "ttft_long_p50": round(float(np.median(long_ttft)), 4),
        "ttft_short_p50": round(float(np.median(short_ttft)), 4),
        "recompiles": recompiles,
        "tok_s": round(n_tokens / dt, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arm", choices=("train", "serve"), required=True)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--sp", type=int, default=4)
    # train arm
    ap.add_argument("--ctx", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=3)
    # serve arm
    ap.add_argument("--max_len", type=int, default=512)
    ap.add_argument("--chunk", type=int, default=64)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--n_long", type=int, default=4)
    ap.add_argument("--n_short", type=int, default=8)
    ap.add_argument("--long_len", type=int, default=384)
    ap.add_argument("--short_len", type=int, default=32)
    ap.add_argument("--max_new", type=int, default=16)
    args = ap.parse_args()

    from building_llm_from_scratch_tpu.serving.worker import apply_host_env

    apply_host_env(args.devices)
    out = _train(args) if args.arm == "train" else _serve(args)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
