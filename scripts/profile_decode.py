"""Trace the KV-cache decode loop on the real chip and print the HLO-op
breakdown (round-5 VERDICT #3: decode at 22% of the weight-stream
roofline — find the other 78%).

  python scripts/profile_decode.py [--parse]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUTDIR = "/tmp/prof_decode"


def trace():
    import jax
    import numpy as np

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import generate
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.utils.seeding import (
        configure_default_prng,
    )

    configure_default_prng()
    cfg = get_config("GPT2", "124M", dtype="bf16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = np.arange(32, dtype=np.int32)[None].repeat(8, 0)
    kw = dict(max_new_tokens=256, context_size=cfg.context_length)
    generate(params, cfg, prompt, **kw)          # compile + warm
    jax.profiler.start_trace(OUTDIR)
    generate(params, cfg, prompt, **kw)
    jax.profiler.stop_trace()
    print("trace written", flush=True)


if __name__ == "__main__":
    if "--parse" not in sys.argv:
        trace()
    from profile_xplane import parse

    parse(OUTDIR, top=40)
