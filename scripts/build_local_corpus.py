"""Assemble a real offline training corpus from text available on disk.

This environment has zero network egress, so the Gutenberg download
(datasets/gutenberg.py `download_archive`) cannot run; the packing side of
that pipeline is reused verbatim here over the ~500MB of English prose and
source text shipped with the Python installation — a genuine (if unusual)
corpus for the convergence runs recorded in RESULTS.md.

  python scripts/build_local_corpus.py [out_dir] [max_mb]
"""

import os
import sys

from building_llm_from_scratch_tpu.datasets.gutenberg import (
    is_english,
    pack_files,
)

ROOTS = [
    "/opt/venv/lib/python3.12/site-packages",
    "/usr/local/lib/python3.12",
]
EXTS = (".py", ".md", ".rst", ".txt")


def collect(max_bytes: int):
    out, total = [], 0
    for root in ROOTS:
        for dirpath, dirs, files in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            for f in sorted(files):
                if not f.endswith(EXTS):
                    continue
                p = os.path.join(dirpath, f)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    continue
                if size < 512:
                    continue
                out.append(p)
                total += size
                if total >= max_bytes:
                    return out, total
    return out, total


def main(argv):
    out_dir = argv[1] if len(argv) > 1 else "data_local/corpus"
    max_mb = int(argv[2]) if len(argv) > 2 else 400
    files, total = collect(max_mb * 1_000_000)
    print(f"collected {len(files)} files, {total / 1e6:.0f} MB")
    # pack through the Gutenberg pipeline (ASCII-ratio English filter +
    # <|endoftext|>-joined <=500MB shards, datasets/gutenberg.py)
    os.makedirs(out_dir, exist_ok=True)
    n = pack_files(files, out_dir, max_size_mb=100)
    for i in range(1, n + 1):
        p = os.path.join(out_dir, f"combined_{i}.txt")
        print("wrote", p, f"{os.path.getsize(p) / 1e6:.0f} MB")


if __name__ == "__main__":
    main(sys.argv)
