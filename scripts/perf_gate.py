"""Perf gate: compare a fresh bench run against the checked-in
PERF_BASELINE.json — the CI tripwire that makes perf claims STAY proven.

Two modes:

  - **structural** (default; deterministic on the shared CPU container,
    so CI-safe): the fresh run's structural fingerprint — per-program HLO
    cost-analysis FLOPs, compiled-program count, argument signatures,
    recompile count, HBM breakdown — must match the baseline EXACTLY.
    Timing never enters the comparison, so a noisy neighbor can't flake
    the gate, but a forced recompile, a new compiled program, or FLOP
    growth in the step fails it with the offending program NAMED.
  - **timing** (``--timing``; opt-in, for humans on quiet machines):
    variance-aware comparison of the headline value — fires only when
    the fresh median falls past a noise floor derived from both arms'
    repeat stddev (obs/perf.compare_timing).

On failure the gate prints a differential diagnosis: per-program FLOP
deltas, new/removed programs, memory deltas, and — when both arms have
metrics JSONLs — the step-timeline / tick-phase / latency delta view from
``summarize_metrics.py --compare``. Exit status 1.

Baseline updates require a reason (mirroring analysis/baseline.json's
accepted-debt discipline): a perf baseline is a CLAIM about what the
code compiles to, and changing it is a reviewed decision, never a
silent refresh.

Usage:
  python scripts/perf_gate.py                     # structural gate (CI)
  python scripts/perf_gate.py --timing            # + timing comparison
  python scripts/perf_gate.py --benches micro_train,micro_serve
  python scripts/perf_gate.py --update-baseline --reason "why it changed"
  python scripts/perf_gate.py --report            # perf trajectory table
  python scripts/perf_gate.py --backfill          # BENCH_r0N.json -> results/perf/
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

# The gate benches run IN-PROCESS, and micro_longctx needs a multi-
# device host for its seq mesh axis — force 8 CPU devices before any
# jax import (same count the tests and the fleet workers pin; the
# structural fingerprints are device-count-insensitive for the
# single-device benches, and the baseline env records 8).
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
# summarize_metrics (the telemetry-diff view) lives next to this script;
# make it importable when perf_gate is imported as a module (tests)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))


def _load_perf(pure: bool = False):
    """Handle on obs/perf.py. ``pure=True`` loads it by FILE PATH —
    stdlib-only, skipping obs/__init__ and therefore jax (the
    analysis.base.load_schema_module pattern) — for the report/backfill
    paths, which only read/write JSONL. The gate paths import the
    package module instead: they run benches, whose BenchResult objects
    must share class identity with the module comparing them."""
    if pure:
        import importlib.util

        path = os.path.join(REPO_ROOT, "building_llm_from_scratch_tpu",
                            "obs", "perf.py")
        spec = importlib.util.spec_from_file_location("_bllm_perf_pure",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        # dataclass processing resolves the module through sys.modules
        # (PEP 563 string annotations) — register before exec
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        return mod
    from building_llm_from_scratch_tpu.obs import perf

    return perf

BASELINE_PATH = os.path.join(REPO_ROOT, "PERF_BASELINE.json")
BASELINE_JSONL_DIR = os.path.join(REPO_ROOT, "results", "perf", "baseline")

#: The default gate benches: debug-size workloads that finish in seconds
#: on CPU (bench.py MICRO_BENCHES). One raw train step, one grad-accum
#: step, one continuous-batching engine run, one fused multi-LoRA step,
#: one speculative (k=4 verify) engine run, one fleet-router run —
#: together they fingerprint the train step builder, the serving
#: engine's whole program family (plain decode AND spec verify tiers),
#: the fused-finetune step, the router path's PER-REPLICA program
#: family (watch_compiles="first": replica-count invariant), and the
#: sequence-sharded ring-attention train step (micro_longctx — the
#: long-context tier, needing the forced 8-device host above).
GATE_BENCHES = ("micro_train", "micro_accum", "micro_serve",
                "micro_paged", "micro_lora_fusion", "micro_spec",
                "micro_router", "micro_longctx")

#: Env fields whose drift invalidates structural comparability (a
#: different XLA counts different FLOPs) — reported, not silently eaten.
ENV_COMPARE_KEYS = ("jax_version", "backend", "device_kind", "device_count")


def load_baseline(path):
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def run_fresh(names, repeats, jsonl_dir):
    """Run the gate benches in-process; returns {name: (BenchResult,
    metrics_jsonl_path)}. Each bench gets its own metrics JSONL so the
    failure diagnosis can diff telemetry against the baseline arm's."""
    import bench  # repo-root module (sys.path[0] is scripts/, [1] repo)

    from building_llm_from_scratch_tpu.obs.metrics import configure_metrics
    from building_llm_from_scratch_tpu.utils.seeding import (
        configure_default_prng,
    )

    configure_default_prng()
    out = {}
    for name in names:
        arm_jsonl = os.path.join(jsonl_dir, f"{name}.jsonl")
        configure_metrics(arm_jsonl, run_metadata={
            "bench": name, "perf_gate": True, "repeats": repeats})
        try:
            res = bench.run_bench(name, repeats=repeats, quick=True)
        finally:
            configure_metrics(None)
        out[name] = (res, arm_jsonl)
    return out


def env_drift(base_env, fresh_env):
    drift = []
    for key in ENV_COMPARE_KEYS:
        a, b = (base_env or {}).get(key), (fresh_env or {}).get(key)
        if a != b:
            drift.append(f"{key}: baseline {a!r} vs fresh {b!r}")
    return drift


def print_diagnosis(name, findings, base_entry, fresh_jsonl):
    print(f"\n!! perf gate FAILED: {name} — {len(findings)} structural/"
          "timing finding(s)")
    for f in findings:
        print(f"   [{f['kind']}] {f['detail']}")
    base_jsonl = base_entry.get("metrics_jsonl")
    if base_jsonl:
        base_jsonl = os.path.join(REPO_ROOT, base_jsonl)
    if base_jsonl and os.path.exists(base_jsonl) and fresh_jsonl \
            and os.path.exists(fresh_jsonl):
        # the A/B telemetry diff (summarize_metrics.py --compare): step-
        # timeline segments, engine tick phases, latency percentiles —
        # WHERE the regression lives, not just that it exists
        try:
            import summarize_metrics

            print(f"\n-- telemetry diff (A=baseline, B=fresh) for "
                  f"{name} --")
            summarize_metrics.compare_runs(base_jsonl, fresh_jsonl)
        except Exception as e:
            print(f"   (telemetry diff unavailable: {e})")
    print(f"\nIf this change is INTENDED, re-baseline with a reason:\n"
          f"  python scripts/perf_gate.py --update-baseline "
          f"--benches {name} --reason \"<why the structure changed>\"")


def _unknown_benches(names):
    """Names the baseline knows but bench.py no longer does (a renamed/
    removed bench without a re-baseline) — refuse cleanly, never
    KeyError mid-run."""
    import bench

    return [n for n in names if n not in bench.BENCHES]


def cmd_gate(args):
    perf = _load_perf()
    baseline = load_baseline(args.baseline)
    if baseline is None:
        print(f"no baseline at {args.baseline}; create one with "
              "--update-baseline --reason \"initial baseline\"")
        return 2
    names = (args.benches.split(",") if args.benches
             else sorted(baseline.get("benches", {})))
    missing = [n for n in names if n not in baseline.get("benches", {})]
    if missing:
        print(f"bench(es) {missing} not in the baseline "
              f"({sorted(baseline.get('benches', {}))}); re-baseline them "
              "first")
        return 2
    unknown = _unknown_benches(names)
    if unknown:
        print(f"bench(es) {unknown} are in the baseline but not in "
              "bench.BENCHES — a renamed/removed bench needs its "
              "baseline entry updated (--update-baseline --reason …) "
              "or pruned")
        return 2
    jsonl_dir = tempfile.mkdtemp(prefix="perf_gate_")
    try:
        return _gate_over(args, perf, baseline, names, jsonl_dir)
    finally:
        # keep the fresh arms' telemetry ONLY when the gate failed (the
        # diagnosis prints their paths); green runs must not leak a
        # /tmp/perf_gate_* dir per invocation
        if os.path.isdir(jsonl_dir) and not getattr(
                args, "_gate_failed", False):
            shutil.rmtree(jsonl_dir, ignore_errors=True)


def _gate_over(args, perf, baseline, names, jsonl_dir):
    fresh = run_fresh(names, args.repeats, jsonl_dir)
    fresh_env = perf.bench_env()
    rc = 0
    for name in names:
        res, arm_jsonl = fresh[name]
        entry = baseline["benches"][name]
        # env recorded PER BENCH (a --benches subset re-baseline must
        # not claim a new environment for entries measured in the old)
        drift = env_drift(entry.get("env") or baseline.get("env"),
                          fresh_env)
        if drift:
            print(f"note: environment drift vs the '{name}' baseline — "
                  "structural mismatches may be environmental, not "
                  "regressions:")
            for d in drift:
                print(f"   {d}")
        findings = perf.compare_structural(entry.get("fingerprint"),
                                           res.fingerprint)
        if args.timing:
            t = perf.compare_timing(entry.get("timing", {}), res.to_row(),
                                    sigma=args.sigma,
                                    floor_frac=args.floor_frac)
            if t:
                findings.append(t)
        if findings:
            rc = 1
            args._gate_failed = True      # cmd_gate keeps jsonl_dir
            print_diagnosis(name, findings, entry, arm_jsonl)
        else:
            fp = res.fingerprint or {}
            print(f"perf gate ok: {name} — {fp.get('n_programs', 0)} "
                  f"program(s), {fp.get('n_recompiles', 0)} recompiles, "
                  f"structural fingerprint matches"
                  + (f"; median {res.repeats['median']:.1f} {res.unit} "
                     f"(baseline {entry.get('timing', {}).get('value')})"
                     if args.timing and res.repeats else ""))
        if args.record:
            store = perf.TrajectoryStore(
                os.path.join(REPO_ROOT, "results", "perf"))
            store.append(res)
    return rc


def cmd_update_baseline(args):
    perf = _load_perf()
    if not args.reason or not args.reason.strip():
        print("refusing to update the baseline without --reason: the perf "
              "baseline is a reviewed claim (analysis/baseline.json "
              "discipline), not a snapshot")
        return 2
    names = (args.benches.split(",") if args.benches else list(GATE_BENCHES))
    unknown = _unknown_benches(names)
    if unknown:
        print(f"bench(es) {unknown} not in bench.BENCHES "
              "(nothing to measure)")
        return 2
    baseline = load_baseline(args.baseline) or {
        "comment": "Perf-observatory baseline (scripts/perf_gate.py): "
                   "structural HLO fingerprints + timing medians for the "
                   "gate benches. Every update carries a reason — "
                   "changing what the code compiles to is a reviewed "
                   "decision.",
        "benches": {}, "updates": []}
    os.makedirs(BASELINE_JSONL_DIR, exist_ok=True)
    jsonl_dir = tempfile.mkdtemp(prefix="perf_baseline_")
    try:
        fresh = run_fresh(names, max(args.repeats, 2), jsonl_dir)
    except Exception:
        shutil.rmtree(jsonl_dir, ignore_errors=True)
        raise
    env = perf.bench_env()
    for name in names:
        res, arm_jsonl = fresh[name]
        # through BASELINE_JSONL_DIR, never a hardcoded repo path: the
        # test suite monkeypatches the dir at a tmp location, and the
        # hardcoded join made its --update-baseline e2e rewrite the
        # COMMITTED arm files on every test run
        dst = os.path.join(BASELINE_JSONL_DIR, f"{name}.jsonl")
        rel_jsonl = os.path.relpath(dst, REPO_ROOT)
        shutil.copyfile(arm_jsonl, dst)
        baseline["benches"][name] = {
            "metric": res.metric,
            "fingerprint": perf.structural_part(res.fingerprint),
            "timing": {"value": round(res.value, 4), "unit": res.unit,
                       "repeats": res.repeats},
            "metrics_jsonl": rel_jsonl,
            # per-bench env: a --benches subset update must not claim a
            # new environment for the entries it did NOT re-measure
            "env": env,
        }
        fp = res.fingerprint or {}
        print(f"baselined {name}: {fp.get('n_programs', 0)} program(s), "
              f"median {res.repeats['median']:.1f} {res.unit}")
    baseline["env"] = env
    baseline["updates"] = (baseline.get("updates") or []) + [{
        "time": time.strftime("%Y-%m-%d %H:%M:%S"),
        "reason": args.reason.strip(),
        "benches": names,
        "git_sha": env.get("git_sha"),
    }]
    with open(args.baseline, "w") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"baseline written to {args.baseline} "
          f"(reason: {args.reason.strip()})")
    shutil.rmtree(jsonl_dir, ignore_errors=True)   # arms already copied
    return 0


def cmd_report(args):
    # pure file-path load: --report/--backfill only read/write JSONL and
    # must work (fast) without jax or the accelerator stack
    perf = _load_perf(pure=True)
    store = perf.TrajectoryStore(os.path.join(REPO_ROOT, "results", "perf"))
    if args.backfill:
        added = perf.backfill_bench_history(REPO_ROOT, store)
        print(f"backfilled {added} row(s) from BENCH_r*.json into "
              f"{store.root}")
    if args.report:
        perf.render_trajectory(store)
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--baseline", default=BASELINE_PATH,
                   help="baseline JSON path (default: PERF_BASELINE.json)")
    p.add_argument("--benches", default=None,
                   help="comma-separated bench subset (default: every "
                        "bench in the baseline; for --update-baseline: "
                        f"{','.join(GATE_BENCHES)})")
    p.add_argument("--repeats", type=int, default=1,
                   help="repeats per bench (timing mode wants >=2 for a "
                        "real stddev; --update-baseline enforces >=2)")
    p.add_argument("--timing", action="store_true",
                   help="ALSO compare the headline value against the "
                        "baseline median (variance-aware; off in CI — "
                        "the shared container's clock is noise)")
    p.add_argument("--sigma", type=float, default=4.0,
                   help="timing noise floor: sigma * combined stddev")
    p.add_argument("--floor-frac", type=float, default=0.10,
                   help="timing noise floor: at least this fraction of "
                        "the baseline median")
    p.add_argument("--record", action="store_true",
                   help="append fresh results to results/perf/*.jsonl "
                        "(the trajectory store)")
    p.add_argument("--update-baseline", action="store_true",
                   help="re-measure and rewrite the baseline (REQUIRES "
                        "--reason)")
    p.add_argument("--reason", default=None,
                   help="why the baseline legitimately changed")
    p.add_argument("--report", action="store_true",
                   help="print the perf trajectory table "
                        "(results/perf/*.jsonl) and exit")
    p.add_argument("--backfill", action="store_true",
                   help="backfill BENCH_r0N.json snapshots into the "
                        "trajectory store and exit")
    args = p.parse_args(argv)
    if args.report or args.backfill:
        return cmd_report(args)
    if args.update_baseline:
        return cmd_update_baseline(args)
    return cmd_gate(args)


if __name__ == "__main__":
    sys.exit(main())
