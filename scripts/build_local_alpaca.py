"""Synthesize an offline Alpaca-FORMAT instruction dataset.

Zero network egress means the real tatsu-lab alpaca_data.json
(datasets/alpaca.py) cannot download, so the SFT convergence run in
RESULTS.md uses deterministic string-manipulation tasks in the exact
Alpaca schema ({"instruction", "input", "output"}). The tasks are chosen
so a byte-level model can visibly LEARN them (reverse/uppercase/repeat):
before-SFT samples are garbage, after-SFT samples follow the instruction —
the observable the reference's own SFT runs produce.

  python scripts/build_local_alpaca.py [out.json] [n_examples]
"""

import json
import os
import random
import sys

WORDS = [
    "tensor", "kernel", "gradient", "shard", "lattice", "vector", "matrix",
    "python", "compile", "buffer", "stream", "socket", "thread", "object",
    "module", "string", "number", "window", "branch", "commit", "memory",
    "device", "driver", "packet", "signal", "record", "column", "schema",
]

TASKS = [
    ("Reverse the given word.", lambda w: w[::-1]),
    ("Convert the given word to uppercase.", lambda w: w.upper()),
    ("Repeat the given word twice, separated by a space.",
     lambda w: f"{w} {w}"),
    ("Output the first three letters of the given word.", lambda w: w[:3]),
]


def main(argv):
    out_path = argv[1] if len(argv) > 1 else "data_local/alpaca/alpaca_local.json"
    n = int(argv[2]) if len(argv) > 2 else 2000
    rng = random.Random(0)
    data = []
    for _ in range(n):
        instr, fn = rng.choice(TASKS)
        w = rng.choice(WORDS)
        data.append({"instruction": instr, "input": w, "output": fn(w)})
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(data, f, indent=1)
    print(f"wrote {len(data)} examples to {out_path}")


if __name__ == "__main__":
    main(sys.argv)
