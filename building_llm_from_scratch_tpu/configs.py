"""Model configuration registry for the TPU-native framework.

Capability parity with the reference's config system:
  - GPT-2 size table       (reference: Models/GPT2/config.py:30-35)
  - LLaMA family configs   (reference: Models/Llama/config.py:8-91)
  - context-length clamp with RoPE theta rescaling
                           (reference: Models/Llama/config.py:117-124,
                            Models/Llama/common_components.py:38-51)
  - dtype injection + debug tiny-model override
                           (reference: build_components.py:67-80)

Unlike the reference (per-model config dicts consumed by three near-duplicate
model classes), every architecture here is a single frozen ``ModelConfig``
consumed by ONE shared transformer implementation
(models/transformer.py). The dataclass is hashable so it can be a static
argument to ``jax.jit``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

# ---------------------------------------------------------------------------
# dtype mapping (reference: utils.py:30-41)
# ---------------------------------------------------------------------------

DTYPE_MAP = {
    "fp32": jnp.float32,
    "fp16": jnp.float16,
    "bf16": jnp.bfloat16,
}

DTYPE_BYTES = {"fp32": 4, "fp16": 2, "bf16": 2}


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """LLaMA-3.1-style RoPE frequency smoothing parameters.

    Mirrors the ``rope_freq`` dicts of the reference
    (Models/Llama/config.py:43-48,63-68) as a hashable dataclass.
    """

    factor: float
    low_freq_factor: float
    high_freq_factor: float
    original_context_length: int


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """A single architecture description covering GPT-2 and all LLaMA variants.

    The reference implements three near-duplicate attention/block/model stacks
    (Models/GPT2/GPT2.py:6, Models/Llama/Llama2.py:61, Models/Llama/Llama3.py:108);
    here the differences collapse into data:

      norm        'layernorm' (GPT-2) | 'rmsnorm' (LLaMA)
      positional  'learned'   (GPT-2) | 'rope'    (LLaMA)
      activation  'gelu'      (GPT-2) | 'swiglu'  (LLaMA)
      n_kv_groups n_heads == MHA (GPT-2, LLaMA-2) | < n_heads == GQA (LLaMA-3)
    """

    name: str
    vocab_size: int
    context_length: int
    emb_dim: int
    n_heads: int
    n_layers: int
    hidden_dim: int                      # FFN hidden width
    n_kv_groups: int                     # == n_heads for full MHA
    norm: str = "layernorm"              # 'layernorm' | 'rmsnorm'
    positional: str = "learned"          # 'learned' | 'rope'
    activation: str = "gelu"             # 'gelu' | 'swiglu'
    qkv_bias: bool = False               # GPT-2 --load_weights sets True
    attn_out_bias: bool = False          # GPT-2 uses biased out-proj
    mlp_bias: bool = False               # GPT-2 uses biased MLP linears
    norm_bias: bool = False              # LayerNorm bias (GPT-2)
    rope_base: float = 10_000.0
    rope_scaling: Optional[RopeScaling] = None
    drop_rate: float = 0.0
    eos_id: int = 50256
    eos_text: str = "<|endoftext|>"
    dtype: str = "fp32"                  # params + activations
    rmsnorm_eps: float = 1e-5
    layernorm_eps: float = 1e-5
    use_actv_ckpt: bool = False          # jax.remat on the scanned block body
    attn_impl: str = "auto"              # 'auto' | 'xla' | 'pallas'

    @property
    def head_dim(self) -> int:
        return self.emb_dim // self.n_heads

    @property
    def jax_dtype(self):
        return DTYPE_MAP[self.dtype]

    @property
    def uses_rope(self) -> bool:
        return self.positional == "rope"

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)

    def num_params(self, exclude_embeddings: bool = False) -> int:
        """Analytic parameter count (used for memory estimates, parity with
        reference utils.py:112-129 which counts live tensors)."""
        d, v, t = self.emb_dim, self.vocab_size, self.context_length
        hd, nh, nkv, f = self.head_dim, self.n_heads, self.n_kv_groups, self.hidden_dim
        emb = v * d + (t * d if self.positional == "learned" else 0)
        qkv = d * (nh * hd) + 2 * d * (nkv * hd)
        if self.qkv_bias:
            qkv += nh * hd + 2 * nkv * hd
        attn_out = (nh * hd) * d + (d if self.attn_out_bias else 0)
        if self.activation == "swiglu":
            mlp = 3 * d * f
        else:
            mlp = 2 * d * f + ((f + d) if self.mlp_bias else 0)
        norm_w = d * (2 if self.norm_bias else 1)
        per_layer = qkv + attn_out + mlp + 2 * norm_w
        final_norm = d * (2 if self.norm_bias else 1)
        head = d * v
        total = per_layer * self.n_layers + final_norm + head
        if not exclude_embeddings:
            total += emb
        return total


# ---------------------------------------------------------------------------
# RoPE theta rescale (reference: Models/Llama/common_components.py:38-51)
# ---------------------------------------------------------------------------

def rescale_theta(theta_old: float, context_length_old: int,
                  context_length_new: int) -> float:
    """Linearly rescale RoPE base frequency when the context length changes."""
    return theta_old * (context_length_new / context_length_old)


# ---------------------------------------------------------------------------
# GPT-2 registry (reference: Models/GPT2/config.py:6-35)
# ---------------------------------------------------------------------------

def _gpt2(name: str, emb_dim: int, n_heads: int, n_layers: int) -> ModelConfig:
    return ModelConfig(
        name=name,
        vocab_size=50257,
        context_length=1024,
        emb_dim=emb_dim,
        n_heads=n_heads,
        n_layers=n_layers,
        hidden_dim=4 * emb_dim,
        n_kv_groups=n_heads,
        norm="layernorm",
        positional="learned",
        activation="gelu",
        qkv_bias=False,
        attn_out_bias=True,
        mlp_bias=True,
        norm_bias=True,
        drop_rate=0.1,
        eos_id=50256,
        eos_text="<|endoftext|>",
    )


GPT2_CONFIGS = {
    "124M": _gpt2("gpt2-124M", 768, 12, 12),
    "355M": _gpt2("gpt2-355M", 1024, 16, 24),
    "774M": _gpt2("gpt2-774M", 1280, 20, 36),
    "1.5B": _gpt2("gpt2-1.5B", 1600, 25, 48),
}


# ---------------------------------------------------------------------------
# LLaMA registry (reference: Models/Llama/config.py:8-91)
# ---------------------------------------------------------------------------
# NOTE (reference defect §2.3 #4): LLAMA2_CONFIG_7B has no eos_id/eos_text in
# the reference even though the trainer requires both. We supply LLaMA-2's
# actual sentencepiece ids (eos=2, '</s>') so the llama2 path works.

LLAMA2_CONFIG_7B = ModelConfig(
    name="llama2-7B",
    vocab_size=32_000,
    context_length=4096,
    emb_dim=4096,
    n_heads=32,
    n_layers=32,
    hidden_dim=11_008,
    n_kv_groups=32,                      # full MHA
    norm="rmsnorm",
    positional="rope",
    activation="swiglu",
    rope_base=10_000.0,
    eos_id=2,
    eos_text="</s>",
    dtype="bf16",
)

LLAMA3_CONFIG_8B = ModelConfig(
    name="llama3-8B",
    vocab_size=128_256,
    context_length=8192,
    emb_dim=4096,
    n_heads=32,
    n_layers=32,
    hidden_dim=14_336,
    n_kv_groups=8,
    norm="rmsnorm",
    positional="rope",
    activation="swiglu",
    rope_base=500_000.0,
    eos_id=128_001,
    eos_text="<|end_of_text|>",
    dtype="bf16",
)

LLAMA31_CONFIG_8B = LLAMA3_CONFIG_8B.replace(
    name="llama3_1-8B",
    context_length=131_072,
    rope_scaling=RopeScaling(
        factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
        original_context_length=8192,
    ),
)

LLAMA32_CONFIG_1B = ModelConfig(
    name="llama3_2-1B",
    vocab_size=128_256,
    context_length=131_072,
    emb_dim=2048,
    n_heads=32,
    n_layers=16,
    hidden_dim=8192,
    n_kv_groups=8,
    norm="rmsnorm",
    positional="rope",
    activation="swiglu",
    rope_base=500_000.0,
    rope_scaling=RopeScaling(
        factor=32.0, low_freq_factor=1.0, high_freq_factor=4.0,
        original_context_length=8192,
    ),
    eos_id=128_001,
    eos_text="<|end_of_text|>",
    dtype="bf16",
)


# The long-context pretrain tier (PR 20): a ~350M GQA model whose
# NATIVE context is 32k — not a clamped-down big model. Sized so the
# sequence dimension dominates activation memory (seq 32768 >> emb
# 1024), which is exactly the regime sequence-parallel training
# (--sp, ops/ring_attention.py) exists for: one device cannot hold a
# 32k activation pane, sp shards it. rope_base 500k follows the
# llama3 long-context recipe; no rope_scaling because 32k IS the
# training context, not an extension of a shorter one. Train it with
# ``--model longctx --num_params 32k --target_context_length 0`` (0
# keeps the native 32k) or via ``bench.py pretrain_longctx``.
LONGCTX_CONFIG_32K = ModelConfig(
    name="longctx-32k",
    vocab_size=50_257,
    context_length=32_768,
    emb_dim=1024,
    n_heads=16,
    n_layers=24,
    hidden_dim=4096,
    n_kv_groups=4,
    norm="rmsnorm",
    positional="rope",
    activation="swiglu",
    rope_base=500_000.0,
    eos_id=50_256,
    eos_text="<|endoftext|>",
    dtype="bf16",
)


# Supported model types and their sizes (reference: utils.py:44-50)
MODEL_PARAMS_MAPPING = {
    "GPT2": ["124M", "355M", "774M", "1.5B"],
    "llama2": ["7B"],
    "llama3": ["8B"],
    "llama3_1": ["8B"],
    "llama3_2": ["1B"],
    "longctx": ["32k"],
}

_LLAMA_REGISTRY = {
    ("llama2", "7B"): LLAMA2_CONFIG_7B,
    ("llama3", "8B"): LLAMA3_CONFIG_8B,
    ("llama3_1", "8B"): LLAMA31_CONFIG_8B,
    ("llama3_2", "1B"): LLAMA32_CONFIG_1B,
    ("longctx", "32k"): LONGCTX_CONFIG_32K,
}


def get_config_gpt2(num_params: str) -> ModelConfig:
    """Reference: Models/GPT2/config.py:38-50."""
    num_params = str(num_params)
    if num_params not in GPT2_CONFIGS:
        raise ValueError(
            f"GPT-2 config for model '{num_params}' not found. "
            f"Available options: {list(GPT2_CONFIGS.keys())}"
        )
    return GPT2_CONFIGS[num_params]


def get_config_llama(num_params: str, model_name: str,
                     target_context_length: Optional[int] = 1024) -> ModelConfig:
    """Look up a LLaMA config, optionally clamping context length.

    Reference (Models/Llama/config.py:97-126) force-downscales every LLaMA
    context to 1024 with a linear theta rescale; we reproduce that default but
    make it parameterizable (pass ``None`` to keep the native context), and we
    do NOT mutate a shared registry entry (reference defect §2.3 #5).
    """
    key = (model_name, str(num_params))
    if key not in _LLAMA_REGISTRY:
        raise ValueError(
            f"A {model_name} model with {num_params} parameters does not exist."
        )
    cfg = _LLAMA_REGISTRY[key]
    if target_context_length and cfg.context_length != target_context_length:
        cfg = cfg.replace(
            rope_base=rescale_theta(cfg.rope_base, cfg.context_length,
                                    target_context_length),
            context_length=target_context_length,
        )
    return cfg


def get_config(model: str, num_params: str, *,
               dtype: Optional[str] = None,
               qkv_bias: Optional[bool] = None,
               use_actv_ckpt: bool = False,
               debug: bool = False,
               target_context_length: Optional[int] = 1024) -> ModelConfig:
    """Unified config builder (reference: build_components.py:50-82).

    Applies dtype injection (build_components.py:67), qkv_bias override used
    when loading GPT-2 HF weights (build_components.py:69-70), and the
    ``--debug`` tiny-model shrink (build_components.py:72-80).
    """
    if model == "GPT2":
        cfg = get_config_gpt2(num_params)
    else:
        cfg = get_config_llama(num_params, model,
                               target_context_length=target_context_length)
    if dtype is not None:
        cfg = cfg.replace(dtype=dtype)
    if qkv_bias is not None:
        cfg = cfg.replace(qkv_bias=qkv_bias)
    if use_actv_ckpt:
        cfg = cfg.replace(use_actv_ckpt=True)
    if debug:
        # Tiny-model override (reference build_components.py:72-80: ctx 10,
        # emb 32, 2 layers, 2 heads). We keep head_dim even for RoPE.
        cfg = cfg.replace(
            context_length=16,
            emb_dim=32,
            n_layers=2,
            n_heads=2,
            n_kv_groups=min(cfg.n_kv_groups, 2),
            hidden_dim=64,
        )
    return cfg


def get_model_config(model: str, num_params: str, **kw) -> ModelConfig:
    """Alias kept for API discoverability."""
    return get_config(model, num_params, **kw)
