"""The sharding rule table: parallelism strategies as data.

The reference implements its strategies as three separate wrapper code
paths — DDP wrap (build_components.py:176), FSDP wrap with a module
wrap-policy (build_components.py:154-174), and ZeroRedundancyOptimizer
(build_components.py:250-256). Here each strategy is a table of
``PartitionSpec`` rules applied to the SAME pytrees; XLA's GSPMD partitioner
inserts the collectives the torch wrappers hand-code:

  mode     params            optimizer state      batch       collectives XLA inserts
  ----     ------            ---------------      -----       ------------------------
  dp       replicated        replicated           data-axis   grad psum (≡ DDP all-reduce)
  fsdp     sharded on data   sharded on data      data-axis   param all-gather fwd/bwd +
                                                              grad reduce-scatter (≡ FSDP)
  zero1    replicated        sharded on data      data-axis   grad psum + state scatter/
                                                              gather (≡ ZeRO-1)
  tp       attn/mlp heads    follows params       data-axis   activation psums
           on model axis                                      (Megatron-style)

FSDP sharding rule: shard the LARGEST non-layer axis divisible by the mesh
size — the spec-level equivalent of the reference's
``ModuleWrapPolicy([nn.Embedding, TransformerBlock])`` granularity
(build_components.py:172), except every tensor shards (no wrap-policy
special cases). Stacked layer params (L, in, out) never shard the scan axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from building_llm_from_scratch_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    make_mesh,
)

Params = Dict[str, Any]

SHARD_MODES = ("dp", "fsdp", "zero1", "tp", "tp_fsdp")

# Megatron-style tensor-parallel rules: path suffix -> axis index to shard
# on the model axis, expressed on the UNSTACKED (per-layer) shape; block
# params carry a leading scan axis at runtime, handled in param_spec.
# Column-parallel (shard output) for QKV/up/gate, row-parallel (shard
# input) for the output projections; vocab-parallel embedding + head.
_TP_RULES: Dict[Tuple[str, ...], int] = {
    ("blocks", "attn", "wq"): 1,      # (D, H*hd) -> shard heads
    ("blocks", "attn", "wk"): 1,
    ("blocks", "attn", "wv"): 1,
    ("blocks", "attn", "bq"): 0,
    ("blocks", "attn", "bk"): 0,
    ("blocks", "attn", "bv"): 0,
    ("blocks", "attn", "wo"): 0,      # (H*hd, D) -> shard input
    ("blocks", "mlp", "up"): 1,
    ("blocks", "mlp", "gate"): 1,
    ("blocks", "mlp", "b_up"): 0,
    ("blocks", "mlp", "down"): 0,
    ("tok_emb", "weight"): 0,         # (V, D) vocab-parallel
    ("head", "weight"): 1,            # (D, V) vocab-parallel
}


def _path_names(path) -> Tuple[str, ...]:
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "name"):
            names.append(str(p.name))
        # skip positional (namedtuple/sequence) entries — optimizer state
        # wraps the param tree in GradientTransformation state tuples
    return tuple(names)


def _fsdp_axis(shape: Tuple[int, ...], n_shards: int,
               skip_leading_layer_axis: bool,
               exclude: Optional[int] = None) -> Optional[int]:
    """Pick the largest axis divisible by ``n_shards`` (None -> replicate),
    optionally excluding an axis already claimed by tensor parallelism."""
    if not shape:
        return None
    start = 1 if (skip_leading_layer_axis and len(shape) >= 2) else 0
    best, best_size = None, 0
    for i in range(start, len(shape)):
        if i == exclude:
            continue
        if shape[i] % n_shards == 0 and shape[i] >= n_shards \
                and shape[i] > best_size:
            best, best_size = i, shape[i]
    return best


def _spec_with_axis(ndim: int, axis: Optional[int], mesh_axis: str) -> P:
    if axis is None:
        return P()
    spec = [None] * ndim
    spec[axis] = mesh_axis
    return P(*spec)


@dataclasses.dataclass
class MeshPlan:
    """A mesh + shard mode; knows how to place params, optimizer state and
    batches. This object REPLACES the reference's multigpu_setup
    (build_components.py:142-182) and optimizer sharding wrapper."""

    mesh: Mesh
    shard_mode: str = "dp"
    # params with fewer elements than this stay replicated in fsdp modes
    # (tiny tensors cost more to gather than they save — same motivation as
    # FSDP's min_num_params wrap policies)
    fsdp_min_size: int = 1024

    def __post_init__(self):
        if self.shard_mode not in SHARD_MODES:
            raise ValueError(
                f"shard_mode '{self.shard_mode}' not in {SHARD_MODES}")

    # -- sizes ---------------------------------------------------------

    @property
    def n_data(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def n_model(self) -> int:
        return self.mesh.shape[MODEL_AXIS]

    @property
    def n_seq(self) -> int:
        return self.mesh.shape[SEQ_AXIS]

    @property
    def sp_mesh(self):
        """The mesh to hand ``forward``'s ring-attention path, or None when
        sequence parallelism is off."""
        return self.mesh if self.n_seq > 1 else None

    # -- spec rules ----------------------------------------------------

    def _is_stacked(self, names: Tuple[str, ...]) -> bool:
        return "blocks" in names

    def param_spec(self, names: Tuple[str, ...], shape: Tuple[int, ...]) -> P:
        """PartitionSpec for a model parameter leaf."""
        tp_axis = None
        if self.shard_mode in ("tp", "tp_fsdp") and self.n_model > 1:
            for suffix, ax in _TP_RULES.items():
                if names[-len(suffix):] == suffix:
                    # block tensors carry a leading scan axis at runtime
                    tp_axis = ax + 1 if self._is_stacked(names) else ax
                    if tp_axis >= len(shape) \
                            or shape[tp_axis] % self.n_model != 0:
                        tp_axis = None
                    break
        fsdp_axis = None
        if self.shard_mode in ("fsdp", "tp_fsdp") and self.n_data > 1 \
                and int(np.prod(shape)) >= self.fsdp_min_size:
            fsdp_axis = _fsdp_axis(
                shape, self.n_data,
                skip_leading_layer_axis=self._is_stacked(names),
                exclude=tp_axis)
        spec = [None] * len(shape)
        if tp_axis is not None:
            spec[tp_axis] = MODEL_AXIS
        if fsdp_axis is not None:
            spec[fsdp_axis] = DATA_AXIS
        if all(s is None for s in spec):
            return P()
        return P(*spec)

    def opt_spec(self, names: Tuple[str, ...], shape: Tuple[int, ...]) -> P:
        """PartitionSpec for an optimizer-state leaf (adam m/v mirror the
        param tree; scalars replicate)."""
        if self.shard_mode == "zero1":
            # ZeRO-1: shard ONLY optimizer state (reference
            # ZeroRedundancyOptimizer, build_components.py:250-256)
            axis = _fsdp_axis(shape, self.n_data,
                              skip_leading_layer_axis=self._is_stacked(names))
            if int(np.prod(shape)) < self.fsdp_min_size:
                axis = None
            return _spec_with_axis(len(shape), axis, DATA_AXIS)
        return self.param_spec(names, shape)

    def batch_spec(self) -> P:
        if self.n_seq > 1:
            # sequence parallelism: tokens shard over (data, seq); the
            # token-local compute follows via GSPMD, attention via the ring
            return P(DATA_AXIS, SEQ_AXIS)
        return P(DATA_AXIS)

    def cache_spec(self, shape: Tuple[int, ...]) -> P:
        """PartitionSpec for a slot-KV cache leaf (serving tier).

        Slot caches are per-layer ``(n_slots, Hkv, Tmax, hd)`` k/v panes
        (int8 policies add ``(n_slots, Hkv, Tmax, 1)`` scale sidecars —
        same rank, same rule). Under tensor parallelism the k/v
        projections are column-parallel (``_TP_RULES`` shards their
        output heads on ``model``), so the natural cache placement is
        the HEADS axis on ``model`` — appends then write each device's
        local heads with no resharding. Heads not divisible by the tp
        degree (and non-4d leaves) replicate.
        """
        if self.shard_mode in ("tp", "tp_fsdp") and self.n_model > 1 \
                and len(shape) == 4 and shape[1] % self.n_model == 0:
            return P(None, MODEL_AXIS, None, None)
        return P()

    def shard_cache(self, cache: Params) -> Params:
        """Place a slot-KV cache pytree on the mesh per ``cache_spec``."""
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, self._named(self.cache_spec(tuple(x.shape)))), cache)

    def put_replicated(self, x):
        """Place one array replicated over this plan's mesh (adapter
        pools and other small per-engine state that every shard reads)."""
        return jax.device_put(x, self._named(P()))

    # -- pytree placement ---------------------------------------------

    def _named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    @staticmethod
    def _put_fresh(x, sharding: NamedSharding):
        return put_fresh(x, sharding)

    def state_shardings(self, state: Params) -> Params:
        """Shardings for a full train state {trainable, frozen, opt_state,
        step, rng}."""
        def spec_of(path, leaf):
            names = _path_names(path)
            shape = tuple(getattr(leaf, "shape", ()))
            if not shape or not names:
                return self._named(P())
            if names[0] in ("trainable", "frozen"):
                return self._named(self.param_spec(names[1:], shape))
            if names[0] == "opt_state":
                return self._named(self.opt_spec(names[1:], shape))
            return self._named(P())

        return jax.tree_util.tree_map_with_path(spec_of, state)

    def shard_state(self, state: Params) -> Params:
        """Place a train state on the mesh, donation-safe
        (see ``place_state_donation_safe``)."""
        return place_state_donation_safe(state, self.state_shardings(state))

    def param_spec_tree(self, params: Params, root: str = "trainable"
                        ) -> Params:
        """Raw ``PartitionSpec`` tree for a params pytree (shard_map
        in/out_specs want plain specs, not NamedShardings)."""
        del root  # param_spec rules don't depend on trainable vs frozen

        def spec_of(path, leaf):
            return self.param_spec(_path_names(path),
                                   tuple(getattr(leaf, "shape", ())))

        return jax.tree_util.tree_map_with_path(spec_of, params)

    def params_shardings(self, params: Params) -> Params:
        def spec_of(path, leaf):
            return self._named(self.param_spec(
                _path_names(path), tuple(getattr(leaf, "shape", ()))))

        return jax.tree_util.tree_map_with_path(spec_of, params)

    def shard_params(self, params: Params, *, copy: bool = True) -> Params:
        """Place a params pytree on the mesh.

        ``copy=True`` (default) never aliases the caller's buffers — safe to
        feed into donating steps. Pass ``copy=False`` ONLY for freshly
        created params with no outside references (init/load paths), where
        the donation-safety copy is pure transient-HBM waste.
        """
        if not copy:
            return jax.device_put(params, self.params_shardings(params))
        return jax.tree_util.tree_map(
            self._put_fresh, params, self.params_shardings(params))

    def shard_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Place a per-process batch as a globally-sharded array.

        Single-process: a straight device_put with the data-axis sharding.
        Multi-process: each process contributes its local rows
        (``jax.make_array_from_process_local_data``), replacing the
        reference's DistributedSampler index sharding.
        """
        def put(x):
            # batch_spec covers the leading (B[, T]) dims; pad/trim to rank
            axes = (list(self.batch_spec()) + [None] * np.ndim(x))[:np.ndim(x)]
            sharding = self._named(P(*axes))
            if jax.process_count() == 1:
                return jax.device_put(x, sharding)
            return jax.make_array_from_process_local_data(sharding, x)

        return jax.tree_util.tree_map(put, batch)


def put_fresh(x, sharding: NamedSharding):
    """device_put that never aliases the caller's buffers.

    ``jax.device_put`` reuses ``x``'s existing device buffer whenever it
    can serve as (part of) the target sharding — even under
    ``may_alias=False`` (measured on jax 0.9 CPU: replicating a
    single-device array keeps the source buffer as the device-0 replica).
    A donated train step consuming such a view deletes buffers the caller
    still holds — e.g. two train states built from one params pytree, or
    ``Trainer._params`` after the first step (round-2 VERDICT weak #1).
    ``x.copy()`` severs the aliasing; host arrays always transfer fresh.
    """
    if isinstance(x, jax.Array):
        return jax.device_put(x.copy(), sharding)
    return jax.device_put(x, sharding)


def place_state_donation_safe(state: Params, shardings: Params) -> Params:
    """Place a train state onto ``shardings``, donation-safe — shared by
    MeshPlan and PipelinePlan.

    Only ``trainable``/``frozen``/``rng`` can alias buffers the caller
    still holds (``init_train_state`` stores them by reference);
    ``opt_state``/``step``/scaler leaves are freshly created there, so they
    take the plain (possibly aliasing) ``device_put`` — no wasted copy of
    the adam moments at 8B scale.
    """
    out = {}
    for key, sub in state.items():
        put = (put_fresh if key in ("trainable", "frozen", "rng")
               else jax.device_put)
        out[key] = jax.tree_util.tree_map(put, sub, shardings[key])
    return out


def build_mesh_plan(shard_mode: str = "dp", *, tp: int = 1, sp: int = 1,
                    devices=None) -> MeshPlan:
    """Convenience: mesh spanning all devices + plan for ``shard_mode``."""
    mesh = make_mesh(data=-1, seq=sp, model=tp, devices=devices)
    return MeshPlan(mesh=mesh, shard_mode=shard_mode)


def serve_mesh_plan(tp: int = 1, sp: int = 1, devices=None) -> MeshPlan:
    """A serving-replica plan: ``(data=1, seq=sp, model=tp)`` over exactly
    ``sp * tp`` devices. ``tp=1, sp=1`` pins a replica to one device (the
    router's replica-per-device layout); ``tp>1`` is the tensor-parallel
    engine (Megatron rules over the ``model`` axis, slot KV sharded on
    heads); ``sp>1`` is the long-context engine — chunk prefill runs with
    its token axis sharded over ``seq`` so one replica admits prompts
    larger than a single device's prefill pane (serving/engine.py)."""
    devices = list(devices if devices is not None else jax.devices())
    need = sp * tp
    if sp < 1 or tp < 1:
        raise ValueError(f"serve_mesh_plan needs sp >= 1 and tp >= 1 "
                         f"(got sp={sp}, tp={tp})")
    if len(devices) < need:
        raise ValueError(
            f"serve_mesh_plan(tp={tp}, sp={sp}) needs {need} devices, "
            f"have {len(devices)}")
    mesh = make_mesh(data=1, seq=sp, model=tp, devices=devices[:need])
    return MeshPlan(mesh=mesh, shard_mode="tp" if tp > 1 else "dp")


def partition_serve_devices(n_replicas: int, tp: int = 1, sp: int = 1,
                            devices=None) -> List[List[jax.Device]]:
    """Split the device pool into one device list per serving replica.

    With enough devices every replica gets a DISJOINT ``sp * tp``-device
    slice (true scale-out: replicas execute concurrently). With fewer,
    replicas round-robin over overlapping slices — correct but
    device-serialized, which is still useful for tests and single-chip
    smoke runs. ``sp * tp`` greater than the pool is an error either way."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    per = sp * tp
    if per > n:
        raise ValueError(
            f"tp={tp} x sp={sp} exceeds the {n} available devices")
    out = []
    for r in range(n_replicas):
        if n >= n_replicas * per:
            lo = r * per
        else:
            lo = (r * per) % max(n - per + 1, 1)
        out.append(devices[lo: lo + per])
    return out
