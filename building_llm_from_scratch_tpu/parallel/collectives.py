"""Collective / multi-host helpers.

Maps the reference's explicit torch.distributed calls to their TPU-native
equivalents (SURVEY.md §2.2 "Communication backend"):

  torch.distributed.barrier()        -> sync_global_devices()
  rank == 0 gating                   -> is_coordinator()
  dist.all_reduce (DDP grads)        -> implicit: GSPMD psum under jit
  FSDP all-gather / reduce-scatter   -> implicit: GSPMD from sharding specs
  FSDP FULL_STATE_DICT gather        -> gather_full(tree)

Explicit collectives (psum/all_gather/ppermute) are provided for
``shard_map`` kernels (ring attention) that hand-schedule communication.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def is_coordinator() -> bool:
    """Process-0 check (the reference's ``rank == 0`` pattern)."""
    return jax.process_index() == 0


def sync_global_devices(name: str = "barrier") -> None:
    """Cross-host barrier (reference dist.barrier, main.py:178 etc.)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def gather_full(tree: Any) -> Any:
    """Gather a (possibly sharded) pytree to full host values — the analog
    of FSDP's FULL_STATE_DICT rank-0 gather (reference train.py:244-249).

    Single-process: device_get reassembles local shards. Multi-process:
    arrays span non-addressable devices, so each leaf goes through a
    process_allgather collective first (every host ends with the full
    value, matching the reference's CPU-offload gather)."""
    import numpy as np

    multi = jax.process_count() > 1

    def gather(x):
        if not isinstance(x, jax.Array):
            return x
        if multi and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(gather, tree)


# shard_map building blocks -------------------------------------------------

def psum(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute_next(x, axis_name: str, axis_size: int):
    """Rotate shards one step around the ring (ring attention's primitive)."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)
