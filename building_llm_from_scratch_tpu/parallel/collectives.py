"""Collective / multi-host helpers.

Maps the reference's explicit torch.distributed calls to their TPU-native
equivalents (SURVEY.md §2.2 "Communication backend"):

  torch.distributed.barrier()        -> sync_global_devices()
  rank == 0 gating                   -> is_coordinator()
  dist.all_reduce (DDP grads)        -> implicit: GSPMD psum under jit
  FSDP all-gather / reduce-scatter   -> implicit: GSPMD from sharding specs
  FSDP FULL_STATE_DICT gather        -> gather_full(tree)

Explicit collectives (psum/all_gather/ppermute) are provided for
``shard_map`` kernels (ring attention) that hand-schedule communication.
"""

from __future__ import annotations

from typing import Any

import jax


def is_coordinator() -> bool:
    """Process-0 check (the reference's ``rank == 0`` pattern)."""
    return jax.process_index() == 0


def sync_global_devices(name: str = "barrier") -> None:
    """Cross-host barrier (reference dist.barrier, main.py:178 etc.)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def gather_full(tree: Any) -> Any:
    """Gather a (possibly sharded) pytree to full host values — the analog
    of FSDP's FULL_STATE_DICT rank-0 gather (reference train.py:244-249).

    Single-process: device_get reassembles local shards. Multi-process:
    arrays span non-addressable devices, so each leaf goes through a
    process_allgather collective first (every host ends with the full
    value, matching the reference's CPU-offload gather)."""
    import numpy as np

    multi = jax.process_count() > 1

    def gather(x):
        if not isinstance(x, jax.Array):
            return x
        if multi and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(gather, tree)


# shard_map building blocks -------------------------------------------------

def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: the top-level ``jax.shard_map`` alias
    (and its ``check_vma`` kwarg) only exist on newer jax; older releases
    ship ``jax.experimental.shard_map.shard_map`` with the same semantics
    under the ``check_rep`` spelling. Every shard_map in this codebase goes
    through here so a jax upgrade/downgrade never strands the explicit-
    collective paths (ring attention, pipeline, bf16_hybrid step).

    Known old-API limitation: differentiating THROUGH a shard_map whose
    out_specs include a replicated SCALAR (the pipeline loss) fails in the
    transpose on jax<0.5 with either check_rep setting (_SpecError under
    False, cond replication-mismatch under True; both fixed upstream
    alongside the alias). The pp grad-through tests carry a conditional
    xfail for it; forward/eval paths and grad-INSIDE-shard_map (ring
    attention, the explicit bf16_hybrid step) work on both APIs."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def psum(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute_next(x, axis_name: str, axis_size: int):
    """Rotate shards one step around the ring (ring attention's primitive)."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)
