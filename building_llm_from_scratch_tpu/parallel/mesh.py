"""Device mesh construction and multi-host runtime init.

Replaces the reference's process/distributed runtime (L1):
  - ``mp.spawn`` one-process-per-GPU + NCCL rendezvous on
    localhost:12355 (reference main.py:22-34,185-193) becomes
    ``jax.distributed.initialize()`` — TPU pods auto-discover peers, no
    MASTER_ADDR analog;
  - the process group IS the mesh: one ``jax.sharding.Mesh`` whose axes
    span ICI (intra-slice) and DCN (inter-slice).

Mesh axes:
  data   — batch/data parallelism AND fully-sharded params (FSDP mode)
  seq    — sequence/context parallelism (ring attention)
  model  — tensor parallelism

The reference's three strategies (DDP / FSDP / ZeRO-1) plus the TPU-first
extensions (TP, SP) are all sharding-rule tables over this one mesh
(parallel/sharding.py) — not separate wrapper code paths.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


_initialized = False


def _tpu_pod_detected() -> bool:
    """True when the environment says this host is one worker of a
    multi-host TPU slice (or a multislice job) — the situations where
    skipping ``jax.distributed.initialize()`` would silently start N
    INDEPENDENT single-host runs instead of one job (round-3 VERDICT
    weakness #5)."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):   # multislice
        return True
    return False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize the multi-host JAX runtime.

    Call order of discovery:
      1. explicit args (GPU/CPU clusters, tests);
      2. ``JAX_NUM_PROCESSES`` env (this repo's multi-process CPU tests);
      3. TPU-pod environment detection — on a pod slice
         ``jax.distributed.initialize()`` is called UNCONDITIONALLY (argless;
         peers come from the TPU metadata) so the documented "run the same
         command on every host" flow can never degrade to per-host jobs.

    Safe no-op for true single-process runs and when already initialized.
    """
    global _initialized
    if _initialized:
        return
    if num_processes is None:
        env_n = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
        if env_n > 1:
            num_processes = env_n
    if num_processes is None and coordinator_address is None:
        if _tpu_pod_detected():
            jax.distributed.initialize()   # TPU metadata supplies peers
            _initialized = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def make_mesh(data: int = -1, seq: int = 1, model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, seq, model) mesh over all devices.

    ``data=-1`` absorbs the remaining devices after seq/model are fixed —
    the common case: ``make_mesh()`` is pure data parallel over every chip.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % (seq * model) != 0:
            raise ValueError(
                f"{n} devices not divisible by seq*model={seq * model}")
        data = n // (seq * model)
    if data * seq * model != n:
        raise ValueError(
            f"mesh {data}x{seq}x{model} != {n} available devices")
    arr = np.asarray(devices).reshape(data, seq, model)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))
