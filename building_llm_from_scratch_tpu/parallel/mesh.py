"""Device mesh construction and multi-host runtime init.

Replaces the reference's process/distributed runtime (L1):
  - ``mp.spawn`` one-process-per-GPU + NCCL rendezvous on
    localhost:12355 (reference main.py:22-34,185-193) becomes
    ``jax.distributed.initialize()`` — TPU pods auto-discover peers, no
    MASTER_ADDR analog;
  - the process group IS the mesh: one ``jax.sharding.Mesh`` whose axes
    span ICI (intra-slice) and DCN (inter-slice).

Mesh axes:
  data   — batch/data parallelism AND fully-sharded params (FSDP mode)
  seq    — sequence/context parallelism (ring attention)
  model  — tensor parallelism

The reference's three strategies (DDP / FSDP / ZeRO-1) plus the TPU-first
extensions (TP, SP) are all sharding-rule tables over this one mesh
(parallel/sharding.py) — not separate wrapper code paths.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize the multi-host JAX runtime when running on >1 process.

    On TPU pods ``jax.distributed.initialize()`` discovers everything from
    the TPU metadata; explicit args cover GPU/CPU clusters. Safe no-op for
    single-process runs.
    """
    if num_processes is None:
        env_n = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
        if env_n > 1:
            num_processes = env_n
    if num_processes is None and coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(data: int = -1, seq: int = 1, model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, seq, model) mesh over all devices.

    ``data=-1`` absorbs the remaining devices after seq/model are fixed —
    the common case: ``make_mesh()`` is pure data parallel over every chip.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % (seq * model) != 0:
            raise ValueError(
                f"{n} devices not divisible by seq*model={seq * model}")
        data = n // (seq * model)
    if data * seq * model != n:
        raise ValueError(
            f"mesh {data}x{seq}x{model} != {n} available devices")
    arr = np.asarray(devices).reshape(data, seq, model)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))
