"""Parallelism tier: mesh, sharding rule tables, collectives.

Replaces the reference's L1 distributed runtime (NCCL process groups,
DDP/FSDP wrappers, ZeroRedundancyOptimizer) with one mesh + GSPMD specs.
"""

from building_llm_from_scratch_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    initialize_distributed,
    make_mesh,
)
from building_llm_from_scratch_tpu.parallel.sharding import (
    SHARD_MODES,
    MeshPlan,
    build_mesh_plan,
    partition_serve_devices,
    serve_mesh_plan,
)
from building_llm_from_scratch_tpu.parallel.pipeline import (
    PipelinePlan,
    make_pp_eval_step,
    make_pp_loss_fn,
    make_pp_mesh,
    make_pp_train_step,
)
from building_llm_from_scratch_tpu.parallel.collectives import (
    all_gather,
    gather_full,
    is_coordinator,
    ppermute_next,
    psum,
    sync_global_devices,
)

__all__ = [
    "PipelinePlan",
    "make_pp_eval_step",
    "make_pp_loss_fn",
    "make_pp_mesh",
    "make_pp_train_step",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "initialize_distributed",
    "make_mesh",
    "SHARD_MODES",
    "MeshPlan",
    "build_mesh_plan",
    "partition_serve_devices",
    "serve_mesh_plan",
    "all_gather",
    "gather_full",
    "is_coordinator",
    "ppermute_next",
    "psum",
    "sync_global_devices",
]
