"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``stage``
mesh axis.

Beyond reference parity (the reference has no pipeline story — SURVEY §2.2
lists PP as absent): the L stacked transformer blocks are split into S
contiguous stages, each stage owning L/S layers; a training batch is split
into M microbatches that flow through the stages with ``lax.ppermute``
moving activations one hop per tick. After ``M + S - 1`` ticks every
microbatch has crossed every stage; the last stage accumulates the
token-weighted loss.

The TPU-first trick: the WHOLE schedule is a differentiable ``lax.scan``
inside one ``shard_map`` — ``jax.grad`` transposes it into the reverse
pipeline automatically (the transpose of a ring ppermute is the reverse
ppermute), so forward and backward share one implementation and the
optimizer step stays the ordinary optax update. XLA overlaps each tick's
hop (ICI neighbor transfer) with the next tick's layer compute.

Embeddings/norm/head are replicated and evaluated where needed (stage 0
embeds, the last stage projects). Bubble fraction is (S-1)/(M+S-1) —
choose M >= S for efficiency. The mesh composes a data axis with the stage
axis ((data=D, stage=S), D = n_devices/S): each data column pipelines its
own microbatch rows and the loss/grads psum over both axes.

Round-4 (v2) changes, per the r3 VERDICT weakness #4:
  - ``--use_actv_ckpt`` is honored: remat of the stage body is OPT-IN.
    With it off, the scan transpose reads saved activations instead of
    recomputing every stage forward during the backward — the backward
    tick drops from (fwd+bwd) to bwd work, worth ~1.33x on the training
    step (bwd ~ 2x fwd). Remat remains the memory-bound choice: saved
    activations scale with M microbatches in flight.
  - dropout is supported (GPT-2's configs train with 0.1): each
    (microbatch, data shard, stage, layer) folds its own PRNG key, so
    masks are iid across the schedule and bit-stable under the scan
    transpose / remat replay.
  - warmup/drain ticks with no valid microbatch for a stage skip their
    compute via ``lax.cond`` (device-local; the SPMD program stays
    uniform) — this also removes the stage-0 drain-tick waste flagged by
    the r3 advisor (pipeline.py ADVICE #4).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.parallel.collectives import shard_map
from building_llm_from_scratch_tpu.models.transformer import (
    _block,
    _embed,
    _norm,
    _rope_tables,
)

Params = Dict[str, Any]

STAGE_AXIS = "stage"
DATA_AXIS = "data"
MODEL_AXIS = "model"

# Megatron rules on the per-layer block tree for pp x tp (round-5 VERDICT
# #6): axis (on the UNSTACKED per-layer shape) to shard over the model
# mesh axis. Column-parallel qkv/up/gate + their feature-sharded biases,
# row-parallel wo/down (their replicated biases are added post-psum in
# transformer._attn_out_proj/_mlp).
_PP_TP_RULES = {
    ("attn", "wq"): 1, ("attn", "wk"): 1, ("attn", "wv"): 1,
    ("attn", "bq"): 0, ("attn", "bk"): 0, ("attn", "bv"): 0,
    ("attn", "wo"): 0,
    ("mlp", "up"): 1, ("mlp", "gate"): 1, ("mlp", "b_up"): 0,
    ("mlp", "down"): 0,
}

# Ablation switch for scripts/bench_pp.py ONLY: False reproduces the r3
# schedule where every stage computed on every tick (stage 0 re-ran its
# whole stage on drain ticks, warmup stages chewed garbage) so the v2
# gating win is measurable. Leave True.
GATE_INVALID_TICKS = True


def make_pp_mesh(n_stages: int, devices=None, tp: int = 1) -> Mesh:
    """A (data=D, stage=S, model=T) mesh: the stage axis takes
    ``n_stages`` blocks of CONTIGUOUS devices and the data axis absorbs
    the rest (D = n_devices / S / T) — microbatches shard their rows over
    data, activations pipeline over stage, and (tp > 1) attention heads /
    MLP features split over model.

    Stage-contiguous device order makes the stage axis map over HOSTS on
    multi-process runs (jax.devices() orders by process): a 2-host pod
    with --pp 2 puts stage 0 on host 0 and stage 1 on host 1, so the
    per-tick ppermute hop is the only inter-host traffic (round-5 VERDICT
    #5 — multi-host pp)."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % (n_stages * tp) != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by {n_stages} stages "
            f"x {tp} model shards")
    d = len(devices) // n_stages // tp
    # stage-major: stage s owns the contiguous block devices[s*d*tp:(s+1)*d*tp]
    arr = np.asarray(devices).reshape(n_stages, d, tp).transpose(1, 0, 2)
    return Mesh(arr, (DATA_AXIS, STAGE_AXIS, MODEL_AXIS))


def _stack_blocks(blocks: Params, n_stages: int) -> Params:
    """(L, ...) stacked block params -> (S, L/S, ...) stage-major."""
    def reshape(x):
        L = x.shape[0]
        return x.reshape(n_stages, L // n_stages, *x.shape[1:])

    return jax.tree_util.tree_map(reshape, blocks)


def _tp_rule_axis(path) -> Optional[int]:
    """Model-shard axis (on the UNSTACKED per-layer shape) for a blocks
    leaf, or None if the leaf replicates over model."""
    names = tuple(p if isinstance(p, str) else str(getattr(p, "key", ""))
                  for p in path)
    for suffix, ax in _PP_TP_RULES.items():
        if names[-len(suffix):] == suffix:
            return ax
    return None


def _block_leaf_spec(path, shape, n_tp: int, lead: int) -> P:
    """PartitionSpec for one blocks leaf: stage axis on dim 0, plus
    (tp > 1) the Megatron model axis at rule-axis + ``lead`` — the ONE
    implementation behind the shard_map in_specs (lead=2: stage-major
    (S, L/S, ...) layout), the state shardings and the weight-loading
    param specs (lead=1: stacked (L, ...) layout). Trailing Nones are
    trimmed so specs compare equal to their canonical form."""
    ndim = len(shape)
    spec: list = [None] * ndim
    if ndim >= 1:
        spec[0] = STAGE_AXIS
    ax = _tp_rule_axis(path) if n_tp > 1 else None
    if ax is not None and ax + lead < ndim and shape[ax + lead] % n_tp == 0:
        spec[ax + lead] = MODEL_AXIS
    while spec and spec[-1] is None:
        spec.pop()
    return P(*spec)


def _stage_block_specs(stage_blocks: Params, n_tp: int) -> Params:
    """shard_map in_specs for the stage-major (S, L/S, per-layer...) block
    tree."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _block_leaf_spec(path, np.shape(leaf), n_tp,
                                            lead=2),
        stage_blocks)


def stage_shardings(params: Params, mesh: Mesh) -> Params:
    """Shardings for pp: block params shard their (L, ...) layer axis over
    stage (contiguous L/S chunks — matching the loss's stage-major
    reshape) plus, when the mesh has a model axis > 1, the Megatron rule
    axis over model; everything else replicates."""
    n_tp = mesh.shape.get(MODEL_AXIS, 1)

    def spec_of(path, leaf):
        names = [getattr(p, "key", None) for p in path]
        if "blocks" in names and np.ndim(leaf) >= 1:
            return NamedSharding(
                mesh, _block_leaf_spec(path, np.shape(leaf), n_tp, lead=1))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_of, params)


def make_pp_loss_fn(cfg: ModelConfig, mesh: Mesh, n_micro: int
                    ) -> Callable:
    """Build loss_fn(params, batch, rng) -> mean CE, pipelined over the
    mesh's stage axis. ``params`` uses the normal (L, ...) layout; the
    stage split happens inside. Differentiable — wrap in
    jax.value_and_grad. ``rng=None`` (or drop_rate 0) disables dropout."""
    S = mesh.shape[STAGE_AXIS]
    n_tp = mesh.shape.get(MODEL_AXIS, 1)
    tp_axis = MODEL_AXIS if n_tp > 1 else None
    if cfg.n_layers % S != 0:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {S} stages")
    if n_tp > 1 and (cfg.n_heads % n_tp or cfg.n_kv_groups % n_tp
                     or cfg.hidden_dim % n_tp):
        raise ValueError(
            f"tp={n_tp} must divide n_heads {cfg.n_heads}, n_kv_groups "
            f"{cfg.n_kv_groups} and hidden_dim {cfg.hidden_dim}")
    rope = _rope_tables(cfg)
    layers_per_stage = cfg.n_layers // S

    def local_stage(blocks_local, x, key):
        """Run this stage's L/S layers (scan over the local slice).
        ``key=None`` -> deterministic; else per-layer folded dropout."""
        deterministic = key is None
        if key is None:
            key = jax.random.PRNGKey(0)          # unused, fixed for scan

        def body(carry, xs):
            p, j = xs
            r = None if deterministic else jax.random.fold_in(key, j)
            y, _ = _block(cfg, p, carry, rope, None, None, None, r,
                          deterministic, tp_axis=tp_axis)
            return y, None

        if cfg.use_actv_ckpt:
            # opt-in remat (r3 forced it): trades a recomputed stage
            # forward in every backward tick for O(1) saved activations
            body = jax.checkpoint(body, prevent_cse=False)
        x, _ = jax.lax.scan(body, x,
                            (blocks_local, jnp.arange(layers_per_stage)))
        return x

    def pp_body(params, stage_blocks, inputs_mb, targets_mb, weights_mb,
                rng):
        """Runs INSIDE shard_map. stage_blocks: this stage's (L/S, ...)
        slice (shard_map strips the leading stage axis to size 1; squeezed
        below). inputs/targets/weights: (M, Bm, T), replicated; ``rng``:
        None, or a replicated key — folded per (micro, data shard, stage)
        here and per layer in local_stage."""
        s = jax.lax.axis_index(STAGE_AXIS)
        blocks_local = jax.tree_util.tree_map(lambda x: x[0], stage_blocks)
        M = inputs_mb.shape[0]
        Bm, T = inputs_mb.shape[1], inputs_mb.shape[2]
        D = cfg.emb_dim
        dropout_on = rng is not None and cfg.drop_rate > 0.0
        if dropout_on:
            shard_key = jax.random.fold_in(
                jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS)),
                s)

        def tick(carry, t):
            act, nll_sum, w_sum = carry
            # the microbatch this stage works on at tick t (stage 0 feeds
            # micro t; stage s received micro t-s via last tick's hop)
            micro = t - s
            valid = (micro >= 0) & (micro < M)
            m_idx = jnp.clip(micro, 0, M - 1)
            if dropout_on:
                mb_key = jax.random.fold_in(shard_key, m_idx)
                emb_key = jax.random.fold_in(mb_key, 10_000)
            else:
                mb_key = emb_key = None

            def run(act):
                # stage 0 replaces the carried activation with the fresh
                # embedding of its feed microbatch; the embed runs INSIDE
                # the device-local cond so stages 1..S-1 never compute it
                def feed(a):
                    return _embed(cfg, params, inputs_mb[m_idx], None,
                                  emb_key if dropout_on else None,
                                  not dropout_on).astype(a.dtype)

                a = jax.lax.cond(s == 0, feed, lambda a: a, act)
                return local_stage(blocks_local, a, mb_key)

            # warmup/drain ticks with no valid micro skip ALL compute
            # (device-local cond — r3 burned a full stage forward per
            # drain tick on stage 0, ADVICE #4). With tensor parallelism
            # the stage body contains psums over the model axis, and a
            # collective inside a cond whose predicate differs per stage
            # would desynchronize the SPMD program — so pp x tp always
            # computes and discards invalid ticks' results instead.
            if GATE_INVALID_TICKS and n_tp == 1:
                act = jax.lax.cond(valid, run, lambda a: a, act)
            else:
                act = jnp.where(valid, run(act), act)

            # last stage: microbatch (t - (S-1)) completes on tick t. The
            # V-sized head projection is the most expensive matmul in the
            # model — lax.cond keeps it off non-final stages and warmup
            # ticks (device-local control flow; no collectives inside, so
            # the SPMD program stays uniform)
            mb = jnp.clip(t - (S - 1), 0, M - 1)

            def loss_terms(act):
                x = _norm(cfg, params["final_norm"], act)
                logits = jnp.einsum("btd,dv->btv", x,
                                    params["head"]["weight"],
                                    preferred_element_type=jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                tgt = targets_mb[mb]
                ll = jnp.take_along_axis(
                    logp, tgt[..., None].astype(jnp.int32), axis=-1)[..., 0]
                w = weights_mb[mb].astype(jnp.float32)
                return -(ll * w).sum(), w.sum()

            nll_inc, w_inc = jax.lax.cond(
                (s == S - 1) & (t >= S - 1), loss_terms,
                lambda _: (jnp.zeros((), jnp.float32),
                           jnp.zeros((), jnp.float32)), act)
            nll_sum = nll_sum + nll_inc
            w_sum = w_sum + w_inc

            # hop: every stage sends its activation to the next; the wrap
            # from the last stage back to 0 is overwritten by the feed above
            perm = [(i, (i + 1) % S) for i in range(S)]
            act = jax.lax.ppermute(act, STAGE_AXIS, perm)
            return (act, nll_sum, w_sum), None

        # dtype follows the (possibly policy-cast) params, not the config —
        # a mismatched fp32 zeros carry would silently promote every layer
        act0 = jnp.zeros((Bm, T, D), params["tok_emb"]["weight"].dtype)
        (_, nll_sum, w_sum), _ = jax.lax.scan(
            tick, (act0, jnp.zeros((), jnp.float32),
                   jnp.zeros((), jnp.float32)),
            jnp.arange(M + S - 1))
        # only the last stage (of each data column) holds its shard's
        # totals; reduce over BOTH axes so every device returns the same
        # global-mean loss (keeps grads symmetric under psum — replicated
        # params get their data-axis grad psum from the shard_map transpose)
        nll_sum = jax.lax.psum(nll_sum, (STAGE_AXIS, DATA_AXIS))
        w_sum = jax.lax.psum(w_sum, (STAGE_AXIS, DATA_AXIS))
        return nll_sum / jnp.maximum(w_sum, 1.0)

    def loss_fn(params: Params, batch: Dict[str, jnp.ndarray],
                rng: Optional[jax.Array] = None) -> jnp.ndarray:
        D_data = mesh.shape[DATA_AXIS]
        if batch["inputs"].ndim == 3:
            # pre-microbatched (M, Bm_global, T) feed — the multi-host
            # path: PipelinePlan.shard_batch assembled it from per-process
            # rows (make_array_from_process_local_data), already sharded
            # over the data axis
            inputs = batch["inputs"]
            targets = batch["targets"]
            weights = batch.get("weights")
            if weights is None:
                weights = jnp.ones_like(targets, jnp.float32)
            if inputs.shape[0] != n_micro:
                raise ValueError(
                    f"pre-microbatched batch has M={inputs.shape[0]}, "
                    f"expected n_micro={n_micro}")
        else:
            B, T = batch["inputs"].shape
            if B % n_micro != 0:
                raise ValueError(
                    f"batch size {B} not divisible by n_micro {n_micro}")
            Bm = B // n_micro
            if Bm % D_data != 0:
                raise ValueError(
                    f"microbatch rows {Bm} not divisible by the data axis "
                    f"{D_data} (batch {B} / n_micro {n_micro})")
            mb = lambda x: x.reshape(n_micro, Bm, *x.shape[1:])
            inputs = mb(batch["inputs"])
            targets = mb(batch["targets"])
            weights = mb(batch.get(
                "weights", jnp.ones_like(batch["targets"], jnp.float32)))

        stage_blocks = _stack_blocks(params["blocks"], S)
        other = {k: v for k, v in params.items() if k != "blocks"}

        rep = P()
        blk_specs = _stage_block_specs(stage_blocks, n_tp)
        mb_spec = P(None, DATA_AXIS)   # each data column pipelines its rows
        if rng is not None and cfg.drop_rate > 0.0:
            fn = shard_map(
                pp_body,
                mesh=mesh,
                in_specs=(rep, blk_specs, mb_spec, mb_spec, mb_spec,
                          rep),
                out_specs=rep,
                check_vma=False,
            )
            return fn(other, stage_blocks, inputs, targets, weights, rng)
        fn = shard_map(
            lambda p, b, i, t, w: pp_body(p, b, i, t, w, None),
            mesh=mesh,
            in_specs=(rep, blk_specs, mb_spec, mb_spec, mb_spec),
            out_specs=rep,
            check_vma=False,
        )
        return fn(other, stage_blocks, inputs, targets, weights)

    return loss_fn


class PipelinePlan:
    """Duck-types the ``MeshPlan`` surface the Trainer/factory consume, for
    ``--shard_mode pp``: block params (and their adam moments) shard their
    layer axis over the stage mesh axis; everything else replicates; the
    data axis (when > 1) splits each microbatch's rows inside the loss."""

    shard_mode = "pp"
    sp_mesh = None

    def __init__(self, mesh: Mesh, n_micro: int = 8):
        self.mesh = mesh
        self.n_micro = n_micro
        self.n_stages = mesh.shape[STAGE_AXIS]
        self.n_tp = mesh.shape.get(MODEL_AXIS, 1)

    def _named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def param_spec(self, names, shape) -> P:
        """Spec for one model-param leaf (the weight-conversion path places
        each converted tensor straight onto its sharding): block leaves
        stage-shard their layer axis (+ model axis per the Megatron rules
        when tp > 1), everything else replicates."""
        if "blocks" in names and len(shape) >= 1 \
                and shape[0] % self.n_stages == 0:
            return _block_leaf_spec(tuple(names), shape, self.n_tp, lead=1)
        return P()

    def state_shardings(self, state: Params) -> Params:
        return stage_shardings(state, self.mesh)

    def shard_state(self, state: Params) -> Params:
        """Donation-safe placement (same contract as MeshPlan.shard_state)."""
        from building_llm_from_scratch_tpu.parallel.sharding import (
            place_state_donation_safe,
        )

        return place_state_donation_safe(state, self.state_shardings(state))

    def shard_params(self, params: Params, *, copy: bool = True) -> Params:
        from building_llm_from_scratch_tpu.parallel.sharding import put_fresh

        shardings = stage_shardings(params, self.mesh)
        if not copy:
            return jax.device_put(params, shardings)
        return jax.tree_util.tree_map(put_fresh, params, shardings)

    def shard_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Single-process: replicated placement — row-sharding the (B, T)
        batch over the data axis would NOT line up with the
        microbatch-major (M, Bm) split the loss performs (contiguous
        B-chunks span multiple microbatches), so GSPMD would reshard at
        the shard_map boundary anyway; replicating the small host batch
        keeps the transfer simple and lets the shard_map slice locally.

        Multi-process (round-5 VERDICT #5): the stage axis maps over
        hosts, so the data axis is HOST-LOCAL per stage and every process
        must feed the SAME global rows (activations for data column i hop
        between the stage replicas of column i across hosts — main.py
        disables per-process loader sharding for pp). The batch is
        reshaped host-side into the microbatch-major (M, Bm, T) layout
        and placed via ``make_array_from_process_local_data``: each
        process supplies the full rows and its devices pick up their data
        columns. The loss detects the rank-3 feed and skips its own
        reshape."""
        if jax.process_count() == 1:
            rep = self._named(P())
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, rep), batch)

        mb_sharding = self._named(P(None, DATA_AXIS))

        def put(x):
            B = x.shape[0]
            if B % self.n_micro:
                raise ValueError(
                    f"batch {B} not divisible by n_micro {self.n_micro}")
            local = x.reshape(self.n_micro, B // self.n_micro,
                              *x.shape[1:])
            return jax.make_array_from_process_local_data(
                mb_sharding, local, global_shape=local.shape)

        return jax.tree_util.tree_map(put, batch)


def make_pp_train_step(cfg: ModelConfig, optimizer, mesh: Mesh, *,
                       n_micro: int, lr_schedule: Optional[Callable] = None,
                       lora_alpha: Optional[float] = None,
                       lora_rank: Optional[int] = None,
                       policy=None,
                       jit: bool = True) -> Callable:
    """train_step(state, batch) -> (state, metrics) with the forward+backward
    pipelined over the stage axis. State layout matches train_step.py.

    LoRA and compute-dtype policies ride the same ``make_full_params_fn``
    combinator as the plain step: adapters merge into full params before the
    stage split, so grads flow back to the adapters only. fp16 (loss
    scaling) and bf16_hybrid (reduce-dtype control) are rejected upstream in
    args.py — the pipelined loss owns its own psums.
    """
    from building_llm_from_scratch_tpu.training.train_step import (
        _finish_step,
        make_full_params_fn,
    )

    _check_pp_policy(policy)
    full_params = make_full_params_fn(cfg, lora_alpha=lora_alpha,
                                      lora_rank=lora_rank, policy=policy)
    loss_fn = make_pp_loss_fn(cfg, mesh, n_micro)

    def train_step(state, batch):
        step_rng = (jax.random.fold_in(state["rng"], state["step"])
                    if cfg.drop_rate > 0.0 else None)

        def loss_of(trainable):
            return loss_fn(full_params(trainable, state["frozen"]), batch,
                           step_rng)

        loss, grads = jax.value_and_grad(loss_of)(state["trainable"])
        return _finish_step(state, loss, grads, batch["inputs"].size,
                            optimizer, lr_schedule, None)

    if jit:
        return jax.jit(train_step, donate_argnums=(0,))
    return train_step


def _check_pp_policy(policy) -> None:
    """The pipelined loss has no loss-scaling state and owns its own psum
    dtypes, so fp16 (needs the scaler) and bf16_hybrid (reduce-dtype
    control) cannot ride it — guard here, at the layer that owns the
    constraint, not only in the CLI checks."""
    if policy is None:
        return
    if policy.compute_dtype == "fp16" \
            or policy.reduce_dtype != policy.compute_dtype:
        raise ValueError(
            f"pipeline parallelism supports bf16/fp32 policies only; "
            f"got '{policy.name}'")


def make_pp_eval_step(cfg: ModelConfig, mesh: Mesh, *, n_micro: int,
                      lora_alpha: Optional[float] = None,
                      lora_rank: Optional[int] = None,
                      policy=None, jit: bool = True) -> Callable:
    """eval_step(state, batch) -> loss on the pipelined forward — same
    adapter/policy composition as make_pp_train_step, defined once here so
    train and eval cannot diverge."""
    from building_llm_from_scratch_tpu.training.train_step import (
        make_full_params_fn,
    )

    _check_pp_policy(policy)
    full_params = make_full_params_fn(cfg, lora_alpha=lora_alpha,
                                      lora_rank=lora_rank, policy=policy)
    loss_fn = make_pp_loss_fn(cfg, mesh, n_micro)

    def eval_step(state, batch):
        return loss_fn(full_params(state["trainable"], state["frozen"]),
                       batch)

    if jit:
        return jax.jit(eval_step)
    return eval_step
