"""Autoregressive sampling.

Capability parity with the reference's ``generate`` (generate.py:4-75):
temperature sampling, top-k filtering, greedy argmax when temperature==0,
and the all-rows-eos early stop (including its quirk of NOT appending the
token that triggered the stop, generate.py:68-73).

TPU-first design: the reference re-runs the FULL forward over the entire
window for every new token (O(L·T²) per token, no KV cache —
generate.py:36-45). Here decode is a jitted ``lax.while_loop`` over a
static-shape KV cache: prefill once over the prompt, then one
single-position forward per token. Compiles once per
(batch, prompt_len, max_new_tokens) shape bucket.

When prompt+new tokens exceed the model context, we fall back to the
reference's sliding-window recompute semantics (slice to the last
``context_size`` tokens, full forward per token) so behavior is identical.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models.transformer import (
    forward,
    forward_with_cache,
    init_cache,
    unstack_blocks,
    unstack_lora_blocks,
)


def _sample_token(logits: jnp.ndarray, rng: jax.Array, temperature: float,
                  top_k: Optional[int]) -> jnp.ndarray:
    """Sample next-token ids from last-position logits (B, V).

    Reference semantics (generate.py:48-65): top-k filter first, then
    temperature-scaled multinomial, or plain argmax when temperature==0.
    """
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if temperature > 0.0:
        return jax.random.categorical(rng, logits / temperature, axis=-1)
    return jnp.argmax(logits, axis=-1)


def token_rng(rng: jax.Array, i) -> jax.Array:
    """Per-token sampling key: ``fold_in(rng, i)`` where ``i`` is the
    number of tokens generated so far. ONE derivation shared by
    ``generate()`` and the serving engine — a request sampled with seed s
    draws the identical key sequence whether it runs through the one-shot
    path or any slot of a continuous batch (serving/engine.py)."""
    return jax.random.fold_in(rng, i)


def sample_tokens_dynamic(logits: jnp.ndarray, keys: jnp.ndarray,
                          temperature: jnp.ndarray, top_k: jnp.ndarray,
                          max_top_k: int) -> jnp.ndarray:
    """Per-row sampling with DYNAMIC per-row params — the serving engine's
    slot batch mixes requests with different temperature/top_k/seed in one
    compiled program.

    logits (S, V); keys (S,) PRNG keys (stacked key data); temperature
    (S,) fp32 (0 = greedy argmax); top_k (S,) int32 (0 = disabled, else
    1..max_top_k — ``max_top_k`` is the STATIC top-k capacity the program
    is compiled for).

    Row-wise equivalent of ``_sample_token``: the k-th-largest threshold,
    the -inf filter and the categorical draw match it exactly (same key,
    same logits => same token), which is what the engine-vs-generate()
    parity test pins down.
    """
    vals = jax.lax.top_k(logits, max_top_k)[0]            # (S, K) desc
    idx = jnp.clip(top_k, 1, max_top_k) - 1
    kth = jnp.take_along_axis(vals, idx[:, None], axis=1)  # (S, 1)
    filtered = jnp.where(logits < kth, -jnp.inf, logits)
    logits = jnp.where((top_k > 0)[:, None], filtered, logits)

    def one(key, row, t):
        greedy = jnp.argmax(row)
        scaled = row / jnp.where(t > 0.0, t, 1.0)
        # (1, V) shape so the draw matches _sample_token's batched
        # categorical bit-for-bit for a single-row batch
        sampled = jax.random.categorical(key, scaled[None, :], axis=-1)[0]
        return jnp.where(t > 0.0, sampled, greedy)

    return jax.vmap(one)(keys, logits, temperature)


def sample_tokens_multi(logits: jnp.ndarray, keys: jnp.ndarray,
                        temperature: jnp.ndarray, top_k: jnp.ndarray,
                        max_top_k: int) -> jnp.ndarray:
    """Per-POSITION dynamic sampling for the speculative verify program:
    ``logits`` (S, Tq, V) scores Tq candidate positions per slot in one
    forward; each (slot, position) pair samples with ITS OWN key (the
    ``token_rng`` fold-in for that position's token index) under the
    slot's temperature/top_k.

    Row (s, j) is computed by exactly the ``sample_tokens_dynamic`` math
    on a flattened (S*Tq, V) batch — every op in that path is row-wise,
    so position j of slot s draws the bit-identical token the Tq=1
    decode path would draw at the same (logits, key, params). That
    row-equivalence is what makes speculative acceptance EXACT: a
    committed token is the token the non-speculative engine would have
    produced (test-pinned)."""
    S, Tq, V = logits.shape
    rep = lambda a: jnp.repeat(a, Tq)       # row-major: (s, j) -> s*Tq + j
    flat = sample_tokens_dynamic(
        logits.reshape(S * Tq, V),
        keys.reshape((S * Tq,) + keys.shape[2:]),
        rep(temperature), rep(top_k), max_top_k)
    return flat.reshape(S, Tq)


def accept_draft_tokens(logits: jnp.ndarray, drafts: jnp.ndarray,
                        keys: jnp.ndarray, temperature: jnp.ndarray,
                        top_k: jnp.ndarray, max_top_k: int):
    """The in-graph speculative accept rule (serving/spec.py is the
    drafting side; ``models/transformer.verify_slots`` produced
    ``logits``).

    ``logits`` (S, k+1, V): position j scores the continuation after
    [last_token, d_1..d_j]. ``drafts`` (S, k) are the proposed tokens
    d_1..d_k. For every position the ENGINE'S OWN token t_j is drawn
    first (``sample_tokens_multi`` with that position's fold-in key —
    argmax when temperature 0); draft d_{j+1} is accepted iff it equals
    t_j, and the longest accepted prefix is committed as t_0..t_{n_acc}
    (t_{n_acc} is the correction/bonus token the verify forward gives
    for free).

    Because the drafter proposes a POINT MASS, exact-match acceptance
    IS Leviathan-style rejection sampling: a draft x is accepted with
    probability p(x) (the chance the model's own draw equals it), and a
    rejected position's committed token is distributed p(· | · != x) —
    the normalized residual max(0, p - q) for a one-hot q. The committed
    sequence is therefore not just distribution-preserving but
    BIT-IDENTICAL to the non-speculative sampler at every acceptance
    rate: t_j rides the same per-token-index ``token_rng`` key the
    Tq=1 path would use, and is only committed when its conditioning
    prefix was itself committed.

    Non-finite guard folded in: committing t_j needs finite logits at
    position j, so the acceptance chain stops before a poisoned
    position; ``ok`` (position 0's finiteness) retires the whole row —
    the same semantics the non-speculative decode guard has.

    Returns (tokens (S, k+1), n_accepted (S,), ok (S,))."""
    toks = sample_tokens_multi(logits, keys, temperature, top_k, max_top_k)
    finite = jnp.all(jnp.isfinite(logits), axis=-1)          # (S, k+1)
    match = (toks[:, :-1] == drafts) & finite[:, 1:]
    acc = jnp.cumprod(match.astype(jnp.int32), axis=1)       # leading run
    return toks, jnp.sum(acc, axis=1), finite[:, 0]


def _bucket(n: int, step: int = 64, lo: int = 32) -> int:
    """Round up to the compile-shape bucket (multiples of ``step``, floor
    ``lo``) so nearby prompt/budget lengths share one XLA program."""
    return max(lo, -(-n // step) * step)


@functools.partial(jax.jit, static_argnames=("cfg",))
def _forward_window(params, cfg: ModelConfig, tokens: jnp.ndarray,
                    lora=None, lora_scaling=1.0):
    """Full forward over one padded window (the sliding-window fallback's
    per-token program). Module-level jit on purpose: the jit cache keys
    on the callable's identity, so the previous ``jax.jit(lambda ...)``
    built inside ``generate()`` recompiled this forward on EVERY
    fallback call (graft-lint GL026)."""
    return forward(params, cfg, tokens, lora=lora,
                   lora_scaling=lora_scaling)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "budget", "temperature", "top_k", "eos_id",
                     "ref_eos"))
def _generate_cached(params, cfg: ModelConfig, prompt: jnp.ndarray,
                     prompt_len: jnp.ndarray, rng: jax.Array,
                     max_new_tokens: jnp.ndarray, budget: int,
                     temperature: float, top_k: Optional[int],
                     eos_id: Optional[int], ref_eos: bool,
                     lora=None, lora_scaling=1.0):
    """KV-cache decode over BUCKETED shapes.

    ``prompt`` is right-padded to its length bucket; ``prompt_len`` (traced)
    is the real length and ``max_new_tokens`` (traced) the real budget, so
    ONE compiled program serves every prompt within the bucket and every
    budget up to the (bucketed, static) ``budget`` buffer. The prefill
    writes k/v for the padding slots too, but ``cache['length']`` is reset
    to the REAL prompt length: decode steps overwrite the garbage slots one
    by one, and attention masks everything past ``length`` (kv_length)
    until they do.

    eos handling: by default each ROW tracks its own finished state — a
    row that samples eos stops (the eos token itself is dropped, matching
    the reference's drop-the-trigger quirk per row) while the others keep
    decoding; finished rows' later columns are padded with ``eos_id``.
    ``ref_eos=True`` restores the reference's batch-global quirk exactly
    (stop only when ALL rows sample eos in the SAME step, generate.py:68-73)
    for bit-parity tests.

    Token i is sampled with ``token_rng(rng, i)`` — the derivation the
    serving engine shares, so seeded requests reproduce across both paths.

    Returns (tokens (B, Tpb + budget), n_generated (B,)): row b's entries
    [:prompt_len + n_generated[b]] are prompt + generated (generated tokens
    are written AT prompt_len, overwriting pad slots first).
    """
    B, Tpb = prompt.shape
    cache = init_cache(cfg, B, Tpb + budget)
    # per-layer weight slices hoisted OUT of the sampling loop (see
    # unstack_blocks: in-loop slicing re-laid-out weights every token)
    blocks_list = unstack_blocks(params, cfg)
    lora_blocks_list = (unstack_lora_blocks(lora, cfg)
                        if lora is not None else None)
    lora_kw = dict(lora=lora, lora_scaling=lora_scaling,
                   lora_blocks_list=lora_blocks_list)

    logits, cache = forward_with_cache(params, cfg, prompt, cache,
                                       blocks_list, **lora_kw)
    # real prompt occupies [0, prompt_len); pad slots hold garbage k/v that
    # decode overwrites (and kv_length masks meanwhile)
    cache = dict(cache, length=prompt_len)
    last = jnp.take_along_axis(
        logits,
        jnp.broadcast_to(jnp.reshape(prompt_len - 1, (1, 1, 1)),
                         (B, 1, logits.shape[-1])),
        axis=1)[:, 0]
    buf = jnp.concatenate(
        [prompt, jnp.zeros((B, budget), prompt.dtype)], axis=1)

    def cond(carry):
        _buf, _cache, _last_logits, i, done, _n = carry
        return (i < max_new_tokens) & ~jnp.all(done)

    def body(carry):
        buf, cache, last_logits, i, done, n_gen = carry
        sub = token_rng(rng, i)
        nxt = _sample_token(last_logits, sub, temperature, top_k)  # (B,)
        hit = (nxt == eos_id) if eos_id is not None \
            else jnp.zeros((B,), bool)
        if ref_eos:
            # reference quirk: the token that makes ALL rows hit eos is
            # dropped and the loop stops (generate.py:68-73)
            all_eos = jnp.all(hit) if eos_id is not None \
                else jnp.asarray(False)
            buf = jax.lax.cond(
                all_eos, lambda b: b,
                lambda b: jax.lax.dynamic_update_slice(b, nxt[:, None].astype(
                    b.dtype), (0, prompt_len + i)),
                buf)
            done = jnp.broadcast_to(all_eos, done.shape)
            n_gen = jnp.where(all_eos, i, i + 1) * jnp.ones_like(n_gen)
        else:
            newly = ~done & hit               # this row's eos: drop + stop
            alive = ~done & ~newly
            pad = jnp.asarray(eos_id if eos_id is not None else 0,
                              buf.dtype)
            col = jnp.where(alive, nxt.astype(buf.dtype), pad)
            buf = jax.lax.dynamic_update_slice(buf, col[:, None],
                                               (0, prompt_len + i))
            done = done | newly
            n_gen = n_gen + alive.astype(n_gen.dtype)
        new_logits, cache = forward_with_cache(
            params, cfg, nxt[:, None].astype(jnp.int32), cache, blocks_list,
            **lora_kw)
        return (buf, cache, new_logits[:, -1], i + 1, done, n_gen)

    carry = (buf, cache, last, jnp.zeros((), jnp.int32),
             jnp.zeros((B,), bool), jnp.zeros((B,), jnp.int32))
    buf, _cache, _logits, _i, _done, n_gen = jax.lax.while_loop(
        cond, body, carry)
    return buf, n_gen


def generate(params, cfg: ModelConfig, token_ids, max_new_tokens: int,
             context_size: Optional[int] = None, temperature: float = 0.0,
             top_k: Optional[int] = None, eos_id: Optional[int] = None,
             rng: Optional[jax.Array] = None,
             ref_eos_semantics: bool = False,
             return_n_generated: bool = False,
             lora=None, lora_alpha: Optional[float] = None,
             lora_rank: Optional[int] = None) -> np.ndarray:
    """Generate up to ``max_new_tokens`` after ``token_ids`` (B, Tp).

    Returns a numpy (B, Tp + max_row_generated) array, mirroring the
    reference's return of prompt+generated ids (generate.py:73-75).

    eos semantics: each row stops at ITS OWN eos (the triggering token is
    dropped; rows that finish early are right-padded with ``eos_id``).
    ``ref_eos_semantics=True`` restores the reference quirk — stop only
    when ALL rows sample eos in the same step, otherwise a row's eos
    neither stops it nor is dropped (generate.py:68-73) — for bit-parity
    against the reference. ``return_n_generated=True`` additionally
    returns the per-row generated-token counts (B,).

    ``lora`` (+ ``lora_alpha``/``lora_rank``): decode with an UNMERGED
    LoRA adapter — the delta rides every adapted projection via
    ``models.lora.apply_lora`` instead of materializing merged weights.
    Same math as ``merge_lora`` (token-parity-tested); what the trainer's
    eval sampling and the serving engine share.
    """
    context_size = context_size or cfg.context_length
    lora_scaling = 1.0
    if lora is not None:
        if lora_alpha is None or lora_rank is None:
            raise ValueError("lora needs lora_alpha and lora_rank")
        lora_scaling = float(lora_alpha) / float(lora_rank)
    token_ids = jnp.asarray(token_ids, jnp.int32)
    if token_ids.ndim == 1:
        token_ids = token_ids[None, :]
    B, Tp = token_ids.shape
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    if Tp + max_new_tokens <= context_size:
        # bucket the compile shapes: prompt right-padded to a multiple of
        # 64, decode budget to a power-of-two-ish bucket — nearby requests
        # share one XLA program instead of recompiling per exact length
        # (round-3 VERDICT weakness #3)
        Tpb = min(_bucket(Tp), context_size)
        # clamp by context_size - Tpb (NOT - Tp): budget is a static jit
        # arg, so it must depend only on the bucket or long prompts would
        # recompile per exact length. The bound still holds: the branch
        # condition Tp + max_new <= context gives
        # context - Tpb >= max_new - (Tpb - Tp), and generated tokens are
        # written from Tp so the buffer Tpb + budget always covers them.
        budget = min(_bucket(max_new_tokens), context_size - Tpb)
        padded = jnp.concatenate(
            [token_ids, jnp.zeros((B, Tpb - Tp), jnp.int32)], axis=1)
        buf, n_gen = _generate_cached(params, cfg, padded,
                                      jnp.asarray(Tp, jnp.int32), rng,
                                      jnp.asarray(max_new_tokens, jnp.int32),
                                      budget, float(temperature),
                                      top_k, eos_id, bool(ref_eos_semantics),
                                      lora, lora_scaling)
        # ONE device_get for both results: on remote/tunnel backends each
        # transfer costs ~100ms of latency regardless of size (measured
        # r4: separate int(n)+asarray(buf) fetches added 119ms/call)
        buf_np, n = jax.device_get((buf, n_gen))
        out = buf_np[:, : Tp + int(np.max(n))]
        return (out, np.asarray(n)) if return_n_generated else out

    # Sliding-window fallback — the reference's per-token recompute semantics
    # (generate.py:36-73), but with ONE compiled shape: windows shorter than
    # ``context_size`` are right-padded (causality makes the padding inert)
    # and the logits are read at the true last position. Without this, every
    # growing prompt length would trigger a fresh XLA compile.
    fwd = lambda p, t: _forward_window(p, cfg, t, lora,  # noqa: E731
                                       lora_scaling)
    ids = np.asarray(token_ids)
    done = np.zeros((B,), bool)
    n_gen = np.zeros((B,), np.int32)
    for i in range(max_new_tokens):
        cur = ids.shape[1]
        if cur >= context_size:
            window = ids[:, -context_size:]
            last = context_size - 1
        else:
            window = np.concatenate(
                [ids, np.zeros((B, context_size - cur), ids.dtype)], axis=1)
            last = cur - 1
        logits = fwd(params, jnp.asarray(window))[:, last]
        sub = token_rng(rng, i)
        nxt = np.asarray(_sample_token(logits, sub, float(temperature), top_k))
        if ref_eos_semantics:
            if eos_id is not None and (nxt == eos_id).all():
                break
            ids = np.concatenate([ids, nxt[:, None].astype(ids.dtype)],
                                 axis=1)
            n_gen += 1
        else:
            if eos_id is not None:
                done |= ~done & (nxt == eos_id)
            if done.all():
                break
            col = np.where(~done, nxt, eos_id if eos_id is not None else 0)
            ids = np.concatenate([ids, col[:, None].astype(ids.dtype)],
                                 axis=1)
            n_gen += (~done).astype(np.int32)
    return (ids, n_gen) if return_n_generated else ids


def text_to_token_ids(text: str, tokenizer) -> np.ndarray:
    """Reference utils.py:71-77 (adds the batch dim)."""
    ids = tokenizer.encode(text, allowed_special={"<|endoftext|>"})
    return np.asarray(ids, np.int32)[None, :]


def token_ids_to_text(token_ids, tokenizer) -> str:
    """Reference utils.py:80-84 (strips the batch dim)."""
    arr = np.asarray(token_ids)
    if arr.ndim == 2:
        arr = arr[0]
    return tokenizer.decode([int(t) for t in arr])
