"""Autoregressive sampling.

Capability parity with the reference's ``generate`` (generate.py:4-75):
temperature sampling, top-k filtering, greedy argmax when temperature==0,
and the all-rows-eos early stop (including its quirk of NOT appending the
token that triggered the stop, generate.py:68-73).

TPU-first design: the reference re-runs the FULL forward over the entire
window for every new token (O(L·T²) per token, no KV cache —
generate.py:36-45). Here decode is a jitted ``lax.while_loop`` over a
static-shape KV cache: prefill once over the prompt, then one
single-position forward per token. Compiles once per
(batch, prompt_len, max_new_tokens) shape bucket.

When prompt+new tokens exceed the model context, we fall back to the
reference's sliding-window recompute semantics (slice to the last
``context_size`` tokens, full forward per token) so behavior is identical.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models.transformer import (
    forward,
    forward_with_cache,
    init_cache,
    unstack_blocks,
)


def _sample_token(logits: jnp.ndarray, rng: jax.Array, temperature: float,
                  top_k: Optional[int]) -> jnp.ndarray:
    """Sample next-token ids from last-position logits (B, V).

    Reference semantics (generate.py:48-65): top-k filter first, then
    temperature-scaled multinomial, or plain argmax when temperature==0.
    """
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if temperature > 0.0:
        return jax.random.categorical(rng, logits / temperature, axis=-1)
    return jnp.argmax(logits, axis=-1)


def _bucket(n: int, step: int = 64, lo: int = 32) -> int:
    """Round up to the compile-shape bucket (multiples of ``step``, floor
    ``lo``) so nearby prompt/budget lengths share one XLA program."""
    return max(lo, -(-n // step) * step)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "budget", "temperature", "top_k", "eos_id"))
def _generate_cached(params, cfg: ModelConfig, prompt: jnp.ndarray,
                     prompt_len: jnp.ndarray, rng: jax.Array,
                     max_new_tokens: jnp.ndarray, budget: int,
                     temperature: float, top_k: Optional[int],
                     eos_id: Optional[int]):
    """KV-cache decode over BUCKETED shapes.

    ``prompt`` is right-padded to its length bucket; ``prompt_len`` (traced)
    is the real length and ``max_new_tokens`` (traced) the real budget, so
    ONE compiled program serves every prompt within the bucket and every
    budget up to the (bucketed, static) ``budget`` buffer. The prefill
    writes k/v for the padding slots too, but ``cache['length']`` is reset
    to the REAL prompt length: decode steps overwrite the garbage slots one
    by one, and attention masks everything past ``length`` (kv_length)
    until they do.

    Returns (tokens (B, Tpb + budget), n_generated): entries
    [:prompt_len + n_generated] are prompt + generated (generated tokens
    are written AT prompt_len, overwriting pad slots first).
    """
    B, Tpb = prompt.shape
    cache = init_cache(cfg, B, Tpb + budget)
    # per-layer weight slices hoisted OUT of the sampling loop (see
    # unstack_blocks: in-loop slicing re-laid-out weights every token)
    blocks_list = unstack_blocks(params, cfg)

    logits, cache = forward_with_cache(params, cfg, prompt, cache,
                                       blocks_list)
    # real prompt occupies [0, prompt_len); pad slots hold garbage k/v that
    # decode overwrites (and kv_length masks meanwhile)
    cache = dict(cache, length=prompt_len)
    last = jnp.take_along_axis(
        logits,
        jnp.broadcast_to(jnp.reshape(prompt_len - 1, (1, 1, 1)),
                         (B, 1, logits.shape[-1])),
        axis=1)[:, 0]
    buf = jnp.concatenate(
        [prompt, jnp.zeros((B, budget), prompt.dtype)], axis=1)

    def cond(carry):
        _buf, _cache, _last_logits, _rng, i, done = carry
        return (i < max_new_tokens) & ~done

    def body(carry):
        buf, cache, last_logits, rng, i, done = carry
        rng, sub = jax.random.split(rng)
        nxt = _sample_token(last_logits, sub, temperature, top_k)  # (B,)
        if eos_id is not None:
            all_eos = jnp.all(nxt == eos_id)
        else:
            all_eos = jnp.asarray(False)
        # reference quirk: the token that makes ALL rows hit eos is dropped
        # and the loop stops (generate.py:68-73)
        buf = jax.lax.cond(
            all_eos, lambda b: b,
            lambda b: jax.lax.dynamic_update_slice(b, nxt[:, None].astype(
                b.dtype), (0, prompt_len + i)),
            buf)
        new_logits, cache = forward_with_cache(
            params, cfg, nxt[:, None].astype(jnp.int32), cache, blocks_list)
        return (buf, cache, new_logits[:, -1], rng, i + 1, all_eos)

    carry = (buf, cache, last, rng, jnp.zeros((), jnp.int32),
             jnp.asarray(False))
    buf, _cache, _logits, _rng, i, done = jax.lax.while_loop(cond, body, carry)
    n_generated = jnp.where(done, i - 1, i)
    return buf, n_generated


def generate(params, cfg: ModelConfig, token_ids, max_new_tokens: int,
             context_size: Optional[int] = None, temperature: float = 0.0,
             top_k: Optional[int] = None, eos_id: Optional[int] = None,
             rng: Optional[jax.Array] = None) -> np.ndarray:
    """Generate up to ``max_new_tokens`` after ``token_ids`` (B, Tp).

    Returns a numpy (B, Tp + n_generated) array, mirroring the reference's
    return of prompt+generated ids (generate.py:73-75).
    """
    context_size = context_size or cfg.context_length
    token_ids = jnp.asarray(token_ids, jnp.int32)
    if token_ids.ndim == 1:
        token_ids = token_ids[None, :]
    B, Tp = token_ids.shape
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    if Tp + max_new_tokens <= context_size:
        # bucket the compile shapes: prompt right-padded to a multiple of
        # 64, decode budget to a power-of-two-ish bucket — nearby requests
        # share one XLA program instead of recompiling per exact length
        # (round-3 VERDICT weakness #3)
        Tpb = min(_bucket(Tp), context_size)
        # clamp by context_size - Tpb (NOT - Tp): budget is a static jit
        # arg, so it must depend only on the bucket or long prompts would
        # recompile per exact length. The bound still holds: the branch
        # condition Tp + max_new <= context gives
        # context - Tpb >= max_new - (Tpb - Tp), and generated tokens are
        # written from Tp so the buffer Tpb + budget always covers them.
        budget = min(_bucket(max_new_tokens), context_size - Tpb)
        padded = jnp.concatenate(
            [token_ids, jnp.zeros((B, Tpb - Tp), jnp.int32)], axis=1)
        buf, n_gen = _generate_cached(params, cfg, padded,
                                      jnp.asarray(Tp, jnp.int32), rng,
                                      jnp.asarray(max_new_tokens, jnp.int32),
                                      budget, float(temperature),
                                      top_k, eos_id)
        # ONE device_get for both results: on remote/tunnel backends each
        # transfer costs ~100ms of latency regardless of size (measured
        # r4: separate int(n)+asarray(buf) fetches added 119ms/call)
        buf_np, n = jax.device_get((buf, n_gen))
        return buf_np[:, : Tp + int(n)]

    # Sliding-window fallback — the reference's per-token recompute semantics
    # (generate.py:36-73), but with ONE compiled shape: windows shorter than
    # ``context_size`` are right-padded (causality makes the padding inert)
    # and the logits are read at the true last position. Without this, every
    # growing prompt length would trigger a fresh XLA compile.
    fwd = jax.jit(lambda p, t: forward(p, cfg, t))
    ids = np.asarray(token_ids)
    for _ in range(max_new_tokens):
        cur = ids.shape[1]
        if cur >= context_size:
            window = ids[:, -context_size:]
            last = context_size - 1
        else:
            window = np.concatenate(
                [ids, np.zeros((B, context_size - cur), ids.dtype)], axis=1)
            last = cur - 1
        logits = fwd(params, jnp.asarray(window))[:, last]
        rng, sub = jax.random.split(rng)
        nxt = np.asarray(_sample_token(logits, sub, float(temperature), top_k))
        if eos_id is not None and (nxt == eos_id).all():
            break
        ids = np.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
    return ids


def text_to_token_ids(text: str, tokenizer) -> np.ndarray:
    """Reference utils.py:71-77 (adds the batch dim)."""
    ids = tokenizer.encode(text, allowed_special={"<|endoftext|>"})
    return np.asarray(ids, np.int32)[None, :]


def token_ids_to_text(token_ids, tokenizer) -> str:
    """Reference utils.py:80-84 (strips the batch dim)."""
    arr = np.asarray(token_ids)
    if arr.ndim == 2:
        arr = arr[0]
    return tokenizer.decode([int(t) for t in arr])
