"""The component factory.

Parity with the reference ``build_components.py:307-320``: one call
assembles config + model params (+ pretrained weights + LoRA) + tokenizer
from the parsed flags. Differences from the reference:

  - no model/optimizer *objects* — params are pytrees and the optimizer is
    built by the Trainer once the cosine horizon is known (train.py:155
    computes it the same way);
  - DDP/FSDP/Zero wrappers (build_components.py:142-182,243-258) become a
    ``MeshPlan`` — sharding specs over one mesh;
  - the rank-ordered download barrier dance (build_components.py:211-216)
    becomes coordinator-first download + ``sync_global_devices``;
  - errors propagate instead of being logged-and-swallowed
    (reference defect §2.3: build_components.py:322-323 returns None on
    failure and main crashes later on unpack).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax

from building_llm_from_scratch_tpu.configs import ModelConfig, get_config
from building_llm_from_scratch_tpu.data.tokenizers import build_tokenizer
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.models.lora import (
    count_lora_params,
    init_lora_params,
)
from building_llm_from_scratch_tpu.parallel import (
    MeshPlan,
    build_mesh_plan,
    is_coordinator,
    sync_global_devices,
)
from building_llm_from_scratch_tpu.training.precision import (
    PrecisionPolicy,
    get_policy,
)
from building_llm_from_scratch_tpu.utils.hf import login_hf
from building_llm_from_scratch_tpu.utils.logging import setup_logger
from building_llm_from_scratch_tpu.utils.memory import (
    count_params,
    estimate_memory_dynamic,
    estimate_memory_static,
)

logger = setup_logger(__name__)


@dataclasses.dataclass
class Components:
    """Everything a run needs (reference returns a 4-tuple,
    build_components.py:317-320)."""

    cfg: ModelConfig
    params: Dict[str, Any]
    lora_params: Optional[Dict[str, Any]]
    tokenizer: Any
    plan: Optional[MeshPlan]
    policy: Optional[PrecisionPolicy]


def build_config(args) -> ModelConfig:
    """Flags -> ModelConfig (reference build_components.py:50-82)."""
    return get_config(
        args.model, args.num_params,
        dtype=args.data_type,
        # GPT-2 HF checkpoints carry QKV biases (build_components.py:69-70)
        qkv_bias=True if (args.load_weights and args.model == "GPT2") else None,
        use_actv_ckpt=args.use_actv_ckpt,
        debug=args.debug,
        target_context_length=(args.target_context_length or None),
    ).replace(attn_impl=args.attn_impl)


def build_plan(args) -> Optional[MeshPlan]:
    """Flags -> MeshPlan (replaces multigpu_setup, build_components.py:142-182)."""
    if args.run_type != "multi_chip":
        return None
    if args.shard_mode == "pp":
        from building_llm_from_scratch_tpu.parallel.pipeline import (
            PipelinePlan,
            make_pp_mesh,
        )

        stages = args.pp or max(1, len(jax.devices()) // args.tp)
        n_micro = args.pp_micro or 8     # perform_checks resolves this too,
        # but don't depend on its mutation for callers that skip get_args
        plan = PipelinePlan(make_pp_mesh(stages, tp=args.tp),
                            n_micro=n_micro)
        # fail at build time, not first-step trace: each microbatch's rows
        # must split over the mesh's data axis
        d = plan.mesh.shape["data"]
        if (args.batch_size % n_micro != 0
                or (args.batch_size // n_micro) % d != 0):
            raise ValueError(
                f"--batch_size {args.batch_size} must split into "
                f"--pp_micro {n_micro} microbatches whose rows divide the "
                f"mesh data axis {d} "
                f"({len(jax.devices())} devices / {stages} stages).")
        return plan
    return build_mesh_plan(args.shard_mode, tp=args.tp, sp=args.sp)


def build_params(args, cfg: ModelConfig, plan: Optional[MeshPlan],
                 seed: int = 0) -> Dict[str, Any]:
    """Initialize or load model params, placed on the plan's sharding.

    Pretrained load order mirrors the reference's coordinator-first barrier
    dance (build_components.py:211-216): process 0 downloads (populating the
    shared cache), everyone else waits, then all processes convert.
    """
    if args.load_weights:
        from building_llm_from_scratch_tpu.weights import (
            download_hf_weights,
            load_hf_weights,
        )

        if args.weights_dir is None:
            login_hf()
            # coordinator populates the shared cache with a LOCAL-only
            # download, THEN everyone syncs, THEN all processes convert
            # together — conversion device_puts onto multi-host shardings,
            # a collective every process must join; running it on one side
            # of the barrier deadlocks (round-2 ADVICE medium #2)
            if is_coordinator():
                download_hf_weights(args.model, args.num_params)
            sync_global_devices("weights_download")
        return load_hf_weights(args.model, args.num_params, cfg, plan=plan,
                               weights_dir=args.weights_dir)

    if getattr(args, "init_params_from", None):
        from building_llm_from_scratch_tpu.training.checkpoint import (
            load_exported_params,
        )

        template = init_params(cfg, jax.random.PRNGKey(seed))
        params = load_exported_params(args.init_params_from, template)
        logger.info("Initialized params from %s", args.init_params_from)
        if plan is not None:
            params = plan.shard_params(params, copy=False)
        return params

    params = init_params(cfg, jax.random.PRNGKey(seed))
    if plan is not None:
        # freshly initialized — nothing else references these buffers, so
        # the donation-safety copy is unnecessary
        params = plan.shard_params(params, copy=False)
    return params


def build_components(args) -> Components:
    """Assemble all run components from parsed flags."""
    cfg = build_config(args)
    plan = build_plan(args)
    policy = get_policy(args.mixed_precision)
    if policy is None and args.data_type == "fp16":
        # --data_type fp16 alone must NOT train scaler-less: fp16's 5-bit
        # exponent underflows LM gradients (round-2 VERDICT weak #4) —
        # synthesize the fp16 policy so the step carries dynamic loss scaling
        logger.info("--data_type fp16: enabling dynamic loss scaling "
                    "(fp16 mixed-precision policy)")
        policy = get_policy("fp16")

    params = build_params(args, cfg, plan, seed=args.seed)

    n_params = count_params(params)
    if is_coordinator():
        logger.info("Total parameters: %s", f"{n_params:,}")
        logger.info("Estimated training memory (4N Adam rule): %.2f GB",
                    estimate_memory_static(n_params, cfg.dtype))
    from building_llm_from_scratch_tpu.obs.metrics import emit_event
    from building_llm_from_scratch_tpu.obs.mfu import flops_per_token

    emit_event("components_built", model=cfg.name, n_params=n_params,
               est_train_mem_gb=round(
                   estimate_memory_static(n_params, cfg.dtype), 3),
               # analytic train FLOPs/token for this config: the baseline
               # the compile event's HLO-counted figure is compared against
               flops_per_token_analytic=flops_per_token(cfg),
               shard_mode=getattr(args, "shard_mode", None),
               load_weights=bool(args.load_weights),
               # host-overlap config, so a postmortem can tell at a glance
               # whether a slow run even had the overlap machinery on
               prefetch=getattr(args, "prefetch", None),
               async_ckpt=getattr(args, "async_ckpt", None),
               tokenizer_cache=bool(getattr(args, "tokenizer_cache_dir",
                                            None)))

    lora_params = None
    if args.use_lora:
        logger.info("Using LoRA...")
        lora_params = init_lora_params(cfg, params,
                                       jax.random.PRNGKey(args.seed + 1),
                                       rank=args.lora_rank)
        if plan is not None:
            # adapters are tiny — replicate them across the mesh
            from jax.sharding import PartitionSpec

            replicated = plan._named(PartitionSpec())
            lora_params = jax.device_put(
                lora_params,
                jax.tree_util.tree_map(lambda _: replicated, lora_params))
        n_lora = count_lora_params(lora_params)
        if is_coordinator():
            logger.info("Total trainable LoRA parameters: %s", f"{n_lora:,}")
            logger.info("Runtime params+grads estimate: %.2f GB",
                        estimate_memory_dynamic(n_params, n_lora, cfg.dtype))
    elif is_coordinator():
        logger.info("Runtime params+grads estimate: %.2f GB",
                    estimate_memory_dynamic(n_params, n_params, cfg.dtype))

    if (args.model != "GPT2" and args.tokenizer_path is None
            and not args.byte_tokenizer and jax.process_count() > 1):
        # coordinator-first tokenizer-asset download (reference's rank
        # barrier dance, build_components.py:265-300): the coordinator
        # populates the shared HF cache with a LOCAL-only download, then
        # everyone resolves from the cache after the barrier
        from building_llm_from_scratch_tpu.data.tokenizers import (
            fetch_tokenizer_asset,
        )

        if is_coordinator():
            fetch_tokenizer_asset(args.model)
        sync_global_devices("tokenizer_download")
    tokenizer = build_tokenizer(args.model, args.tokenizer_path,
                                fallback_byte=args.byte_tokenizer)

    return Components(cfg=cfg, params=params, lora_params=lora_params,
                      tokenizer=tokenizer, plan=plan, policy=policy)
