"""KV-cache memory engine: layout/dtype policy, shared-prefix store,
and the chunked-prefill pane primitives.

Before this module the serving KV tier hardcoded three assumptions that
each cost real capacity or latency at scale:

  1. every request prefills its FULL prompt from scratch — a fleet where
     millions of users share a handful of system prompts recomputes the
     same prefix forward pass per request;
  2. prompt prefill is monolithic — a 2k-token prompt holds the engine
     lock for one giant program call, stalling the decode tick for every
     co-resident request (PR 7's per-tick phase timeline measures exactly
     this head-of-line blocking);
  3. the slot cache is the model dtype, contiguous — KV bytes, not
     compute, cap ``n_slots`` well below what HBM allows.

One ``KVCachePolicy`` object (layout + dtype + prefix policy) replaces
all three:

  - **prefix caching** (``prefix_cache=True``): a hash-keyed
    (token-ids, model-fingerprint, adapter-tag) store of per-layer KV
    panes. A shared prefix prefills ONCE; later requests copy its panes
    into their slot with one batched dynamic-update-slice and
    chunk-prefill only the suffix — zero forward FLOPs for the cached
    span. Per-adapter namespacing (the registry's load tag) keeps each
    tenant's cached prefix adapter-consistent with unmerged-LoRA
    prefill, and a reloaded adapter gets a fresh tag so stale panes can
    never hit. LRU eviction under a byte budget with in-use pinning
    (the same non-reuse discipline as ``AdapterRegistry``).
  - **chunked prefill** (``prefill_chunk=C``): prompts prefill in
    fixed-size C-token chunks interleaved with decode ticks. The chunk
    shape is STATIC, so the whole prefill tier is ONE compiled program
    (vs one per prompt-length bucket) and ``tick_prefill_s`` is bounded
    by one chunk's wall time instead of the longest prompt's.
  - **int8 slot KV** (``kv_quant="int8"``): symmetric per-(slot, layer,
    head, position) scale quantization on append, dequantized inside
    ``decode_attention`` (scales fold into the score/value einsums, no
    dequantized cache copy ever materializes). KV data bytes halve
    exactly vs bf16; the fp32 scale sidecar adds 2/head_dim overhead
    (6.25% at head_dim 64), so total cache bytes are ~0.53x.

Compile discipline: pane width and chunk size are static; hit/miss/
evict, span length and slot index are DATA. The engine's frozen
``CompileWatcher`` set (prefill-or-chunk, copy, extract, decode) is
warmed up front, so live traffic — including store eviction and adapter
churn — runs with zero recompiles (test-pinned).

Layering: this module sees only configs + obs (events) + jax; the model
side (``models/transformer.py``) imports ``KVCachePolicy.alloc`` lazily
so train-time ``init_cache`` and serving ``init_slot_cache`` share one
allocation rule without an import cycle.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.obs.metrics import get_metrics
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

Params = Dict[str, Any]

KV_QUANT_CHOICES = ("model", "int8")


@dataclass(frozen=True)
class KVCachePolicy:
    """Layout + dtype + prefix policy for the slot KV cache.

    The policy is STATIC per engine: it decides the cache pytree's
    structure (scale sidecars or not), leaf dtypes, and which prefill
    tier (monolithic-bucketed vs chunked) the engine compiles. Request
    traffic — hits, misses, spans, slots — is data against those fixed
    shapes.
    """

    kv_quant: str = "model"          # "model" (cfg dtype) | "int8"
    prefix_cache: bool = False
    prefill_chunk: int = 0           # 0 = monolithic bucketed prefill
    prefix_budget_bytes: int = 256 * 1024 ** 2
    paged: bool = False              # page-table layout over a shared pool
    page_tokens: int = 16            # positions per KV page (paged only)
    pool_pages: int = 0              # usable pool pages; 0 = n_slots full

    def __post_init__(self):
        if self.kv_quant not in KV_QUANT_CHOICES:
            raise ValueError(
                f"kv_quant must be one of {KV_QUANT_CHOICES}, "
                f"got '{self.kv_quant}'")
        if self.prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0")
        if self.prefix_cache and self.prefill_chunk <= 0:
            raise ValueError(
                "prefix_cache needs chunked prefill (prefill_chunk > 0): "
                "the suffix after a cached span prefills in chunks — the "
                "monolithic bucketed prefill always starts at position 0")
        if self.prefix_budget_bytes < 0:
            raise ValueError("prefix_budget_bytes must be >= 0")
        if self.page_tokens < 1:
            raise ValueError("page_tokens must be >= 1")
        if self.pool_pages < 0:
            raise ValueError("pool_pages must be >= 0")
        if self.paged:
            if self.prefill_chunk <= 0:
                raise ValueError(
                    "paged KV needs chunked prefill (prefill_chunk > 0): "
                    "pages are allocated on demand as the chunk frontier "
                    "advances — the monolithic bucketed prefill would "
                    "need every page up front per bucket")
            if self.prefill_chunk % self.page_tokens != 0:
                raise ValueError(
                    "paged KV needs prefill_chunk to be a multiple of "
                    f"page_tokens (got chunk {self.prefill_chunk}, page "
                    f"{self.page_tokens}): chunk boundaries must land on "
                    "page boundaries so mid-prefill appends never touch "
                    "an unallocated page and shared prefix spans are "
                    "whole pages")

    # -- layout ------------------------------------------------------------

    @property
    def quantized(self) -> bool:
        return self.kv_quant == "int8"

    def cache_dtype(self, cfg: ModelConfig):
        import jax.numpy as jnp

        return jnp.int8 if self.quantized else cfg.jax_dtype

    def alloc(self, cfg: ModelConfig, n_rows: int, max_length: int) -> Params:
        """Allocate the per-layer KV buffers: the ONE allocation rule
        behind train-time ``init_cache`` and serving ``init_slot_cache``
        (previously three identical ``jnp.zeros`` blocks that could
        silently drift).

        Layout (n_rows, Hkv, max_length, head_dim) — attention-native
        (see ``init_cache``'s docstring for the per-layer-buffer and
        layout rationale). Quantized policies add fp32 scale sidecars
        (n_rows, Hkv, max_length, 1): one symmetric scale per written
        position per head.
        """
        import jax.numpy as jnp

        if self.paged:
            n_pages = self.total_pool_pages(n_rows, max_length)
            shape = (n_pages, cfg.n_kv_groups, self.page_tokens,
                     cfg.head_dim)
            sshape = (n_pages, cfg.n_kv_groups, self.page_tokens, 1)
        else:
            shape = (n_rows, cfg.n_kv_groups, max_length, cfg.head_dim)
            sshape = (n_rows, cfg.n_kv_groups, max_length, 1)
        dt = self.cache_dtype(cfg)
        cache: Params = {
            "k": [jnp.zeros(shape, dt) for _ in range(cfg.n_layers)],
            "v": [jnp.zeros(shape, dt) for _ in range(cfg.n_layers)],
        }
        if self.quantized:
            cache["k_scale"] = [jnp.zeros(sshape, jnp.float32)
                                for _ in range(cfg.n_layers)]
            cache["v_scale"] = [jnp.zeros(sshape, jnp.float32)
                                for _ in range(cfg.n_layers)]
        return cache

    # -- paged layout --------------------------------------------------------

    def pages_per_slot(self, max_length: int) -> int:
        """Page-table width: enough table columns to map a full-length
        row. A slot never maps more — oversubscription shrinks the POOL,
        never the table (the table shape is compiled into the programs)."""
        return -(-max_length // self.page_tokens)

    def total_pool_pages(self, n_rows: int, max_length: int) -> int:
        """Physical pages allocated on device: the usable pool
        (``pool_pages``, defaulting to ``n_rows`` full-length rows —
        contiguous-equivalent capacity) plus the reserved trash page 0.

        Page 0 is never owned by any slot: zeroed table entries point at
        it, so out-of-range appends (a free row's garbage lane, a
        mid-prefill row's clamped tail) land there instead of corrupting
        live pages, and gathers from it are always masked."""
        usable = self.pool_pages or n_rows * self.pages_per_slot(max_length)
        return usable + 1

    def page_bytes(self, cfg: ModelConfig) -> int:
        """Device bytes of ONE page across every layer and sidecar — the
        exact quantum the ledger reconciles against: total cache bytes
        == total_pool_pages x page_bytes."""
        import jax.numpy as jnp

        width = jnp.dtype(self.cache_dtype(cfg)).itemsize
        per = 2 * cfg.n_layers * cfg.n_kv_groups * self.page_tokens
        kv = per * cfg.head_dim * width
        scale = per * 4 if self.quantized else 0
        return kv + scale

    def bytes_per_slot(self, cfg: ModelConfig, max_length: int) -> Dict[str, int]:
        """Per-slot cache bytes under this policy: the HBM number that
        decides ``n_slots`` (proven against ``memory_analysis()`` /
        ``nbytes`` in tests). ``kv_bytes`` is the K+V data alone — int8
        halves it exactly vs bf16; ``scale_bytes`` is the quantization
        sidecar (0 unquantized)."""
        import jax.numpy as jnp

        per_pos = cfg.n_kv_groups * cfg.head_dim
        width = jnp.dtype(self.cache_dtype(cfg)).itemsize
        kv = 2 * cfg.n_layers * max_length * per_pos * width
        scale = (2 * cfg.n_layers * max_length * cfg.n_kv_groups * 4
                 if self.quantized else 0)
        return {"kv_bytes": kv, "scale_bytes": scale,
                "total_bytes": kv + scale,
                "bytes_per_token": (kv + scale) // max_length}

    def describe(self) -> Dict[str, Any]:
        """Event-payload summary (rides ``serve_warmup``)."""
        out = {"kv_quant": self.kv_quant,
               "prefix_cache": self.prefix_cache,
               "prefill_chunk": self.prefill_chunk}
        if self.paged:
            out["kv_paged"] = True
            out["page_tokens"] = self.page_tokens
            out["pool_pages"] = self.pool_pages
        return out


#: slot caches allocated before the policy object existed (or by older
#: call sites passing policy=None) behave exactly like this
DEFAULT_POLICY = KVCachePolicy()


def cache_is_quantized(cache: Params) -> bool:
    """Data-driven quantization probe: the cache pytree itself says
    whether appends must quantize and attention must dequantize — the
    model code never needs the policy object."""
    return "k_scale" in cache


def cache_nbytes(cache: Params) -> int:
    """Total device bytes of one cache pytree — per-layer buffer LISTS
    (slot caches) or stacked pane ARRAYS (prefix panes) alike."""
    total = 0
    for leaves in cache.values():
        if isinstance(leaves, (list, tuple)):
            total += sum(leaf.nbytes for leaf in leaves)
        else:
            total += leaves.nbytes
    return total


# ---------------------------------------------------------------------------
# the page pool (paged layout only; host-side allocator)
# ---------------------------------------------------------------------------

class PagePool:
    """Host-side allocator + refcounts for the shared device page pool.

    The device arrays are a flat pool of ``n_pages`` fixed-size pages;
    WHICH page holds WHICH slot's positions is pure host bookkeeping —
    the per-slot int32 page table rides the jitted programs as traced
    data (the adapter-pool trick: identity is data, capacity is static,
    so page churn never recompiles anything).

    Refcounts make prefix sharing copy-free: a prefix hit increfs the
    stored entry's pages and writes their ids into the slot's table —
    zero device work. A page returns to the free list only when its LAST
    owner (slot or store entry) drops it, so effective capacity is
    bounded by tokens in flight, not ``n_slots x Tmax``.

    ``reserved`` is the admission ledger: admitting a request reserves
    its worst-case PRIVATE page need up front (free pages minus reserved
    is what admission may promise next), and each on-demand allocation
    by that slot draws its reservation down — so two admitted requests
    can never deadlock mid-decode fighting over the same last page.

    Page 0 is the trash page: permanently allocated, never freed, the
    target of every zeroed table entry (see
    ``KVCachePolicy.total_pool_pages``).

    Thread-safe (leaf lock; callers hold the engine lock anyway, but
    stats/ledger probes may fire from admin threads).
    """

    def __init__(self, n_pages: int, page_bytes: int):
        if n_pages < 2:
            raise ValueError("PagePool needs >= 2 pages (trash + 1 usable)")
        self.n_pages = int(n_pages)
        self.page_bytes = int(page_bytes)
        self._lock = threading.Lock()
        self._refs = np.zeros(self.n_pages, np.int64)   # guarded-by: _lock
        self._refs[0] = 1                               # trash page: pinned
        # lowest-id-first free list keeps page ids dense and runs
        # byte-reproducible across identical request sequences
        self._free = list(range(self.n_pages - 1, 0, -1))  # pop() -> lowest
        self.reserved = 0               # guarded-by: _lock
        self.n_allocs = 0               # guarded-by: _lock
        self.n_frees = 0                # guarded-by: _lock
        self.peak_used = 0              # guarded-by: _lock

    # -- allocation ----------------------------------------------------------

    def alloc(self, *, from_reserved: bool = False) -> int:
        """Take the lowest free page (refcount 1). ``from_reserved=True``
        consumes one unit of the admission reservation that promised
        this page. Raises ``RuntimeError`` on exhaustion — admission
        checks ``available()`` first, so running dry here is an
        accounting bug, not an oversubscription event."""
        with self._lock:
            if not self._free:
                raise RuntimeError(
                    "page pool exhausted: admission reservation "
                    "accounting is broken (alloc past available())")
            page = self._free.pop()
            self._refs[page] = 1
            if from_reserved:
                self.reserved = max(self.reserved - 1, 0)
            self.n_allocs += 1
            used = self.n_pages - 1 - len(self._free)
            if used > self.peak_used:
                self.peak_used = used
            return page

    def incref(self, page: int) -> None:
        with self._lock:
            if page == 0 or self._refs[page] <= 0:
                raise RuntimeError(
                    f"incref on unallocated page {page} (use-after-free)")
            self._refs[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; returns True when the page went back to
        the free list."""
        with self._lock:
            if page == 0:
                return False            # trash page is never freed
            if self._refs[page] <= 0:
                raise RuntimeError(
                    f"decref on free page {page} (double free)")
            self._refs[page] -= 1
            if self._refs[page] == 0:
                self._free.append(page)
                self._free.sort(reverse=True)
                self.n_frees += 1
                return True
            return False

    def refcount(self, page: int) -> int:
        with self._lock:
            return int(self._refs[page])

    # -- admission ledger ----------------------------------------------------

    def available(self) -> int:
        """Free pages not yet promised to an admitted request — what
        admission may still hand out."""
        with self._lock:
            return len(self._free) - self.reserved

    def reserve(self, n: int) -> None:
        with self._lock:
            self.reserved += int(n)    # graft-ok: GL011 host int

    def unreserve(self, n: int) -> None:
        with self._lock:
            self.reserved = max(
                self.reserved - int(n), 0)  # graft-ok: GL011 host int

    # -- introspection -------------------------------------------------------

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    def used_pages(self) -> int:
        """Allocated pages, trash page excluded."""
        with self._lock:
            return self.n_pages - 1 - len(self._free)

    def stats(self) -> dict:
        with self._lock:
            used = self.n_pages - 1 - len(self._free)
            return {"n_pages": self.n_pages - 1,     # usable (sans trash)
                    "page_bytes": self.page_bytes,
                    "used": used,
                    "free": len(self._free),
                    "reserved": self.reserved,
                    "peak_used": self.peak_used,
                    "allocs": self.n_allocs,
                    "frees": self.n_frees}


# ---------------------------------------------------------------------------
# pane primitives (jitted by the engine; pane width is STATIC)
# ---------------------------------------------------------------------------

def copy_prefix_into_slot(cache: Params, panes: Params, slot) -> Params:
    """Write a stored prefix's stacked per-layer panes into row ``slot``
    of the slot cache: one dynamic-update-slice per layer per k/v (and
    per scale sidecar when quantized). ``panes`` leaves are
    (L, Hkv, P, hd) / (L, Hkv, P, 1) with P static; ``slot`` is data.

    This is the whole prefix-HIT compute: no embedding, no projection,
    no attention — zero prompt-forward FLOPs for the cached span
    (test-asserted via a forward-call spy)."""
    import jax

    def write(bufs, pane):
        return [jax.lax.dynamic_update_slice(
                    buf, pane[layer][None].astype(buf.dtype),
                    (slot, 0, 0, 0))
                for layer, buf in enumerate(bufs)]

    return {name: write(bufs, panes[name]) for name, bufs in cache.items()}


def extract_prefix_panes(cache: Params, slot, n_valid, *,
                         pane_len: int) -> Params:
    """Read row ``slot``'s first ``pane_len`` positions out of the slot
    cache as stacked (L, Hkv, pane_len, ...) panes, ZEROING every
    position >= ``n_valid``.

    The zeroing is load-bearing, not cosmetic: positions past the
    prefix span hold whatever the slot saw last (the request's own
    suffix KV, a previous occupant's decode tail, pad garbage) — all of
    it request-private state that must never become shareable. Clamping
    to the span makes a stored pane a pure function of
    (prefix tokens, params, adapter): byte-deterministic, so its hash
    key and any audit of store contents are stable across donors."""
    import jax
    import jax.numpy as jnp

    keep = jnp.arange(pane_len) < n_valid

    def take(bufs):
        rows = []
        for buf in bufs:
            row = jax.lax.dynamic_slice(
                buf, (slot, 0, 0, 0), (1,) + buf.shape[1:])[0]
            row = row[:, :pane_len]
            rows.append(jnp.where(keep[None, :, None], row,
                                  jnp.zeros((), buf.dtype)))
        return jnp.stack(rows)

    return {name: take(bufs) for name, bufs in cache.items()}


# ---------------------------------------------------------------------------
# the prefix store
# ---------------------------------------------------------------------------

class _Entry:
    __slots__ = ("key", "panes", "span", "nbytes", "pins", "hits",
                 "t_insert", "tag", "pages")

    def __init__(self, key: str, panes: Optional[Params], span: int,
                 nbytes: int, tag: Optional[str] = None,
                 pages: Optional[List[int]] = None):
        self.key = key
        self.panes = panes
        self.span = span
        self.nbytes = nbytes
        self.pins = 0
        self.hits = 0
        self.t_insert = time.monotonic()
        # namespace tag (adapter identity) for per-tenant byte
        # attribution; None for raw-key imports (the donor's tag is
        # hashed into the key but not transported)
        self.tag = tag
        # paged layout: the store owns REFERENCES to shared pool pages
        # instead of a private pane copy (panes is None) — nbytes is the
        # pages' pool footprint, charged against the same LRU budget
        self.pages = pages


class PrefixStore:
    """Hash-keyed LRU store of device-resident prefix KV panes.

    Keys are sha1(model-fingerprint, adapter-tag, token-ids): a pane can
    only ever hit for the exact tokens, base weights, and adapter load
    it was computed under. Spans are CHUNK-GRANULAR — lookups probe the
    longest multiple-of-``chunk_tokens`` prefix first and walk down, so
    a prompt sharing only part of a stored prefix still reuses the
    shared chunks.

    Concurrency: the engine calls ``match``/``insert``/``release`` under
    its own lock, but mutations also serialize on the store lock so
    registry-style admin (stats, external eviction) is safe from any
    thread. Pinning follows the ``AdapterRegistry`` non-reuse
    discipline: an entry pinned by an in-flight copy is never evicted —
    eviction skips it and charges the budget overrun to the next insert.
    """

    def __init__(self, fingerprint: str, *, chunk_tokens: int,
                 budget_bytes: int, pane_tokens: int,
                 page_pool: Optional[PagePool] = None):
        if chunk_tokens < 1:
            raise ValueError("chunk_tokens must be >= 1")
        self.fingerprint = fingerprint
        self.chunk_tokens = int(chunk_tokens)
        self.budget_bytes = int(budget_bytes)
        self.pane_tokens = int(pane_tokens)
        # paged layout: entries hold pool page ids, and eviction must
        # return the store's references to this pool
        self.page_pool = page_pool
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self.bytes_total = 0            # guarded-by: _lock
        self.n_hits = 0                 # guarded-by: _lock
        self.n_misses = 0               # guarded-by: _lock
        self.n_inserts = 0              # guarded-by: _lock
        self.n_evictions = 0            # guarded-by: _lock
        self.n_insert_skips = 0         # guarded-by: _lock

    # -- keys --------------------------------------------------------------

    def key(self, token_ids, tag: str) -> str:
        h = hashlib.sha1()
        h.update(self.fingerprint.encode())
        h.update(b"\x00")
        h.update(tag.encode())
        h.update(b"\x00")
        h.update(np.ascontiguousarray(token_ids, np.int32).tobytes())
        return h.hexdigest()

    def storable_span(self, prompt_len: int) -> int:
        """Longest chunk-aligned span of a ``prompt_len`` prompt worth
        storing: capped one below the prompt (a hit must leave >= 1
        suffix token to produce first-token logits) and at the static
        pane width."""
        span = ((prompt_len - 1) // self.chunk_tokens) * self.chunk_tokens
        return min(span, self.pane_tokens)

    # -- engine-side hot path ----------------------------------------------

    def match(self, prompt_ids, tag: str, *, min_span: int = 0,
              count_miss: bool = True) -> Tuple[int, Optional[_Entry]]:
        """Longest-prefix lookup for one prompt. Returns (span, entry):
        span 0 / None on a miss. A returned entry is PINNED — the caller
        must ``release`` it after copying its panes.

        ``min_span``: only spans strictly longer count (the mid-prefill
        catch-up probe — a pane no longer than what the slot already
        holds is not a hit). ``count_miss=False`` keeps that repeated
        probe from inflating the miss ratio: only admission-time misses
        are real workload misses."""
        n_max = self.storable_span(len(prompt_ids))
        for m in range(n_max // self.chunk_tokens, 0, -1):
            span = m * self.chunk_tokens
            if span <= min_span:
                break
            k = self.key(prompt_ids[:span], tag)
            with self._lock:
                entry = self._entries.get(k)
                if entry is not None:
                    self._entries.move_to_end(k)
                    entry.hits += 1
                    entry.pins += 1
                    self.n_hits += 1
                    return span, entry
        if count_miss:
            with self._lock:
                self.n_misses += 1
        return 0, None

    def release(self, entry: _Entry) -> None:
        with self._lock:
            entry.pins = max(entry.pins - 1, 0)

    def contains(self, token_ids, tag: str) -> bool:
        k = self.key(token_ids, tag)
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                return True
        return False

    def insert(self, token_ids, tag: str, panes: Params) -> int:
        """Store one prefix's panes under the LRU byte budget; evicts
        least-recently-used UNPINNED entries to make room. Returns the
        entry's byte size, or 0 — skipped (and counted) — when the
        entry alone exceeds the budget or everything evictable is
        pinned (also 0, uncounted, when the key is already stored)."""
        return self._insert_keyed(self.key(token_ids, tag), panes,
                                  len(token_ids), tag=tag)

    def insert_pages(self, token_ids, tag: str, pages: List[int]) -> int:
        """Paged insert: store REFERENCES to the donor slot's pool pages
        instead of copying panes — the store increfs each page (its own
        ownership, outliving the donor slot) and charges their pool
        footprint to the same LRU byte budget. Zero device work: the
        panes already live in the pool; sharing is bookkeeping."""
        if self.page_pool is None:
            raise RuntimeError("insert_pages needs a page_pool-backed "
                               "PrefixStore")
        return self._insert_keyed(
            self.key(token_ids, tag), None, len(token_ids), tag=tag,
            pages=list(pages),
            nbytes=len(pages) * self.page_pool.page_bytes)

    def import_entry(self, key: str, panes: Params, span: int) -> int:
        """Raw-key insert for cross-process pane handoff (fleet drain).

        The key is sha1(fingerprint, tag, tokens) computed by the donor;
        fingerprints are config-derived, so same-config workers agree on
        every key and the donor's keys import verbatim — the adoptee
        serves the shared prefix as a hit without recomputing anything.
        Same LRU/budget/pin discipline as ``insert``."""
        return self._insert_keyed(key, panes, int(span))

    def export_entries(self) -> list:
        """Snapshot ``[(key, span, panes)]`` LRU-first (so the adoptee's
        LRU order, rebuilt by importing in sequence, matches the
        donor's). Panes are the live device/host arrays — the transport
        layer serializes them."""
        with self._lock:
            return [(e.key, e.span, e.panes)
                    for e in self._entries.values()
                    if e.panes is not None]    # paged entries hold pool
                                               # page ids, meaningless in
                                               # another process's pool

    def _insert_keyed(self, k: str, panes: Optional[Params], span: int,
                      tag: Optional[str] = None,
                      pages: Optional[List[int]] = None,
                      nbytes: Optional[int] = None) -> int:
        if nbytes is None:
            nbytes = cache_nbytes(panes)
        evicted = []
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                return 0
            if nbytes > self.budget_bytes:
                self.n_insert_skips += 1
                return 0
            while self.bytes_total + nbytes > self.budget_bytes:
                victim_key = next(
                    (key for key, e in self._entries.items() if e.pins == 0),
                    None)
                if victim_key is None:       # everything evictable pinned
                    self.n_insert_skips += 1
                    return 0
                victim = self._entries.pop(victim_key)
                self.bytes_total -= victim.nbytes
                self.n_evictions += 1
                evicted.append(victim)
            if pages is not None:
                # the store's own references; the donor slot keeps its
                # refs and drops them independently at retirement
                for p in pages:
                    self.page_pool.incref(p)
            entry = _Entry(k, panes, span, nbytes, tag=tag, pages=pages)
            self._entries[k] = entry
            self.bytes_total += nbytes
            self.n_inserts += 1
            n_entries = len(self._entries)
            bytes_total = self.bytes_total
        for victim in evicted:
            self._release_victim_pages(victim)
            get_metrics().event(
                "prefix_evict", key=victim.key, bytes=victim.nbytes,
                span_tokens=victim.span, hits=victim.hits,
                age_s=round(time.monotonic() - victim.t_insert, 3),
                entries_left=n_entries, bytes_left=bytes_total)
        logger.debug("Prefix stored: %s span %d (%d bytes, %d entries, "
                     "%d evicted).", k[:12], span, nbytes,
                     n_entries, len(evicted))
        return nbytes

    def _release_victim_pages(self, victim: _Entry) -> None:
        """Return an evicted/cleared paged entry's page references to
        the pool (pages whose last owner was the store go back on the
        free list — eviction RECLAIMS capacity, exactly like freeing a
        pane copy did in the contiguous layout)."""
        if victim.pages is not None and self.page_pool is not None:
            for p in victim.pages:
                self.page_pool.decref(p)

    def clear(self) -> None:
        """Drop every entry, releasing paged page references. The paged
        engine restart path calls this: stored entries reference pages
        of the ABOUT-TO-BE-REPLACED pool arrays, so unlike the
        contiguous store (whose private pane copies survive a cache
        rebuild) they cannot outlive a restart."""
        with self._lock:
            victims = list(self._entries.values())
            self._entries.clear()
            self.bytes_total = 0
        for victim in victims:
            self._release_victim_pages(victim)

    # -- introspection -----------------------------------------------------

    @property
    def n_entries(self) -> int:
        return len(self._entries)

    def hit_ratio(self) -> Optional[float]:
        with self._lock:
            hits, misses = self.n_hits, self.n_misses
        n = hits + misses
        return (hits / n) if n else None

    def bytes_by_tag(self) -> Dict[str, int]:
        """Per-namespace byte attribution for the memory ledger: tag ->
        total pane bytes ("external" for raw-key imports, whose donor
        tag is hashed into the key but not transported)."""
        out: Dict[str, int] = {}
        with self._lock:
            for e in self._entries.values():
                tag = e.tag if e.tag is not None else "external"
                out[tag] = out.get(tag, 0) + e.nbytes
        return out

    def pinned_bytes(self) -> Tuple[int, List[str]]:
        """(bytes, keys) of currently pinned entries. Pins are transient
        by design — held only across one in-flight pane copy under the
        engine lock — so anything still pinned at a cadence boundary is
        an orphan: the memory ledger's ``pinned_orphan`` probe turns a
        non-empty answer into a ``memory_drift`` event."""
        with self._lock:
            pinned = [(e.key, e.nbytes) for e in self._entries.values()
                      if e.pins > 0]
        return sum(nb for _k, nb in pinned), [k for k, _nb in pinned]

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.bytes_total,
                "budget_bytes": self.budget_bytes,
                "hits": self.n_hits,
                "misses": self.n_misses,
                "inserts": self.n_inserts,
                "evictions": self.n_evictions,
                "insert_skips": self.n_insert_skips,
                "chunk_tokens": self.chunk_tokens,
                "pane_tokens": self.pane_tokens,
            }
