"""Serving fault isolation: injectable fault hooks + the tick-watchdog
supervisor that restarts a wedged decode loop.

The failure classes this targets mirror the training tier's
(`training/resilience.py`, `tests/test_fault_injection.py`), re-cast for
a server that must stay up:

  - a POISON REQUEST (bad prompt, raising client callback, prefill that
    trips a bug) must fail alone — its co-residents' token streams stay
    bit-identical to a fault-free run (the engine's per-request isolation;
    test-asserted);
  - NON-FINITE LOGITS (numerically-poisoned KV state, flaky HBM) must
    retire the offending slot with an error status instead of streaming
    garbage tokens to a client (the engine's in-graph finite guard);
  - a HUNG TICK (device wedged in a collective, runtime deadlock) must
    produce a flight record — every thread's stack + device memory, via
    ``obs/stall.StallDetector`` — and then a bounded-backoff engine
    RESTART that fails only the in-flight requests, keeps the queue, and
    serves new traffic with ZERO recompiles (the compiled programs and
    their CompileWatchers survive the restart; only the KV cache and the
    loop thread are replaced).

``FaultHooks`` is the injection surface the serving fault tests drive —
every hook is a no-op in production. Hooks run INSIDE the engine lock at
well-defined points of the tick, so an injected hang is indistinguishable
from a real one to the watchdog.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from building_llm_from_scratch_tpu.obs.stall import StallDetector
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


class FaultHooks:
    """Injectable fault points for the serving engine (all no-op by
    default; tests replace individual attributes with closures).

    - ``before_tick(engine)``: start of every tick, inside the engine
      lock. Block here to simulate a hung tick; raise to simulate a
      batch-wide loop fault.
    - ``before_prefill(request)``: just before a request's prefill
      program runs. Raise to make THIS request a poison request — the
      engine fails it alone.
    - ``poison_nan(request) -> bool``: return True to overwrite the
      request's freshly-prefilled KV rows with NaN, so the next decode
      tick produces non-finite logits for that slot IN-GRAPH (exercises
      the finite-logit guard without a second compiled program).
    - ``after_token(request, token)``: after each accepted token, inside
      the lock. Sleep here to simulate a slow consumer stretching ticks.
    """

    def before_tick(self, engine) -> None:
        pass

    def before_prefill(self, request) -> None:
        pass

    def poison_nan(self, request) -> bool:
        return False

    def after_token(self, request, token) -> None:
        pass


class EngineSupervisor:
    """Tick watchdog + restart policy for one ``DecodeEngine``.

    A per-tick heartbeat feeds an ``obs/stall.StallDetector`` configured
    to fire exactly at ``tick_timeout_s`` (``median_floor`` pinned to the
    timeout disables the adaptive early trigger: serving ticks are
    uniform, and the engine heartbeats through idle waits too, so the
    fixed timeout is the right contract). On fire, the detector has
    already dumped every thread's stack + device memory (the flight
    record); the supervisor then asks the engine to restart its decode
    loop. Restarts are bounded: ``max_restarts`` total, with exponential
    backoff starting at ``backoff_s`` — a persistently-wedged device
    fails the engine loudly instead of flapping forever.
    """

    def __init__(self, engine, tick_timeout_s: float,
                 max_restarts: int = 3, backoff_s: float = 0.5):
        if tick_timeout_s <= 0:
            raise ValueError(
                f"tick_timeout_s must be > 0, got {tick_timeout_s}")
        self.engine = engine
        self.tick_timeout_s = float(tick_timeout_s)
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        # serializes concurrent stall callbacks (detector thread vs a
        # re-armed fire landing mid-recovery); in the cross-module lock
        # graph (graft-lint GL032) this lock sits ABOVE the engine's
        # _restart_lock/_lock — never acquire it from engine code
        self._lock = threading.Lock()
        self.n_fires = 0                         # guarded-by: _lock
        self.detector = StallDetector(
            timeout=self.tick_timeout_s,
            median_floor=self.tick_timeout_s,
            first_grace=1.0,
            poll_interval=min(0.25, self.tick_timeout_s / 4),
            on_stall=self._on_stall)

    # -- heartbeat (engine loop thread) ----------------------------------

    def notify_tick(self) -> None:
        self.detector.notify_step()

    # -- watchdog fire (detector thread) ---------------------------------

    def _on_stall(self, elapsed: float, threshold: float) -> None:
        # the detector already dumped the flight record (stacks + device
        # memory + a `stall` event); what remains is the recovery action
        with self._lock:
            self.n_fires += 1
            logger.error(
                "Serving tick hung for %.1fs (threshold %.1fs): "
                "restarting the decode loop (watchdog fire %d).",
                elapsed, threshold, self.n_fires)
            if not self.engine._restart(
                    reason="hung_tick",
                    detail=f"tick made no progress for {elapsed:.1f}s"):
                self.engine._fail_all(
                    f"hung tick and restart budget exhausted "
                    f"({self.max_restarts} restarts)")

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "EngineSupervisor":
        self.detector.start()
        return self

    def stop(self) -> None:
        self.detector.stop()


def make_serve_stall_detector(timeout_s: float,
                              on_stall: Optional[Callable] = None
                              ) -> StallDetector:
    """A plain flight-recorder StallDetector for ``--mode serve`` without
    the supervisor (``--stall_timeout``): dumps stacks on a hung tick,
    restarts nothing. Heartbeats come from the engine loop."""
    return StallDetector(timeout=float(timeout_s),
                         median_floor=float(timeout_s),
                         first_grace=2.0, on_stall=on_stall)
