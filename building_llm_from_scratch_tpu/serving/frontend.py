"""Serving frontends: the ``serve`` CLI mode (JSONL batch + minimal HTTP).

Two dependency-free ways to put load on the engine:

  - JSONL batch (``--serve_prompts requests.jsonl``): one request per
    line — ``{"prompt": "...", "max_new_tokens": 32, "temperature": 0.7,
    "top_k": 40, "seed": 1, "deadline_s": 30}`` (or ``"prompt_ids":
    [..]``). Results stream to ``--serve_out`` (default stdout) as JSONL,
    one line per request in submission order — each line is flushed the
    moment its in-order handle completes, so a crash or drain never loses
    finished work. Submission uses blocking backpressure: a full queue
    stalls the reader instead of rejecting.
  - HTTP (``--serve_port``): a stdlib ``http.server`` endpoint —
    ``POST /generate`` with the same JSON fields returns the generated
    text + telemetry; ``GET /healthz`` reports a structured stats
    snapshot (state, uptime, ticks, occupancy, queue, restarts, request
    counters); ``GET /metrics`` is Prometheus text exposition (latency
    histograms, occupancy/queue gauges, SLO burn rate) for scraping.
    Status mapping: 429 + Retry-After for queue-full AND SLO shed, 503 +
    Retry-After while draining, 504 for queue-expired deadlines and
    handler timeouts (the timed-out request is CANCELLED, freeing its
    slot), 413 for oversized bodies, 400 for malformed JSON, 500 only
    for engine-side faults.

Run-mode resilience (``run_serve``): SIGTERM/SIGINT arm
``training/resilience.GracefulStopper``; a watcher thread then drains the
engine (admission closed, in-flight finishes within ``--drain_timeout``,
the remainder fails with reason ``preempted``) and stops the HTTP server,
so a preempted replica exits 0 with every completed result already
written.
"""

from __future__ import annotations

import json
import sys
import threading
from typing import List, Optional

from building_llm_from_scratch_tpu.serving.engine import DecodeEngine
from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    PromptTooLongError,
    QueueFullError,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import (
    Request,
    RequestExpiredError,
    SamplingParams,
)
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


def parse_adapter_specs(spec: str, flag: str = "--serve_adapters") -> dict:
    """``name=path[,name=path...]`` -> {name: path}. Names must be
    unique. Shared by ``--serve_adapters`` (adapter artifacts) and the
    fused-finetune fleet's ``--fleet_jobs`` (per-tenant record files) —
    ``flag`` only labels the error messages."""
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"{flag} entry '{part}' is not name=path")
        name, path = part.split("=", 1)
        name, path = name.strip(), path.strip()
        if not name or not path:
            raise ValueError(f"{flag} entry '{part}' is not name=path")
        if name in out:
            raise ValueError(f"{flag} names '{name}' twice")
        out[name] = path
    if not out:
        raise ValueError(f"{flag} is empty")
    return out


def params_from_record(rec: dict, default_max_new: int) -> SamplingParams:
    return SamplingParams(
        max_new_tokens=int(rec.get("max_new_tokens", default_max_new)),
        temperature=float(rec.get("temperature", 0.0)),
        top_k=(int(rec["top_k"]) if rec.get("top_k") else None),
        seed=int(rec.get("seed", 0)),
        eos_id=(int(rec["eos_id"]) if "eos_id" in rec
                and rec["eos_id"] is not None else None),
        ignore_eos=bool(rec.get("ignore_eos", False)),
        # `is not None`, not truthiness: deadline_s=0 must flow through to
        # engine.submit's `deadline_s must be > 0` ValueError (HTTP 400),
        # not be silently promoted to "no deadline"
        deadline_s=(float(rec["deadline_s"])
                    if rec.get("deadline_s") is not None else None),
        # LoRA adapter by registry name; unknown names reject at submit
        # (ValueError -> HTTP 400)
        adapter=(str(rec["adapter"])
                 if rec.get("adapter") is not None else None),
        # per-request speculative opt-out ("spec": false) — tokens are
        # bit-identical either way; this only trades draft compute
        spec=bool(rec.get("spec", True)),
    )


def result_record(req: Request, text: Optional[str] = None) -> dict:
    rec = req.summary()
    rec["token_ids"] = [int(t) for t in req.output_ids]
    rec["text"] = req.text if text is None else text
    return rec


def error_record(req: Request) -> dict:
    """The JSONL line for a request the engine failed/shed/preempted:
    still one line in submission order, with the failure surfaced instead
    of silently missing output."""
    rec = req.summary()
    rec["error"] = req.error
    return rec


def serve_jsonl(engine: DecodeEngine, prompts_path: str,
                out_path: Optional[str], default_max_new: int) -> List[dict]:
    """Pump a JSONL request file through the engine (blocking
    backpressure), write one result line per request in submission order.

    Fault/drain-tolerant: a failed, expired or preempted request becomes
    an ``error`` line instead of crashing the pump, and admission closing
    mid-file (drain) records the unsubmitted remainder as shed — every
    COMPLETED request's line is on disk either way."""
    handles: List[Request] = []
    shed: List[dict] = []
    with open(prompts_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            prompt = rec.get("prompt_ids", rec.get("prompt"))
            if prompt is None:
                raise ValueError(
                    f"{prompts_path}:{lineno}: needs 'prompt' or "
                    "'prompt_ids'")
            try:
                handles.append(engine.submit(
                    prompt, params_from_record(rec, default_max_new),
                    block=True))
            except (EngineDrainingError, SLOShedError,
                    QueueFullError) as e:
                shed.append({"line": lineno, "error": str(e),
                             "finish_reason": "shed"})
    # write each result as its in-order handle completes (flushed per
    # line) so finished work is durable even if a later request crashes
    # the process
    results: List[dict] = []
    out = open(out_path, "w") if out_path else sys.stdout
    try:
        for h in handles:
            try:
                rec = result_record(h.result())
            except (RuntimeError, RequestExpiredError):
                rec = error_record(h)
            results.append(rec)
            out.write(json.dumps(rec) + "\n")
            out.flush()
        for rec in shed:
            results.append(rec)
            out.write(json.dumps(rec) + "\n")
            out.flush()
    finally:
        if out_path:
            out.close()
    n_ok = sum(1 for r in results if "error" not in r)
    logger.info("Served %d/%d JSONL requests (%d tokens; %d failed/shed).",
                n_ok, len(results),
                sum(r.get("n_tokens", 0) for r in results),
                len(results) - n_ok)
    return results


# ---------------------------------------------------------------------------
# HTTP endpoint (stdlib only)
# ---------------------------------------------------------------------------

def make_http_server(engine: DecodeEngine, port: int,
                     host: str = "127.0.0.1",
                     request_timeout_s: float = 300.0,
                     max_body_bytes: int = 1 << 20):
    """Build (not start) a ThreadingHTTPServer bound to ``port`` (0 = any
    free port; read the actual one off ``server.server_address``).
    Loopback-only by default — the endpoint is unauthenticated, so
    exposing it (``host="0.0.0.0"`` / ``--serve_host``) is opt-in.

    Input hardening: bodies over ``max_body_bytes`` get 413 without being
    read, malformed/mistyped JSON gets 400 (never a handler traceback),
    and a handler timeout CANCELS the underlying request so its slot
    stops decoding for a client that already hung up."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        # socket read timeout (BaseRequestHandler.setup applies it): a
        # client that sends Content-Length: N but stalls mid-body would
        # otherwise block rfile.read(n) — and its handler thread — forever
        # (slow-loris); on timeout http.server drops the connection
        timeout = 60

        def log_message(self, fmt, *args):          # route through our logger
            logger.debug("http: " + fmt, *args)

        def _json(self, code: int, payload: dict,
                  retry_after: Optional[float] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                # RFC 7231 delay-seconds (integer, >= 1)
                self.send_header("Retry-After",
                                 str(max(1, int(round(retry_after)))))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                # Prometheus text exposition: counters (requests by
                # outcome, restarts, per-phase tick seconds), gauges
                # (occupancy, queue depth, draining, SLO burn rate) and
                # the TTFT/TPOT/e2e/queue-wait histograms — what the
                # replica router / alerting scrape
                body = engine.prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path != "/healthz":
                return self._json(404, {"error": "unknown path"})
            # one method for both binds: a DecodeEngine answers its
            # historical structured snapshot, an EngineRouter answers
            # the fleet view (per-replica status + routing counters)
            self._json(200, engine.healthz_payload())

        def do_POST(self):
            if self.path != "/generate":
                return self._json(404, {"error": "unknown path"})
            try:
                n = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                return self._json(400, {"error": "bad Content-Length"})
            if n < 0:
                return self._json(400, {"error": "bad Content-Length"})
            if n > max_body_bytes:
                # refuse WITHOUT reading: an oversized body must cost the
                # server a header parse, not max_body_bytes of RAM
                return self._json(413, {
                    "error": f"body {n} bytes exceeds the "
                             f"{max_body_bytes}-byte limit"})
            try:
                rec = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(rec, dict):
                    return self._json(
                        400, {"error": "body must be a JSON object"})
                prompt = rec.get("prompt_ids", rec.get("prompt"))
                if prompt is None:
                    return self._json(
                        400, {"error": "missing 'prompt'/'prompt_ids'"})
                params = params_from_record(
                    rec, engine.default_max_new_tokens)
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                # TypeError: wrong-typed JSON fields (int({}) etc.) —
                # still the client's malformed input, still a 400
                return self._json(400, {"error": str(e)})
            try:
                handle = engine.submit(prompt, params, block=False)
            except EngineDrainingError as e:     # drain: try a peer
                return self._json(503, {"error": str(e)},
                                  retry_after=e.retry_after_s or 1.0)
            except SLOShedError as e:            # deadline unmeetable now
                return self._json(429, {
                    "error": str(e), "shed": True},
                    retry_after=e.retry_after_s or 1.0)
            except QueueFullError:
                return self._json(429, {
                    "error": "request queue full — retry later",
                    "queue_capacity": engine.queue_capacity()},
                    retry_after=engine.estimate_queue_clear_s() or 1.0)
            except PromptTooLongError as e:
                # 413: the client must shorten the payload, not retry
                # it. `max_prompt` is the seq-sharded ceiling on
                # --serve_sp engines (pane x sp).
                return self._json(413, {
                    "error": str(e), "max_prompt": e.limit,
                    "prompt_tokens": e.prompt_tokens})
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            except RuntimeError as e:           # engine is dead
                return self._json(500, {"error": str(e)})
            try:
                handle.result(timeout=request_timeout_s)
            except RequestExpiredError as e:    # deadline shed in queue
                return self._json(504, {"error": str(e), "expired": True},
                                  retry_after=engine.estimate_queue_clear_s())
            except TimeoutError as e:
                # cancel so the slot stops decoding for a client whose
                # handler already gave up (it would otherwise burn the
                # slot to max_new_tokens)
                engine.cancel(handle)
                return self._json(504, {"error": str(e)})
            except RuntimeError as e:           # engine failed the request
                return self._json(500, {"error": str(e)})
            self._json(200, result_record(handle))

    return ThreadingHTTPServer((host, port), Handler)


def serve_http(engine: DecodeEngine, port: int,
               host: str = "127.0.0.1",
               server=None) -> None:
    server = server or make_http_server(engine, port, host=host)
    host, real_port = server.server_address[:2]
    logger.info("Serving on http://%s:%d (POST /generate, GET /healthz); "
                "Ctrl-C to stop.", host, real_port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        logger.info("Shutting down HTTP server.")
    finally:
        server.server_close()


# ---------------------------------------------------------------------------
# the `serve` run mode (main.py dispatches here)
# ---------------------------------------------------------------------------

def run_serve(args, comps, metric_logger) -> DecodeEngine:
    """Warm the engine and serve --serve_prompts and/or --serve_port.
    ``comps``/``metric_logger`` come from main.py's shared bootstrap
    (metrics sink + compile cache + build_components + run-metadata
    header) so serve telemetry can't diverge from training telemetry.
    Returns the (shut-down) engine for callers/tests — an
    ``EngineRouter`` when ``--serve_replicas > 1``.

    Resilience wiring: SIGTERM/SIGINT trigger a graceful drain
    (``--drain_timeout``; rolling per replica in router mode, with
    queued work re-dispatched); ``--serve_tick_timeout`` arms the fault
    supervisor (hung-tick flight record + bounded-backoff restart);
    ``--stall_timeout`` alone arms just the flight recorder."""
    from building_llm_from_scratch_tpu.serving.kvcache import KVCachePolicy

    prefix_on = getattr(args, "serve_prefix_cache", "off") == "on"
    paged_on = getattr(args, "serve_kv_paged", "off") == "on"
    serve_sp = getattr(args, "serve_sp", 1)
    chunk = getattr(args, "serve_prefill_chunk", 0)
    if (prefix_on or paged_on or serve_sp > 1) and chunk <= 0:
        chunk = 64          # these paths all imply chunked prefill
        logger.info("--serve_%s on: defaulting --serve_prefill_chunk "
                    "to 64.",
                    "prefix_cache" if prefix_on
                    else ("kv_paged" if paged_on else "sp"))
    kv_policy = KVCachePolicy(
        kv_quant=getattr(args, "serve_kv_quant", "model"),
        prefix_cache=prefix_on,
        prefill_chunk=chunk,
        prefix_budget_bytes=int(
            getattr(args, "serve_prefix_budget_mb", 256.0) * 1024 ** 2),
        paged=paged_on,
        page_tokens=getattr(args, "serve_kv_page_tokens", 16),
    )
    n_replicas = getattr(args, "serve_replicas", 1)
    serve_tp = getattr(args, "serve_tp", 1)
    max_prompt = getattr(args, "serve_max_prompt", 0) or None
    n_workers = getattr(args, "serve_workers", 0)
    if n_workers > 0:
        # cross-process fleet (serving/fleet.py): N supervised worker
        # PROCESSES behind one engine-shaped facade. Workers rebuild
        # cfg + params from the spec (init_params is seed-deterministic;
        # --init_params_from loads the same artifact in every process),
        # so the parent's params never cross the process boundary — and
        # a worker crash can only ever take down its own replica.
        from building_llm_from_scratch_tpu.serving.fleet import (
            ProcessFleet,
        )
        from building_llm_from_scratch_tpu.serving.worker import (
            EngineSpec,
        )

        adapter_paths = (parse_adapter_specs(args.serve_adapters)
                         if getattr(args, "serve_adapters", None)
                         else None)
        spec = EngineSpec(
            model=args.model, size=args.num_params,
            dtype=args.data_type, debug=args.debug, seed=args.seed,
            init_params_from=getattr(args, "init_params_from", None),
            tokenizer=("byte" if args.byte_tokenizer else "none"),
            tp=serve_tp,
            engine=dict(
                n_slots=args.serve_slots,
                max_len=(args.serve_max_len or None),
                max_queue=args.serve_max_queue,
                max_top_k=args.serve_max_top_k,
                default_max_new_tokens=args.serve_max_new_tokens,
                default_deadline_s=(args.serve_deadline_s or None),
                tick_timeout_s=args.serve_tick_timeout,
                max_restarts=args.serve_max_restarts,
                metrics_every=args.serve_metrics_every,
                max_prompt=max_prompt),
            kv_policy=dict(
                kv_quant=kv_policy.kv_quant,
                prefix_cache=kv_policy.prefix_cache,
                prefill_chunk=kv_policy.prefill_chunk,
                prefix_budget_bytes=kv_policy.prefix_budget_bytes,
                paged=kv_policy.paged,
                page_tokens=kv_policy.page_tokens),
            adapters=adapter_paths,
            spec_k=getattr(args, "serve_spec_k", 0),
        )
        fleet = ProcessFleet(
            spec, n_workers, tokenizer=comps.tokenizer,
            max_restarts=args.serve_max_restarts,
            drain_timeout_s=args.drain_timeout,
            default_max_new_tokens=args.serve_max_new_tokens,
            metrics_base=metric_logger.jsonl_path)
        fleet.start()
        return _serve_frontends(args, fleet, [], metric_logger)
    if n_replicas > 1:
        # fleet tier (serving/router.py): N engine replicas — each on
        # its own mesh plan (tp devices apiece, disjoint when the pool
        # allows) with its own adapter registry — behind one router
        # surface. The frontends below bind the router exactly like an
        # engine. The 1-replica branch stays the historical path: no
        # router object exists there at all.
        from building_llm_from_scratch_tpu.serving.router import (
            EngineRouter,
        )

        specs = (parse_adapter_specs(args.serve_adapters)
                 if getattr(args, "serve_adapters", None) else None)
        engine = EngineRouter.build(
            comps.cfg, comps.params, comps.tokenizer,
            n_replicas=n_replicas, tp=serve_tp, sp=serve_sp,
            max_prompt=max_prompt,
            adapter_specs=specs,
            adapter_capacity=args.serve_adapter_slots,
            kv_policy=kv_policy,
            n_slots=args.serve_slots,
            max_len=(args.serve_max_len or None),
            max_queue=args.serve_max_queue,
            max_top_k=args.serve_max_top_k,
            default_max_new_tokens=args.serve_max_new_tokens,
            default_deadline_s=(args.serve_deadline_s or None),
            tick_timeout_s=args.serve_tick_timeout,
            max_restarts=args.serve_max_restarts,
            metrics_every=args.serve_metrics_every,
            spec_k=getattr(args, "serve_spec_k", 0),
        )
        stalls = []
        if args.stall_timeout > 0:
            # same semantics as the single-engine path: without the full
            # supervisor, each replica gets its OWN flight recorder (a
            # shared one would stay silent while healthy replicas tick
            # past a wedged one)
            from building_llm_from_scratch_tpu.serving.supervisor import (
                make_serve_stall_detector,
            )

            for rep in engine.engines:
                if rep.supervisor is None:
                    det = make_serve_stall_detector(args.stall_timeout)
                    rep.set_heartbeat(det.notify_step)
                    stalls.append(det)
        engine.warmup()
        engine.start()
        for det in stalls:
            det.start()
        return _serve_frontends(args, engine, stalls, metric_logger)

    adapters = None
    if getattr(args, "serve_adapters", None):
        # --serve_adapters name=path[,name=path...]: build the multi-
        # tenant LoRA registry before the engine compiles (the pool's
        # static capacity/rank are baked into the decode program)
        from building_llm_from_scratch_tpu.serving.adapters import (
            AdapterRegistry,
        )

        specs = parse_adapter_specs(args.serve_adapters)
        adapters = AdapterRegistry.from_artifacts(
            comps.cfg, comps.params, specs,
            capacity=args.serve_adapter_slots)
        logger.info("Adapter registry: %d adapter(s) loaded (%s), "
                    "capacity %d.", adapters.n_loaded,
                    ", ".join(adapters.names()), adapters.capacity)

    mesh_plan = None
    if serve_tp > 1 or serve_sp > 1:
        # single sharded replica: tp shards the whole compiled program
        # family (NamedSharding'd weights + heads-sharded slot KV over
        # the `model` mesh axis); sp sequence-shards chunk prefill over
        # the `seq` axis so long prompts admit beyond one device's pane
        # (parallel/sharding.serve_mesh_plan — the two compose)
        from building_llm_from_scratch_tpu.parallel.sharding import (
            serve_mesh_plan,
        )

        mesh_plan = serve_mesh_plan(serve_tp, sp=serve_sp)
    engine = DecodeEngine(
        comps.cfg, comps.params, comps.tokenizer,
        n_slots=args.serve_slots,
        max_len=(args.serve_max_len or None),
        max_queue=args.serve_max_queue,
        max_top_k=args.serve_max_top_k,
        default_max_new_tokens=args.serve_max_new_tokens,
        default_deadline_s=(args.serve_deadline_s or None),
        tick_timeout_s=args.serve_tick_timeout,
        max_restarts=args.serve_max_restarts,
        metrics_every=args.serve_metrics_every,
        adapters=adapters,
        kv_policy=kv_policy,
        spec_k=getattr(args, "serve_spec_k", 0),
        mesh_plan=mesh_plan,
        max_prompt=max_prompt,
    )
    stall = None
    if args.stall_timeout > 0 and engine.supervisor is None:
        # flight recorder without the supervisor: a hung tick still dumps
        # every thread's stack + device memory (obs/stall.py), it just
        # isn't auto-restarted
        from building_llm_from_scratch_tpu.serving.supervisor import (
            make_serve_stall_detector,
        )

        stall = make_serve_stall_detector(args.stall_timeout)
        engine.set_heartbeat(stall.notify_step)
    engine.warmup()
    engine.start()
    if stall is not None:
        stall.start()
    return _serve_frontends(args, engine,
                            [stall] if stall is not None else [],
                            metric_logger)


def _serve_frontends(args, engine, stalls, metric_logger):
    """Drive the frontends (JSONL pump and/or HTTP) + signal-drain wiring
    over one warmed, started ``engine`` — a ``DecodeEngine`` or an
    ``EngineRouter``; both expose the surface this loop needs (submit/
    drain/shutdown/draining/healthz/metrics). ``stalls``: already-started
    flight recorders to stop on exit (one per replica in router mode)."""
    from building_llm_from_scratch_tpu.training.resilience import (
        GracefulStopper,
    )

    server = (make_http_server(engine, args.serve_port,
                               host=args.serve_host)
              if args.serve_port else None)
    stopper = GracefulStopper()
    drained = threading.Event()

    def _drain_on_signal():
        # poll the stopper flag (the handler itself must stay tiny and
        # async-signal-safe); on preemption: close admission, finish
        # in-flight within --drain_timeout, then unblock the frontends
        while not drained.wait(0.1):
            if stopper.requested:
                engine.drain(timeout=args.drain_timeout)
                if server is not None:
                    server.shutdown()
                return

    watcher = threading.Thread(target=_drain_on_signal,
                               name="serve-drain-watch", daemon=True)
    try:
        with stopper:
            watcher.start()
            http_thread = None
            if server is not None and args.serve_prompts:
                # both workloads: HTTP serves CONCURRENTLY with the JSONL
                # pump — a /metrics scrape or /generate call must not
                # queue behind the batch (the engine is thread-safe; the
                # drain path shuts the server down via server.shutdown())
                http_thread = threading.Thread(
                    target=serve_http, name="serve-http", daemon=True,
                    args=(engine, args.serve_port),
                    kwargs=dict(host=args.serve_host, server=server))
                http_thread.start()
            if args.serve_prompts:
                serve_jsonl(engine, args.serve_prompts, args.serve_out,
                            args.serve_max_new_tokens)
            if http_thread is not None:
                http_thread.join()      # until SIGTERM/SIGINT stops it
            elif server is not None:
                serve_http(engine, args.serve_port, host=args.serve_host,
                           server=server)
    finally:
        drained.set()
        watcher.join(timeout=5)
        if stopper.requested and not engine.draining:
            engine.drain(timeout=args.drain_timeout)
        engine.shutdown()
        for det in stalls:
            det.stop()
        metric_logger.close()
    return engine
