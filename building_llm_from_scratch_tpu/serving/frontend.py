"""Serving frontends: the ``serve`` CLI mode (JSONL batch + minimal HTTP).

Two dependency-free ways to put load on the engine:

  - JSONL batch (``--serve_prompts requests.jsonl``): one request per
    line — ``{"prompt": "...", "max_new_tokens": 32, "temperature": 0.7,
    "top_k": 40, "seed": 1}`` (or ``"prompt_ids": [..]``). Results stream
    to ``--serve_out`` (default stdout) as JSONL, one line per request in
    submission order. Submission uses blocking backpressure: a full queue
    stalls the reader instead of rejecting.
  - HTTP (``--serve_port``): a stdlib ``http.server`` endpoint —
    ``POST /generate`` with the same JSON fields returns the generated
    text + telemetry; a full queue returns 429 (reject-over-capacity);
    ``GET /healthz`` reports slot/queue state.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from building_llm_from_scratch_tpu.serving.engine import DecodeEngine
from building_llm_from_scratch_tpu.serving.queue import QueueFullError
from building_llm_from_scratch_tpu.serving.request import (
    Request,
    SamplingParams,
)
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


def params_from_record(rec: dict, default_max_new: int) -> SamplingParams:
    return SamplingParams(
        max_new_tokens=int(rec.get("max_new_tokens", default_max_new)),
        temperature=float(rec.get("temperature", 0.0)),
        top_k=(int(rec["top_k"]) if rec.get("top_k") else None),
        seed=int(rec.get("seed", 0)),
        eos_id=(int(rec["eos_id"]) if "eos_id" in rec
                and rec["eos_id"] is not None else None),
        ignore_eos=bool(rec.get("ignore_eos", False)),
    )


def result_record(req: Request, text: Optional[str] = None) -> dict:
    rec = req.summary()
    rec["token_ids"] = [int(t) for t in req.output_ids]
    rec["text"] = req.text if text is None else text
    return rec


def serve_jsonl(engine: DecodeEngine, prompts_path: str,
                out_path: Optional[str], default_max_new: int) -> List[dict]:
    """Pump a JSONL request file through the engine (blocking
    backpressure), write one result line per request in submission order."""
    handles: List[Request] = []
    with open(prompts_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            prompt = rec.get("prompt_ids", rec.get("prompt"))
            if prompt is None:
                raise ValueError(
                    f"{prompts_path}:{lineno}: needs 'prompt' or "
                    "'prompt_ids'")
            handles.append(engine.submit(
                prompt, params_from_record(rec, default_max_new),
                block=True))
    # write each result as its in-order handle completes (flushed per
    # line) so finished work is durable even if a later request crashes
    # the process
    results: List[dict] = []
    out = open(out_path, "w") if out_path else sys.stdout
    try:
        for h in handles:
            rec = result_record(h.result())
            results.append(rec)
            out.write(json.dumps(rec) + "\n")
            out.flush()
    finally:
        if out_path:
            out.close()
    logger.info("Served %d JSONL requests (%d tokens).", len(results),
                sum(r["n_tokens"] for r in results))
    return results


# ---------------------------------------------------------------------------
# HTTP endpoint (stdlib only)
# ---------------------------------------------------------------------------

def make_http_server(engine: DecodeEngine, port: int,
                     host: str = "127.0.0.1",
                     request_timeout_s: float = 300.0):
    """Build (not start) a ThreadingHTTPServer bound to ``port`` (0 = any
    free port; read the actual one off ``server.server_address``).
    Loopback-only by default — the endpoint is unauthenticated, so
    exposing it (``host="0.0.0.0"`` / ``--serve_host``) is opt-in."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):          # route through our logger
            logger.debug("http: " + fmt, *args)

        def _json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path != "/healthz":
                return self._json(404, {"error": "unknown path"})
            self._json(200, {
                "slots": engine.n_slots,
                "active": engine.scheduler.n_active,
                "queue_depth": len(engine.queue),
                "queue_capacity": engine.queue.max_size,
                "warmed_up": engine.warmed_up,
            })

        def do_POST(self):
            if self.path != "/generate":
                return self._json(404, {"error": "unknown path"})
            try:
                n = int(self.headers.get("Content-Length", 0))
                rec = json.loads(self.rfile.read(n) or b"{}")
                prompt = rec.get("prompt_ids", rec.get("prompt"))
                if prompt is None:
                    return self._json(
                        400, {"error": "missing 'prompt'/'prompt_ids'"})
                params = params_from_record(
                    rec, engine.default_max_new_tokens)
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                # TypeError: wrong-typed JSON fields (int({}) etc.) —
                # still the client's malformed input, still a 400
                return self._json(400, {"error": str(e)})
            try:
                handle = engine.submit(prompt, params, block=False)
            except QueueFullError:
                return self._json(429, {
                    "error": "request queue full — retry later",
                    "queue_capacity": engine.queue.max_size})
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            except RuntimeError as e:           # engine is dead
                return self._json(500, {"error": str(e)})
            try:
                handle.result(timeout=request_timeout_s)
            except TimeoutError as e:
                return self._json(504, {"error": str(e)})
            except RuntimeError as e:           # engine failed the request
                return self._json(500, {"error": str(e)})
            self._json(200, result_record(handle))

    return ThreadingHTTPServer((host, port), Handler)


def serve_http(engine: DecodeEngine, port: int,
               host: str = "127.0.0.1") -> None:
    server = make_http_server(engine, port, host=host)
    host, real_port = server.server_address[:2]
    logger.info("Serving on http://%s:%d (POST /generate, GET /healthz); "
                "Ctrl-C to stop.", host, real_port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        logger.info("Shutting down HTTP server.")
    finally:
        server.server_close()


# ---------------------------------------------------------------------------
# the `serve` run mode (main.py dispatches here)
# ---------------------------------------------------------------------------

def run_serve(args, comps, metric_logger) -> DecodeEngine:
    """Warm the engine and serve --serve_prompts and/or --serve_port.
    ``comps``/``metric_logger`` come from main.py's shared bootstrap
    (metrics sink + compile cache + build_components + run-metadata
    header) so serve telemetry can't diverge from training telemetry.
    Returns the (shut-down) engine for callers/tests."""
    engine = DecodeEngine(
        comps.cfg, comps.params, comps.tokenizer,
        n_slots=args.serve_slots,
        max_len=(args.serve_max_len or None),
        max_queue=args.serve_max_queue,
        max_top_k=args.serve_max_top_k,
        default_max_new_tokens=args.serve_max_new_tokens,
    )
    engine.warmup()
    engine.start()
    try:
        if args.serve_prompts:
            serve_jsonl(engine, args.serve_prompts, args.serve_out,
                        args.serve_max_new_tokens)
        if args.serve_port:
            serve_http(engine, args.serve_port, host=args.serve_host)
    finally:
        engine.shutdown()
        metric_logger.close()
    return engine
