"""Cross-process fleet: supervised worker replicas over the RPC transport.

``EngineRouter`` (serving/router.py) scales replicas inside ONE process
— one GIL, one blast radius. ``ProcessFleet`` keeps the router's
dispatch semantics (service-estimate ordering, fall-through admission,
drain re-dispatch with the SAME ``Request`` handles) but puts every
replica behind a process boundary:

  - each replica is a ``serving/worker.py`` subprocess with its own
    metrics JSONL, reached over the unix-socket RPC transport
    (control) plus a push channel (heartbeats + request progress);
  - a ``WorkerSupervisor`` per replica watches THREE death signals —
    missed heartbeats, process exit, and stdout pipe-EOF (kill -9
    closes the pipe before any timeout can fire) — and restarts the
    worker process with bounded exponential backoff;
  - on death, the dead worker's QUEUED requests re-dispatch onto
    survivors under their original handles (zero lost requests);
    requests already decoding fail with a typed ``worker_dead`` reason
    (their tokens died with the process — a silent re-run could emit
    duplicate text to a streaming client);
  - restart-budget exhaustion degrades the fleet to the survivors —
    ``healthz`` says ``degraded``, dispatch keeps flowing;
  - graceful drain ships the worker's hot ``PrefixStore`` panes over
    the transport to an adopting replica (keys are config-fingerprint
    derived, so they transfer verbatim) before the SIGTERM.

The fleet object is engine-shaped: ``make_http_server``/``serve_jsonl``
/``_serve_frontends`` drive it exactly like a ``DecodeEngine``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from building_llm_from_scratch_tpu.obs.metrics import (
    get_metrics,
    render_prometheus,
)
from building_llm_from_scratch_tpu.serving.engine import (
    queue_clear_estimate,
    service_estimate,
)
from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    QueueFullError,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import (
    FINISHED,
    FINISH_REJECTED,
    FINISH_SHED,
    Request,
    SamplingParams,
    next_request_id,
)
from building_llm_from_scratch_tpu.serving.transport import (
    RpcClient,
    RpcStats,
    TransportError,
    recv_frame,
    send_frame,
)
from building_llm_from_scratch_tpu.serving.worker import EngineSpec
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

_WORKER_MODULE = "building_llm_from_scratch_tpu.serving._worker_main"

#: Per-worker budget for one aggregated-/metrics scrape RPC, and the
#: whole-endpoint deadline: a dead or hung worker costs AT MOST this
#: much wall time — the endpoint then serves its cached series instead.
_SCRAPE_TIMEOUT_S = 0.4
_SCRAPE_DEADLINE_S = 0.9

#: Flight-recorder depth: the last N fleet incidents kept in memory for
#: post-mortem snapshots (ring — old rows fall off, never grows).
_INCIDENT_RING = 256

#: Minimum seconds between ``clock_sync`` emissions per worker (every
#: RPC refines the sample; only refreshes reach the JSONL).
_CLOCK_SYNC_EVERY_S = 5.0


def _labeled(key: str, replica: int, incarnation: int) -> str:
    """Merge ``replica``/``worker``/``incarnation`` into a metric key's
    label set. ``replica`` keeps the in-process router's convention;
    ``worker``/``incarnation`` are the fleet-scrape passthrough labels
    (a restarted worker's series are distinguishable from its previous
    life's)."""
    extra = (f'replica="{replica}",worker="{replica}",'
             f'incarnation="{incarnation}"')
    base, sep, labels = key.partition("{")
    if not sep:
        return f"{base}{{{extra}}}"
    return f"{base}{{{labels[:-1]},{extra}}}"


class _HistSnap:
    """Duck-typed stand-in for ``obs.metrics.Histogram``: a worker ships
    its histogram as the SNAPSHOT dict; ``render_prometheus`` only ever
    calls ``.snapshot()``."""

    __slots__ = ("_snap",)

    def __init__(self, snap: dict):
        self._snap = snap

    def snapshot(self) -> dict:
        return self._snap


class _FleetEntry:
    """Ledger row: one in-flight request's cross-process identity."""

    __slots__ = ("req", "prompt_ids", "params", "worker", "state",
                 "rpc_spans", "span_emitted", "incarnation")

    def __init__(self, req: Request, prompt_ids: List[int],
                 params: Dict[str, Any], worker: int):
        self.req = req
        self.prompt_ids = prompt_ids
        self.params = params
        self.worker = worker
        self.state = "queued"        # "queued" | "running"
        self.rpc_spans: List[dict] = []   # closed rpc:<method> children
        self.span_emitted = False    # exactly one trace tree, ever
        self.incarnation = 0         # worker's life number at dispatch

    def add_rpc(self, timing: dict) -> None:
        """``RpcClient.call`` timing hook → one ``rpc:<method>`` child
        on this request's span. The method rides in the NAME because
        ``log_span`` keeps only name/t0/dur_s on children."""
        self.rpc_spans.append({"name": "rpc:" + timing["method"],
                               "t0": timing["t0"],
                               "dur_s": timing["dur_s"]})


class WorkerSupervisor:
    """One replica's process + connections + liveness bookkeeping.

    Mutable liveness fields are written under the OWNING fleet's lock
    (the supervisor is not a standalone object — death/restart
    transitions need the fleet ledger atomically).
    """

    __slots__ = ("index", "socket_path", "metrics_path", "proc", "ctrl",
                 "events_sock", "pid", "alive", "stopped", "restarts",
                 "last_beat", "snapshot", "generation", "closing",
                 "out_of_dispatch", "incarnation", "last_beat_wall",
                 "clock", "last_clock_emit", "scrape", "last_metrics",
                 "last_metrics_wall")

    def __init__(self, index: int, socket_path: str,
                 metrics_path: Optional[str]):
        self.index = index
        self.socket_path = socket_path
        self.metrics_path = metrics_path
        self.proc: Optional[subprocess.Popen] = None
        self.ctrl: Optional[RpcClient] = None
        self.events_sock: Optional[socket.socket] = None
        self.pid: Optional[int] = None
        self.alive = False
        self.stopped = False         # permanent: drained or budget spent
        self.restarts = 0
        self.last_beat = 0.0
        self.snapshot: Optional[dict] = None
        self.generation = 0          # bumped per spawn; stale-event guard
        self.closing = False         # intentional teardown in progress
        self.out_of_dispatch = False
        self.incarnation = 0         # == restarts at spawn time
        self.last_beat_wall: Optional[float] = None  # worker's own stamp
        self.clock = None            # freshest RPC-derived ClockSample
        self.last_clock_emit = 0.0   # wall time of last clock_sync event
        self.scrape: Optional[RpcClient] = None  # metrics-only conn
        self.last_metrics: Optional[dict] = None  # cached /metrics reply
        self.last_metrics_wall = 0.0


class ProcessFleet:
    """N supervised worker processes behind one engine-shaped facade."""

    def __init__(self, spec: EngineSpec, n_workers: int, *,
                 tokenizer=None, socket_dir: Optional[str] = None,
                 metrics_base: Optional[str] = None,
                 heartbeat_s: float = 0.25,
                 heartbeat_timeout_s: Optional[float] = None,
                 max_restarts: int = 3, restart_backoff_s: float = 0.5,
                 call_timeout_s: float = 10.0,
                 ready_timeout_s: float = 180.0,
                 drain_timeout_s: float = 30.0,
                 default_max_new_tokens: Optional[int] = None):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.spec = spec
        self.n_workers = n_workers
        self.tokenizer = tokenizer
        self.heartbeat_s = heartbeat_s
        self.heartbeat_timeout_s = (heartbeat_timeout_s
                                    if heartbeat_timeout_s is not None
                                    else 20.0 * heartbeat_s)
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        self.call_timeout_s = float(call_timeout_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        if default_max_new_tokens is None:
            default_max_new_tokens = int(
                (spec.fake or {}).get("default_max_new_tokens")
                or spec.engine.get("default_max_new_tokens", 128))
        self.default_max_new_tokens = default_max_new_tokens
        self.warmed_up = False
        self._dir = socket_dir or tempfile.mkdtemp(prefix="fleet_")
        self.metrics_base = metrics_base
        self._lock = threading.Lock()
        self._requests: Dict[int, _FleetEntry] = {}    # guarded-by: _lock
        self._draining = False
        self._closing = False
        self.n_deaths = 0                              # guarded-by: _lock
        self.n_restarts = 0                            # guarded-by: _lock
        self.n_redispatched = 0                        # guarded-by: _lock
        self.n_failed_on_death = 0                     # guarded-by: _lock
        self.n_handoffs = 0                            # guarded-by: _lock
        self.rpc_stats = RpcStats()  # shared across every fleet client
        self._incidents: deque = deque(maxlen=_INCIDENT_RING)
        self._incident_seq = 0                         # guarded-by: _lock
        self.workers = [
            WorkerSupervisor(
                i, os.path.join(self._dir, f"w{i}.sock"),
                # each worker owns its metrics JSONL next to the
                # supervisor's: <base>.worker<i>.jsonl
                (f"{metrics_base}.worker{i}.jsonl"
                 if metrics_base else None))
            for i in range(n_workers)]
        self._monitor: Optional[threading.Thread] = None
        self._t0 = time.monotonic()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ProcessFleet":
        t0 = time.monotonic()
        get_metrics().event("serve_fleet", phase="build",
                            n_replicas=self.n_workers, tp=self.spec.tp)
        errs: List[BaseException] = []

        def boot(w: WorkerSupervisor) -> None:
            try:
                self._spawn(w)
            except BaseException as e:       # noqa: BLE001 - collected
                errs.append(e)

        threads = [threading.Thread(target=boot, args=(w,), daemon=True)
                   for w in self.workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            self.shutdown(drain=False)
            raise RuntimeError(f"fleet start failed: {errs[0]}") from errs[0]
        self.warmed_up = True
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-monitor", daemon=True)
        self._monitor.start()
        get_metrics().event("serve_fleet", phase="end",
                            n_replicas=self.n_workers, tp=self.spec.tp,
                            seconds=round(time.monotonic() - t0, 3))
        return self

    def warmup(self) -> None:
        """Workers warm their own engines before the ready line; kept
        for engine-surface parity."""

    def _spawn(self, w: WorkerSupervisor) -> None:
        """Start (or restart) one worker process and wire it up. Raises
        on failure — callers own the retry/backoff policy."""
        t0 = time.monotonic()
        if os.path.exists(w.socket_path):
            os.unlink(w.socket_path)
        cmd = [sys.executable, "-m", _WORKER_MODULE,
               "--socket", w.socket_path,
               "--spec", self.spec.to_json(),
               "--replica", str(w.index),
               "--incarnation", str(w.restarts),
               "--heartbeat_s", str(self.heartbeat_s),
               "--drain_timeout", str(self.drain_timeout_s)]
        if w.metrics_path:
            cmd += ["--metrics_jsonl", w.metrics_path]
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
        ready = None
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"worker {w.index} exited before ready "
                    f"(rc={proc.poll()})")
            try:
                import json as _json

                obj = _json.loads(line)
            except ValueError:
                continue                     # stray log line on stdout
            if isinstance(obj, dict) and obj.get("ready"):
                ready = obj
                break
        if ready is None:
            proc.kill()
            raise RuntimeError(
                f"worker {w.index} not ready within "
                f"{self.ready_timeout_s}s")
        ctrl = RpcClient(w.socket_path, timeout=self.call_timeout_s,
                         stats=self.rpc_stats)
        try:
            ctrl.call("ping")        # first NTP-style clock sample
        except (TransportError, RuntimeError):
            pass
        ev_sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        ev_sock.connect(w.socket_path)
        send_frame(ev_sock, {"method": "subscribe", "args": {}})
        recv_frame(ev_sock)                  # ack
        ev_sock.settimeout(None)
        with self._lock:
            w.generation += 1
            gen = w.generation
            w.proc = proc
            w.ctrl = ctrl
            w.events_sock = ev_sock
            w.pid = int(ready["pid"])
            w.alive = True
            w.closing = False
            w.out_of_dispatch = False
            w.last_beat = time.monotonic()
            w.incarnation = w.restarts
            w.last_beat_wall = None
            w.clock = None
            w.last_clock_emit = 0.0
        threading.Thread(target=self._stdout_loop, args=(w, gen, proc),
                         name=f"fleet-stdout-{w.index}",
                         daemon=True).start()
        threading.Thread(target=self._event_loop, args=(w, gen, ev_sock),
                         name=f"fleet-events-{w.index}",
                         daemon=True).start()
        get_metrics().event("worker_spawn", replica=w.index, pid=w.pid,
                            restarts=w.restarts,
                            seconds=round(time.monotonic() - t0, 3))
        self._incident("worker_spawn", replica=w.index, pid=w.pid,
                       restarts=w.restarts)
        self._note_clock(w)
        logger.info("Worker %d up (pid %d, %.2fs).", w.index, w.pid,
                    time.monotonic() - t0)

    # -- observability -----------------------------------------------------

    def _incident(self, kind: str, **fields) -> None:
        """Flight recorder: bounded in-memory ring of incident rows,
        snapshotted to a file when a worker dies or runs out of restart
        budget (the telemetry JSONL has the same rows — the snapshot is
        the grab-and-go artifact for a pager incident)."""
        row = {"wall": time.time(), "kind": kind}
        row.update(fields)
        self._incidents.append(row)

    def _snapshot_incidents(self, reason: str,
                            replica: Optional[int] = None
                            ) -> Optional[str]:
        """Dump the incident ring to a JSON file and log where."""
        with self._lock:
            rows = list(self._incidents)
            self._incident_seq += 1
            seq = self._incident_seq
        path = (f"{self.metrics_base}.incident{seq}.json"
                if self.metrics_base
                else os.path.join(self._dir, f"incident{seq}.json"))
        try:
            with open(path, "w") as f:
                json.dump({"reason": reason, "wall": time.time(),
                           "n_events": len(rows), "events": rows},
                          f, sort_keys=True)
        except OSError as e:
            logger.warning("Incident snapshot failed: %s", e)
            return None
        get_metrics().event("incident_snapshot", reason=reason,
                            path=path, n_events=len(rows),
                            replica=replica)
        logger.error("Incident snapshot (%s): %d events -> %s", reason,
                     len(rows), path)
        return path

    def _note_clock(self, w: WorkerSupervisor) -> None:
        """Publish worker ``w``'s freshest RPC-derived clock sample as a
        ``clock_sync`` event. Every reply refines the estimate; only a
        cadence tick or a big uncertainty improvement reaches the JSONL.
        The merged-timeline exporter keys corrections on these rows."""
        ctrl = w.ctrl
        sample = ctrl.clock if ctrl is not None else None
        if sample is None:
            return
        now = time.time()
        with self._lock:
            prev = w.clock
            w.clock = sample
            due = (prev is None
                   or sample.uncertainty_s < prev.uncertainty_s * 0.5
                   or now - w.last_clock_emit >= _CLOCK_SYNC_EVERY_S)
            if not due:
                return
            w.last_clock_emit = now
            incarnation = w.incarnation
            pid = w.pid
        get_metrics().event(
            "clock_sync", replica=w.index,
            offset_s=round(sample.offset_s, 6),
            uncertainty_s=round(sample.uncertainty_s, 6),
            rtt_s=round(sample.rtt_s, 6), incarnation=incarnation,
            pid=pid, source="rpc_midpoint", n_samples=sample.n_samples)

    # -- liveness ----------------------------------------------------------

    def _stdout_loop(self, w: WorkerSupervisor, gen: int,
                     proc: subprocess.Popen) -> None:
        """Drain the worker's stdout; EOF is the fastest kill -9 signal
        (the kernel closes the pipe the instant the process dies)."""
        for _ in proc.stdout:
            pass
        self._on_death(w, gen, "pipe_eof")

    def _event_loop(self, w: WorkerSupervisor, gen: int,
                    sock: socket.socket) -> None:
        while True:
            try:
                ev = recv_frame(sock)
            except TransportError:
                self._on_death(w, gen, "events_lost")
                return
            try:
                self._apply_event(w, gen, ev)
            except Exception:                # noqa: BLE001
                logger.exception("Worker %d: bad event %r.", w.index, ev)

    def _monitor_loop(self) -> None:
        while not self._closing:
            time.sleep(self.heartbeat_s)
            now = time.monotonic()
            for w in self.workers:
                with self._lock:
                    live = w.alive and not w.closing
                    gen = w.generation
                    age = now - w.last_beat
                    beat_wall = w.last_beat_wall
                    clock = w.clock
                if not live:
                    continue
                self._note_clock(w)
                if w.proc is not None and w.proc.poll() is not None:
                    self._on_death(w, gen, f"exit_{w.proc.returncode}")
                    continue
                if beat_wall is not None and clock is not None:
                    # Paired-timestamp age: the worker stamps each beat
                    # with ITS wall clock; skew-correcting that onto
                    # ours measures send-to-now directly, immune to
                    # event-thread receive jitter on the fleet side.
                    age = time.time() - (beat_wall - clock.offset_s)
                if age > self.heartbeat_timeout_s:
                    get_metrics().event(
                        "worker_heartbeat_missed", replica=w.index,
                        age_s=round(age, 3),
                        timeout_s=self.heartbeat_timeout_s, pid=w.pid)
                    self._incident("worker_heartbeat_missed",
                                   replica=w.index, age_s=round(age, 3),
                                   pid=w.pid)
                    logger.error(
                        "Worker %d: no heartbeat for %.2fs (timeout "
                        "%.2fs) — killing it.", w.index, age,
                        self.heartbeat_timeout_s)
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
                    self._on_death(w, gen, "heartbeat_missed")

    # -- events ------------------------------------------------------------

    def _apply_event(self, w: WorkerSupervisor, gen: int,
                     ev: dict) -> None:
        kind = ev.get("ev")
        if kind == "heartbeat":
            with self._lock:
                if w.generation == gen:
                    w.last_beat = time.monotonic()
                    w.last_beat_wall = ev.get("wall")
                    w.snapshot = ev.get("snapshot")
            return
        cid = ev.get("client_id")
        with self._lock:
            entry = self._requests.get(cid)
            if entry is not None and entry.worker != w.index:
                entry = None                 # stale frame from a pre-
                # redispatch owner: the handle moved on
        if entry is None:
            return
        req = entry.req
        if kind == "admitted":
            with self._lock:
                entry.state = "running"
            if req.t_admit is None:
                req.t_admit = time.monotonic()
            return
        if kind == "piece":
            if req.done:
                return
            if req.t_first_token is None:
                req.t_first_token = time.monotonic()
            req.output_ids.append(  # graft-ok: GL011 wire JSON int, host-resident
                int(ev["token"]))
            req.text += ev["piece"]
            if req.on_token is not None:
                req.on_token(req,  # graft-ok: GL011 wire JSON int, host-resident
                             int(ev["token"]), ev["piece"])
            req._push_piece(ev["piece"])
            return
        if kind == "done":
            with self._lock:
                self._requests.pop(cid, None)
            if req.done:
                return
            req.output_ids = [int(t) for t in  # graft-ok: GL011 wire JSON ints, host-resident
                              ev["token_ids"]]
            req.text = ev["text"]
            req.finish_reason = ev.get("finish_reason")
            req.state = FINISHED
            if req.t_first_token is None and req.output_ids:
                req.t_first_token = time.monotonic()
            req.t_finish = time.monotonic()
            req._mark_done()
            self._emit_request_span(entry)
            return
        if kind == "failed":
            with self._lock:
                self._requests.pop(cid, None)
            if req.done:
                return
            req.finish_reason = ev.get("reason")
            req.error = ev.get("error") or ev.get("reason")
            req.state = FINISHED
            req.t_finish = time.monotonic()
            req._mark_done()
            self._emit_request_span(entry)
            return

    def _emit_request_span(self, entry: _FleetEntry) -> None:
        """The fleet-side request span: exactly ONE closed tree per
        request id, whatever the outcome — done, failed, shed,
        rejected, expired, worker_dead, or shutdown leftover. The RPC
        hops ride as extra ``rpc:<method>`` children; the worker's own
        ``worker_request`` span joins on the same request_id in the
        merged timeline."""
        with self._lock:
            if entry.span_emitted:
                return
            entry.span_emitted = True
        try:
            row = entry.req.trace_row()
            rpc = sorted(entry.rpc_spans,
                         key=lambda c: (c["t0"], c["name"]))
            row["children"] = list(row.get("children") or ()) + rpc
            row["worker"] = entry.worker
            row["incarnation"] = entry.incarnation
            get_metrics().log_span(**row)
        except Exception:                # noqa: BLE001 - telemetry only
            logger.exception("Fleet request span emit failed (ignored).")

    # -- death + restart ---------------------------------------------------

    def _on_death(self, w: WorkerSupervisor, gen: int,
                  reason: str) -> None:
        """The crash path: runs AT MOST ONCE per worker incarnation
        (generation-gated), from whichever liveness signal fires first."""
        with self._lock:
            if w.generation != gen or not w.alive or w.closing:
                return
            w.alive = False
            w.snapshot = None
            self.n_deaths += 1
            mine = [e for e in self._requests.values()
                    if e.worker == w.index]
            queued = [e for e in mine
                      if e.state == "queued" and not e.req.output_ids]
            running = [e for e in mine if e not in queued]
            for e in mine:
                self._requests.pop(e.req.id, None)
        pid = w.pid
        if w.ctrl is not None:
            w.ctrl.close()
        if w.scrape is not None:
            w.scrape.close()
            w.scrape = None
        if w.events_sock is not None:
            try:
                w.events_sock.close()
            except OSError:
                pass
        get_metrics().event("worker_dead", replica=w.index, reason=reason,
                            pid=pid, queued_redispatched=len(queued),
                            inflight_failed=len(running),
                            restarts=w.restarts)
        self._incident("worker_dead", replica=w.index, reason=reason,
                       pid=pid, queued_redispatched=len(queued),
                       inflight_failed=len(running))
        logger.error(
            "Worker %d DIED (%s, pid %s): re-dispatching %d queued, "
            "failing %d in-flight.", w.index, reason, pid, len(queued),
            len(running))
        for e in running:
            self._fail_entry(e, "worker_dead",
                             f"worker_dead: worker {w.index} died "
                             f"mid-decode ({reason})")
        for e in queued:
            self._redispatch(e, from_replica=w.index)
        if self._closing or self._draining:
            return
        self._snapshot_incidents(f"worker_dead_{reason}",
                                 replica=w.index)
        if w.restarts >= self.max_restarts:
            with self._lock:
                w.stopped = True
            logger.error(
                "Worker %d: restart budget (%d) exhausted — fleet "
                "degrades to survivors.", w.index, self.max_restarts)
            self._snapshot_incidents("restart_budget_exhausted",
                                     replica=w.index)
            return
        threading.Thread(target=self._restart, args=(w,),
                         name=f"fleet-restart-{w.index}",
                         daemon=True).start()

    def _restart(self, w: WorkerSupervisor) -> None:
        t_dead = time.monotonic()
        while not (self._closing or self._draining):
            if w.restarts >= self.max_restarts:
                with self._lock:
                    w.stopped = True
                logger.error(
                    "Worker %d: restart budget (%d) exhausted — fleet "
                    "degrades to survivors.", w.index, self.max_restarts)
                self._snapshot_incidents("restart_budget_exhausted",
                                         replica=w.index)
                return
            backoff = self.restart_backoff_s * (2.0 ** w.restarts)
            w.restarts += 1
            time.sleep(backoff)
            if self._closing or self._draining:
                return
            try:
                self._spawn(w)
            except Exception as e:           # noqa: BLE001 - retry loop
                logger.error("Worker %d: restart attempt %d failed: %s",
                             w.index, w.restarts, e)
                continue
            with self._lock:
                self.n_restarts += 1
            get_metrics().event(
                "worker_restart", replica=w.index, restarts=w.restarts,
                backoff_s=round(backoff, 3),
                downtime_s=round(time.monotonic() - t_dead, 3), pid=w.pid)
            self._incident(
                "worker_restart", replica=w.index, restarts=w.restarts,
                downtime_s=round(time.monotonic() - t_dead, 3),
                pid=w.pid)
            logger.warning("Worker %d restarted (attempt %d, %.2fs down) "
                           "— back in dispatch.", w.index, w.restarts,
                           time.monotonic() - t_dead)
            return

    def _fail_entry(self, e: _FleetEntry, reason: str, msg: str) -> None:
        req = e.req
        if req.done:
            return
        with self._lock:
            self.n_failed_on_death += 1
        req.finish_reason = "error"
        req.error = msg
        req.state = FINISHED
        req.t_finish = time.monotonic()
        req._mark_done()
        self._emit_request_span(e)

    def _redispatch(self, e: _FleetEntry, from_replica: int) -> None:
        """Move one queued request to a survivor under its ORIGINAL
        handle (``drain_replica`` semantics across the process
        boundary)."""
        req = e.req
        for w in self._dispatch_order(max_new=e.params.get(
                "max_new_tokens", self.default_max_new_tokens)):
            if w.index == from_replica:
                continue
            e.worker = w.index
            e.state = "queued"
            with self._lock:
                self._requests[req.id] = e
                e.incarnation = w.incarnation
            try:
                w.ctrl.call("adopt", client_id=req.id,
                            prompt_ids=e.prompt_ids, params=e.params,
                            route={"replica": w.index,
                                   "redispatched_from": from_replica},
                            trace_ctx={"request_id": req.id,
                                       "replica": w.index},
                            on_timing=e.add_rpc)
            except (QueueFullError, SLOShedError, EngineDrainingError,
                    TransportError, RuntimeError) as err:
                with self._lock:
                    if self._requests.get(req.id) is e:
                        del self._requests[req.id]
                logger.warning("Redispatch of %d to worker %d refused: "
                               "%s", req.id, w.index, err)
                continue
            with self._lock:
                self.n_redispatched += 1
            if req.route:
                req.route = {**req.route, "replica": w.index,
                             "redispatched_from": from_replica}
            get_metrics().event("router_redispatch", request_id=req.id,
                                from_replica=from_replica,
                                to_replica=w.index)
            self._incident("router_redispatch", request_id=req.id,
                           from_replica=from_replica,
                           to_replica=w.index)
            return
        self._fail_entry(e, "worker_dead",
                         f"worker_dead: worker {from_replica} died and "
                         "no survivor accepted the request")

    # -- dispatch ----------------------------------------------------------

    def _live(self) -> List[WorkerSupervisor]:
        with self._lock:
            return [w for w in self.workers
                    if w.alive and not (w.closing or w.out_of_dispatch)]

    def _dispatch_order(self, max_new: int) -> List[WorkerSupervisor]:
        """Live workers, cheapest predicted service first (same pure
        ``service_estimate`` the in-process router sorts by, computed
        from heartbeat snapshots)."""
        scored = []
        for w in self._live():
            snap = w.snapshot or {}
            est = service_estimate(
                snap.get("queue_depth", 0), snap.get("n_active", 0),
                snap.get("n_slots", 1), snap.get("tpot_ewma"),
                snap.get("tokens_ewma"), max_new)
            scored.append((est if est is not None else 0.0, w.index, w))
        scored.sort(key=lambda t: (t[0], t[1]))
        return [w for _, _, w in scored]

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, timeout: Optional[float] = None,
               on_token=None, route=None) -> Request:
        if self._draining:
            raise EngineDrainingError(
                "fleet is draining: admission closed",
                retry_after_s=self.drain_timeout_s)
        params = params or SamplingParams(
            max_new_tokens=self.default_max_new_tokens)
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("text prompt needs a tokenizer")
            prompt_ids = np.asarray(  # graft-ok: GL012 tokenizer host list, no device
                self.tokenizer.encode(prompt), np.int32)
        else:
            prompt_ids = np.asarray(  # graft-ok: GL012 caller host ids, no device
                prompt, np.int32).reshape(-1)
        wire_params = {k: v for k, v in
                       dataclasses.asdict(params).items()
                       if v is not None}
        wire_ids = [int(t) for t in prompt_ids]  # graft-ok: GL011 host numpy, no device
        req = Request(next_request_id(), prompt_ids, params, on_token)
        # ONE ledger row reused across dispatch attempts, so the rpc
        # child spans of refused hops still land on the final trace.
        entry = _FleetEntry(req, wire_ids, wire_params, -1)
        deadline = (time.monotonic() + timeout
                    if (block and timeout is not None) else None)
        while True:
            first_refusal: Optional[BaseException] = None
            order = self._dispatch_order(params.max_new_tokens)
            for w in order:
                entry.worker = w.index
                entry.state = "queued"
                with self._lock:
                    self._requests[req.id] = entry
                    entry.incarnation = w.incarnation
                try:
                    w.ctrl.call("submit", client_id=req.id,
                                prompt_ids=wire_ids, params=wire_params,
                                route={"replica": w.index},
                                trace_ctx={"request_id": req.id,
                                           "replica": w.index},
                                on_timing=entry.add_rpc)
                except (QueueFullError, SLOShedError) as e:
                    claimed = self._unclaim(req, entry)
                    if not claimed:
                        return req           # death path owns it now
                    if first_refusal is None:
                        first_refusal = e
                    continue
                except (EngineDrainingError, TransportError,
                        RuntimeError):
                    if not self._unclaim(req, entry):
                        return req
                    continue
                req.route = route or {"replica": w.index}
                return req
            if not order:
                first_refusal = first_refusal or RuntimeError(
                    "no live workers")
            if not block:
                err = first_refusal or QueueFullError(
                    "every live worker refused admission")
                self._finish_refused(req, entry, err)
                raise err
            if deadline is not None and time.monotonic() >= deadline:
                err = first_refusal or QueueFullError(
                    f"no worker admitted the request within {timeout}s")
                self._finish_refused(req, entry, err)
                raise err
            time.sleep(0.05)

    def _finish_refused(self, req: Request, entry: _FleetEntry,
                        err: BaseException) -> None:
        """Close the telemetry for a request no worker admitted: the
        raise is the client's answer; the refusal event + the closed
        span tree are the timeline's."""
        if isinstance(err, SLOShedError):
            req.finish_reason = FINISH_SHED
            get_metrics().event(
                "request_shed", request_id=req.id, reason=str(err),
                retry_after_s=getattr(err, "retry_after_s", None))
        elif isinstance(err, QueueFullError):
            req.finish_reason = FINISH_REJECTED
            get_metrics().event("request_rejected", request_id=req.id,
                                reason=str(err))
        else:
            req.finish_reason = "error"
            req.error = str(err)
        req.state = FINISHED
        req.t_finish = time.monotonic()
        req._mark_done()
        self._emit_request_span(entry)

    def _unclaim(self, req: Request, entry: _FleetEntry) -> bool:
        """Remove a not-yet-acked ledger entry; False when the death
        path already claimed it (it owns the request's fate then)."""
        with self._lock:
            if self._requests.get(req.id) is entry:
                del self._requests[req.id]
                return True
        return False

    def cancel(self, req: Request) -> bool:
        with self._lock:
            entry = self._requests.get(req.id)
        if entry is None:
            return False
        w = self.workers[entry.worker]
        try:
            out = w.ctrl.call("cancel", client_id=req.id)
        except (TransportError, RuntimeError):
            return False
        return bool(out.get("cancelled"))

    # -- drain / handoff ---------------------------------------------------

    def drain_worker(self, i: int, timeout: Optional[float] = None,
                     handoff_to: Optional[int] = None) -> dict:
        """Gracefully retire worker ``i``: steal its queue (re-dispatch
        under the same handles), hand its hot prefix panes to a
        survivor, let in-flight work finish, then SIGTERM the process."""
        timeout = self.drain_timeout_s if timeout is None else timeout
        w = self.workers[i]
        with self._lock:
            if not w.alive:
                return {"drained": False, "reason": "not alive"}
            w.out_of_dispatch = True
        t0 = time.monotonic()
        get_metrics().event("replica_drain", replica=i, phase="start",
                            timeout_s=timeout)
        stolen: List[int] = []
        try:
            stolen = w.ctrl.call("steal_queue").get("client_ids", [])
        except (TransportError, RuntimeError) as e:
            logger.warning("Drain of worker %d: steal_queue failed "
                           "(%s).", i, e)
        for cid in stolen:
            with self._lock:
                e = self._requests.get(cid)
            if e is not None:
                self._redispatch(e, from_replica=i)
        self._handoff_panes(w, handoff_to)
        try:
            w.ctrl.call("drain", rpc_timeout=timeout + 10.0,
                        timeout=timeout)
        except (TransportError, RuntimeError) as e:
            logger.warning("Drain RPC to worker %d failed: %s", i, e)
        self._stop_worker(w)
        get_metrics().event("replica_drain", replica=i, phase="end",
                            n_redispatched=len(stolen),
                            seconds=round(time.monotonic() - t0, 3))
        return {"drained": True, "redispatched": len(stolen),
                "seconds": round(time.monotonic() - t0, 3)}

    def _handoff_panes(self, w: WorkerSupervisor,
                       handoff_to: Optional[int]) -> None:
        """Ship the draining worker's PrefixStore over the transport to
        an adopting replica. Keys are config-fingerprinted — identical
        across same-spec workers — so the adoptee serves the donor's
        prefixes as hits, no recompute."""
        targets = [t for t in self._live() if t.index != w.index]
        if handoff_to is not None:
            targets = [t for t in targets if t.index == handoff_to]
        if not targets:
            return
        t0 = time.monotonic()
        try:
            exported = w.ctrl.call(
                "export_panes",
                rpc_timeout=max(self.call_timeout_s, 30.0))
        except (TransportError, RuntimeError) as e:
            logger.warning("Pane export from worker %d failed: %s",
                           w.index, e)
            return
        entries = exported.get("entries", [])
        if not entries:
            return
        adoptee = targets[0]
        try:
            res = adoptee.ctrl.call(
                "import_panes", entries=entries,
                rpc_timeout=max(self.call_timeout_s, 30.0))
        except (TransportError, RuntimeError) as e:
            logger.warning("Pane import into worker %d failed: %s",
                           adoptee.index, e)
            return
        with self._lock:
            self.n_handoffs += 1
        get_metrics().event(
            "pane_handoff", from_replica=w.index, to_replica=adoptee.index,
            entries=len(entries), imported=res.get("imported", 0),
            bytes=res.get("bytes", 0),
            seconds=round(time.monotonic() - t0, 3))
        self._incident("pane_handoff", from_replica=w.index,
                       to_replica=adoptee.index, entries=len(entries))
        logger.info("Prefix panes handed off %d -> %d: %d entries, %d "
                    "bytes, %.3fs.", w.index, adoptee.index,
                    len(entries), res.get("bytes", 0),
                    time.monotonic() - t0)

    def _stop_worker(self, w: WorkerSupervisor) -> None:
        """Intentional teardown of one worker process (no death path)."""
        with self._lock:
            w.closing = True
            w.alive = False
            w.stopped = True
            w.snapshot = None
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.terminate()
                w.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                w.proc.kill()
                w.proc.wait(timeout=5.0)
            except OSError:
                pass
        if w.ctrl is not None:
            w.ctrl.close()
        if w.scrape is not None:
            w.scrape.close()
            w.scrape = None
        if w.events_sock is not None:
            try:
                w.events_sock.close()
            except OSError:
                pass

    def drain(self, timeout: float = 30.0) -> dict:
        """Rolling fleet drain: retire workers one at a time (queue
        steal + pane handoff to survivors), plain-drain the last."""
        self._draining = True
        t0 = time.monotonic()
        live = [w.index for w in self.workers
                if w.alive and not w.closing]
        n_re = 0
        for i in live[:-1]:
            out = self.drain_worker(i, timeout=timeout)
            n_re += out.get("redispatched", 0)
        for i in live[-1:]:
            w = self.workers[i]
            try:
                w.ctrl.call("drain", rpc_timeout=timeout + 10.0,
                            timeout=timeout)
            except (TransportError, RuntimeError) as e:
                logger.warning("Final drain RPC to worker %d failed: %s",
                               i, e)
            self._stop_worker(w)
        summary = {"seconds": round(time.monotonic() - t0, 3),
                   "redispatched": n_re}
        get_metrics().event("drain", phase="end", seconds=summary["seconds"])
        return summary

    def shutdown(self, drain: bool = True) -> None:
        if drain and not self._draining:
            self.drain(timeout=self.drain_timeout_s)
        self._closing = True
        self._draining = True
        for w in self.workers:
            self._stop_worker(w)
        # fail anything still in the ledger so no client hangs forever
        with self._lock:
            leftovers = list(self._requests.values())
            self._requests.clear()
        for e in leftovers:
            if not e.req.done:
                e.req.finish_reason = "preempted"
                e.req.error = "fleet shutdown"
                e.req.state = FINISHED
                e.req.t_finish = time.monotonic()
                e.req._mark_done()
            self._emit_request_span(e)

    def run_until_idle(self) -> None:
        while True:
            with self._lock:
                if not self._requests:
                    return
            time.sleep(0.01)

    # -- engine-shaped introspection --------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def n_recompiles(self) -> int:
        """Sum of live workers' recompile counters (fault tests assert
        survivors stay at zero through a neighbor's death)."""
        total = 0
        for s in self._worker_stats().values():
            total += int(s.get("n_recompiles", 0))
        return total

    def queue_capacity(self) -> int:
        cap = 0
        for w in self.workers:
            snap = w.snapshot or {}
            cap += int(snap.get("queue_capacity", 0))
        if cap:
            return cap
        per = ((self.spec.fake or {}).get("max_queue")
               or self.spec.engine.get("max_queue", 64))
        return int(per) * self.n_workers

    def estimate_queue_clear_s(self) -> Optional[float]:
        best = None
        for w in self._live():
            snap = w.snapshot or {}
            est = queue_clear_estimate(
                snap.get("queue_depth", 0), snap.get("n_active", 0),
                snap.get("n_slots", 1), snap.get("tpot_ewma"),
                snap.get("tokens_ewma"))
            if est is not None and (best is None or est < best):
                best = est
        return best

    def _worker_stats(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for w in self._live():
            try:
                out[w.index] = w.ctrl.call("stats")
            except (TransportError, RuntimeError):
                continue
        return out

    def stats(self) -> dict:
        per = self._worker_stats()
        with self._lock:
            out = {
                "n_workers": self.n_workers,
                "workers_up": sum(1 for w in self.workers if w.alive),
                "worker_deaths": self.n_deaths,
                "worker_restarts": self.n_restarts,
                "redispatched_total": self.n_redispatched,
                "failed_on_death": self.n_failed_on_death,
                "in_flight": len(self._requests),
                "draining": self._draining,
            }
        out["n_recompiles"] = sum(int(s.get("n_recompiles", 0))
                                  for s in per.values())
        out["requests_finished"] = sum(int(s.get("requests_finished", 0))
                                       for s in per.values())
        out["workers"] = {i: per[i] for i in sorted(per)}
        return out

    def _scrape_worker(self, w: WorkerSupervisor) -> None:
        """Scrape one worker's metrics over a DEDICATED short-timeout
        connection. A timeout desyncs the framed stream and poisons the
        client — poisoning the CONTROL client would fail real dispatch,
        so scrapes get their own connection and simply rebuild it."""
        with self._lock:
            cli = w.scrape
            w.scrape = None          # taken: no concurrent scrape share
        m = None
        try:
            if cli is None:
                cli = RpcClient(w.socket_path,
                                timeout=_SCRAPE_TIMEOUT_S,
                                stats=self.rpc_stats)
            m = cli.call("metrics", rpc_timeout=_SCRAPE_TIMEOUT_S)
        except (TransportError, RuntimeError, OSError):
            if cli is not None:
                cli.close()
            cli = None
        with self._lock:
            if cli is not None and w.scrape is None:
                w.scrape = cli
            if m is not None:
                w.last_metrics = m
                w.last_metrics_wall = time.time()

    def metrics_snapshot(self) -> tuple:
        """Aggregated fleet metrics: live workers are scraped in
        parallel over timed RPC; a dead or slow worker contributes its
        last-known (cached) series plus a staleness gauge instead of
        blocking the endpoint — same never-block discipline as
        ``healthz_payload``."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Any] = {}
        live = self._live()
        threads = [threading.Thread(target=self._scrape_worker,
                                    args=(w,),
                                    name=f"fleet-scrape-{w.index}",
                                    daemon=True)
                   for w in live]
        scrape_deadline = time.monotonic() + _SCRAPE_DEADLINE_S
        for t in threads:
            t.start()
        for t in threads:
            t.join(max(0.0, scrape_deadline - time.monotonic()))
        now = time.time()
        for w in self.workers:
            with self._lock:
                m = w.last_metrics
                m_wall = w.last_metrics_wall
                inc = w.incarnation
            if m is None:
                continue             # never scraped: nothing to serve
            age = max(now - m_wall, 0.0)
            stale = age > max(2.0 * self.heartbeat_s, _SCRAPE_DEADLINE_S)
            lab = f'worker="{w.index}",incarnation="{inc}"'
            gauges[f"fleet_worker_metrics_stale{{{lab}}}"] = (
                1.0 if stale else 0.0)
            gauges[f"fleet_worker_metrics_age_s{{{lab}}}"] = round(age, 3)
            for k, v in m.get("counters", {}).items():
                counters[_labeled(k, w.index, inc)] = v
            for k, v in m.get("gauges", {}).items():
                gauges[_labeled(k, w.index, inc)] = v
            for k, v in m.get("hists", {}).items():
                hists[_labeled(k, w.index, inc)] = _HistSnap(v)
        # The fleet's own rpc-client instrumentation (per-method).
        for method, s in self.rpc_stats.snapshot().items():
            lab = f'{{method="{method}"}}'
            counters[f"fleet_rpc_client_calls{lab}"] = s["calls"]
            counters[f"fleet_rpc_client_errors{lab}"] = s["errors"]
            counters[f"fleet_rpc_client_frame_bytes_sent{lab}"] = (
                s["bytes_sent"])
            counters[f"fleet_rpc_client_frame_bytes_received{lab}"] = (
                s["bytes_received"])
            hists[f"fleet_rpc_client_latency_seconds{lab}"] = _HistSnap(
                s["latency"])
        with self._lock:
            up = sum(1 for w in self.workers if w.alive)
            gauges["fleet_workers_up"] = up
            gauges["fleet_workers_total"] = self.n_workers
            counters["fleet_worker_deaths_total"] = self.n_deaths
            counters["fleet_worker_restarts_total"] = self.n_restarts
            counters["fleet_redispatched_total"] = self.n_redispatched
            counters["fleet_failed_on_death_total"] = (
                self.n_failed_on_death)
            counters["fleet_pane_handoffs_total"] = self.n_handoffs
        return counters, gauges, hists

    def prometheus_text(self) -> str:
        counters, gauges, hists = self.metrics_snapshot()
        return render_prometheus(counters, gauges, hists)

    def healthz_payload(self) -> dict:
        """Fleet health WITHOUT any RPC: built from cached heartbeat
        snapshots, so a downed/restarting worker can never stall or
        fail the health endpoint — it reports ``degraded`` instead."""
        now = time.monotonic()
        replicas = []
        up = 0
        with self._lock:
            draining = self._draining
            for w in self.workers:
                if w.alive:
                    status = "serving"
                    up += 1
                elif w.stopped:
                    status = "drained" if w.closing else "dead"
                else:
                    status = "restarting"
                row = {"replica": w.index, "status": status,
                       "restarts": w.restarts, "pid": w.pid}
                snap = w.snapshot
                if w.alive and snap:
                    row["queue_depth"] = snap.get("queue_depth")
                    row["active"] = snap.get("n_active")
                    row["heartbeat_age_s"] = round(now - w.last_beat, 3)
                replicas.append(row)
        if draining:
            status = "draining"
        elif up == 0:
            status = "dead"
        elif up < self.n_workers:
            status = "degraded"
        else:
            status = "serving"
        return {"status": status, "workers_up": up,
                "workers_total": self.n_workers,
                "uptime_s": round(now - self._t0, 3),
                "draining": draining, "replicas": replicas}


__all__ = ["ProcessFleet", "WorkerSupervisor"]
