"""Process entrypoint for the fleet worker.

Separate from ``serving.worker`` so ``python -m ..serving._worker_main``
doesn't re-execute a module the ``serving`` package ``__init__`` already
imported (runpy warns about exactly that).
"""

import sys

from building_llm_from_scratch_tpu.serving.worker import main

if __name__ == "__main__":
    sys.exit(main())
