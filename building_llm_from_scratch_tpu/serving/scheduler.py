"""Slot scheduler: FCFS admission into a fixed slot batch.

The decode batch has ``n_slots`` rows with STATIC shapes; the scheduler
owns which request occupies which row. Admission happens only at step
boundaries (the engine calls ``admit`` before each decode tick), retirement
frees the slot immediately so the next queued request fills it on the same
tick — the continuous-batching invariant that keeps the fixed batch full
under load.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from building_llm_from_scratch_tpu.serving.queue import RequestQueue
from building_llm_from_scratch_tpu.serving.request import Request


class Scheduler:
    def __init__(self, n_slots: int):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        self.n_slots = n_slots
        self.slots: List[Optional[Request]] = [None] * n_slots
        # ordered free list: lowest slot first (deterministic placement,
        # which the placement-invariance test then proves irrelevant)
        self._free: List[int] = list(range(n_slots))

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free)

    def active(self) -> List[Tuple[int, Request]]:
        return [(i, r) for i, r in enumerate(self.slots) if r is not None]

    def occupancy(self) -> float:
        return self.n_active / self.n_slots

    def admit_from(self, queue: RequestQueue,
                   skip: Optional[Callable[[Request], bool]] = None
                   ) -> List[Tuple[int, Request]]:
        """FCFS: fill free slots from the queue head; returns the
        (slot, request) pairs admitted this boundary.

        ``skip`` is the admission-boundary shed hook: a popped request for
        which it returns True is dropped WITHOUT consuming a slot (the
        engine uses it for deadline expiry and client cancellation — the
        callee is responsible for failing/finishing the request)."""
        admitted: List[Tuple[int, Request]] = []
        while self._free:
            req = queue.get_nowait()
            if req is None:
                break
            if skip is not None and skip(req):
                continue
            slot = self._free.pop(0)
            self.slots[slot] = req
            admitted.append((slot, req))
        return admitted

    def retire(self, slot: int) -> None:
        if self.slots[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        self.slots[slot] = None
        self._free.append(slot)
        self._free.sort()
