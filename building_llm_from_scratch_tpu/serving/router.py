"""Fleet tier: N ``DecodeEngine`` replicas behind one dispatch surface.

The single engine tops out at one model replica on one device (or one
tensor-parallel device group). This router is the "millions of users"
layer above it: an in-process replica set with one
``submit()/result()/stream()`` surface and one HTTP frontend, where

  - each replica is a full ``DecodeEngine`` on its OWN ``MeshPlan``
    (``parallel/sharding.serve_mesh_plan``): ``tp=1`` pins a replica to
    its own device, ``tp>1`` runs it tensor-parallel over a disjoint
    device slice — replicas execute concurrently, so aggregate
    throughput scales with the replica count (``bench.py serve_fleet``);
  - dispatch is deadline-aware: each replica's live TPOT/queue-depth
    EWMAs (``DecodeEngine.service_snapshot``) feed the same completion
    estimate the single-engine SLO shed uses, generalized fleet-wide —
    a request is only refused when EVERY replica predicts a miss, and
    the 429 carries the best replica's Retry-After;
  - adapter-affinity: a tenant's traffic prefers replicas whose
    ``AdapterRegistry`` already holds its adapter row (residency is a
    lock-free ``lookup``), with load-spill past an overloaded resident
    and a routed HOT-LOAD on fleet-wide miss (the router knows the
    artifact paths);
  - prefix-affinity: requests sharing a prompt prefix hash to the same
    replica, so ``PrefixStore`` hits concentrate instead of every
    replica paying the same cold prefill;
  - drain/restart of ONE replica never drops a request: its queued work
    is re-dispatched onto live replicas (the SAME ``Request`` handles —
    clients never notice), in-flight work finishes within the drain
    timeout, and a ``restart_replica`` brings a fresh engine back into
    dispatch.

Telemetry: every engine event carries ``replica=<i>`` (the engines label
their own rows), the router adds ``replica_drain`` / ``replica_restart``
/ ``router_redispatch`` events plus fleet counters, and ``/metrics``
re-exports each replica's series with a ``{replica="i"}`` label next to
fleet-level gauges (replicas_up, fleet occupancy, affinity ratio). Each
routed request still closes exactly ONE span tree — the router hop rides
as a ``router`` child span on the request's root.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from building_llm_from_scratch_tpu.obs.metrics import (
    get_metrics,
    render_prometheus,
)
from building_llm_from_scratch_tpu.serving.engine import DecodeEngine
from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    QueueFullError,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import (
    Request,
    SamplingParams,
)
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

#: prompt-prefix window the prefix-affinity hash reads: long enough to
#: distinguish system prompts, short enough that requests sharing one
#: land on the same replica even when their suffixes diverge
PREFIX_AFFINITY_TOKENS = 64


def _labeled(key: str, replica: int) -> str:
    """Merge ``replica="i"`` into a metric key's (possibly existing)
    label set: ``adapter_tokens{adapter="x"}`` ->
    ``adapter_tokens{adapter="x",replica="i"}``."""
    base, sep, labels = key.partition("{")
    if not sep:
        return f'{base}{{replica="{replica}"}}'
    return f'{base}{{{labels[:-1]},replica="{replica}"}}'


class EngineRouter:
    """N ``DecodeEngine`` replicas behind one engine-shaped surface.

    Construct from live engines (tests) or via ``build()`` (the CLI
    path), then use it exactly like a ``DecodeEngine``: ``warmup()``,
    ``start()``, ``submit()`` (returns the replica's ``Request`` handle
    — ``result()``/``stream()`` ride it unchanged), ``drain()``,
    ``shutdown()``. The HTTP frontend binds either without caring.
    """

    def __init__(self, engines: Sequence[DecodeEngine], *,
                 adapter_paths: Optional[Dict[str, str]] = None,
                 factory: Optional[Callable[[int], DecodeEngine]] = None,
                 prefix_affinity: bool = True):
        if not engines:
            raise ValueError("EngineRouter needs at least one engine")
        self.engines: List[DecodeEngine] = list(engines)
        for i, eng in enumerate(self.engines):
            if eng.replica is None:
                eng.replica = i
        #: adapter name -> artifact path, for routed hot-load on a
        #: fleet-wide residency miss (and for drain re-dispatch of
        #: tenant traffic onto a replica that never saw the tenant)
        self._adapter_paths = dict(adapter_paths or {})
        self._factory = factory
        self.prefix_affinity = bool(prefix_affinity)
        self._lock = threading.Lock()
        #: replicas the router stopped dispatching to (drain/restart)
        self._out: set = set()              # guarded-by: _lock [writes]
        self.routed_total = 0               # guarded-by: _lock
        self.routed_affinity = 0            # guarded-by: _lock
        self.routed_spill = 0               # guarded-by: _lock
        self.hot_loads = 0                  # guarded-by: _lock
        self.redispatched = 0               # guarded-by: _lock
        self._t_start = time.monotonic()

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, cfg, params, tokenizer=None, *, n_replicas: int,
              tp: int = 1, sp: int = 1, devices=None,
              adapter_specs: Optional[Dict[str, str]] = None,
              adapter_capacity: int = 0,
              kv_policy=None, watch_compiles: str = "all",
              prefix_affinity: bool = True,
              **engine_kwargs) -> "EngineRouter":
        """Build ``n_replicas`` engines over partitioned devices.

        Each replica gets its own ``serve_mesh_plan`` (``tp`` devices,
        disjoint slices when the pool is big enough — see
        ``parallel.partition_serve_devices``) and its OWN
        ``AdapterRegistry``. Adapters are placed round-robin across
        replicas (affinity routing makes the placement sticky; misses
        hot-load), every registry sized to hold the full set so a drain
        can consolidate tenants onto the survivors.

        ``watch_compiles``: "all" (default) wraps every replica's
        programs in CompileWatchers; "first" watches only replica 0 —
        the perf-gate mode, whose fingerprint is then replica-count
        invariant by construction; "none" disables watching.
        """
        from building_llm_from_scratch_tpu.parallel.sharding import (
            partition_serve_devices,
            serve_mesh_plan,
        )
        from building_llm_from_scratch_tpu.serving.adapters import (
            AdapterRegistry,
        )

        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if watch_compiles not in ("all", "first", "none"):
            raise ValueError("watch_compiles must be all|first|none")
        t0 = time.monotonic()
        dev_slices = partition_serve_devices(n_replicas, tp, sp,
                                             devices=devices)
        specs = dict(adapter_specs or {})
        names = sorted(specs)
        if not adapter_capacity:
            adapter_capacity = max(2, len(names) + 1)

        def make_engine(i: int) -> DecodeEngine:
            plan = serve_mesh_plan(tp, sp, devices=dev_slices[i])
            registry = None
            if adapter_specs is not None:
                # an EMPTY spec dict still builds (empty) registries:
                # the router can then hot-load artifacts it learns about
                # (adapter_paths) onto any replica
                mine = {nm: specs[nm] for k, nm in enumerate(names)
                        if k % n_replicas == i}
                registry = AdapterRegistry.from_artifacts(
                    cfg, params, mine, capacity=adapter_capacity) \
                    if mine else AdapterRegistry(
                        cfg, params, capacity=adapter_capacity)
            watch = (watch_compiles == "all"
                     or (watch_compiles == "first" and i == 0))
            return DecodeEngine(cfg, params, tokenizer,
                                mesh_plan=plan, replica=i,
                                adapters=registry, kv_policy=kv_policy,
                                watch_compiles=watch, **engine_kwargs)

        engines = [make_engine(i) for i in range(n_replicas)]
        router = cls(engines, adapter_paths=specs, factory=make_engine,
                     prefix_affinity=prefix_affinity)
        disjoint = (len({d for sl in dev_slices for d in sl})
                    == n_replicas * tp * sp)
        get_metrics().event(
            "serve_fleet", phase="build", n_replicas=n_replicas, tp=tp,
            sp=sp, disjoint_devices=disjoint, n_adapters=len(names),
            seconds=round(time.monotonic() - t0, 3))
        logger.info(
            "Fleet: %d replica(s) x tp=%d x sp=%d (%s device slices), %d "
            "adapter(s) round-robin.", n_replicas, tp, sp,
            "disjoint" if disjoint else "OVERLAPPING", len(names))
        return router

    # -- engine-shaped lifecycle ------------------------------------------

    def warmup(self) -> None:
        """Warm every replica CONCURRENTLY (each compiles its own program
        family; XLA compiles release the GIL, so a fleet warms in roughly
        one replica's wall time). Worker exceptions re-raise here."""
        errs: List[BaseException] = []

        def warm(eng):
            try:
                eng.warmup()
            except BaseException as e:          # noqa: BLE001 — re-raised
                errs.append(e)

        threads = [threading.Thread(target=warm, args=(eng,),
                                    name=f"warmup-r{i}", daemon=True)
                   for i, eng in enumerate(self.engines)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def start(self) -> None:
        for eng in self.engines:
            eng.start()

    def shutdown(self, drain: bool = True) -> None:
        for eng in self.engines:
            eng.shutdown(drain=drain)
        get_metrics().event("serve_fleet", phase="end",
                            n_replicas=len(self.engines),
                            seconds=round(time.monotonic()
                                          - self._t_start, 3))

    def run_until_idle(self) -> None:
        """Manual mode (tests): tick every replica until the whole fleet
        is idle."""
        while any(eng.step() for eng in self.engines):
            pass

    # -- dispatch ----------------------------------------------------------

    def _live(self) -> List[int]:
        with self._lock:
            out = set(self._out)
        return [i for i, eng in enumerate(self.engines)
                if i not in out and eng._dead is None
                and not eng.draining]

    @staticmethod
    def _estimate(snap: dict, max_new: int) -> Optional[float]:
        """The single-engine SLO completion estimate, computed from a
        replica's snapshot — THE shared ``engine.service_estimate``
        formula, so fleet admission and per-engine shed agree on what
        "predicted miss" means."""
        from building_llm_from_scratch_tpu.serving.engine import (
            service_estimate,
        )

        return service_estimate(snap["queue_depth"], snap["n_active"],
                                snap["n_slots"], snap["tpot_ewma"],
                                snap["tokens_ewma"], max_new)

    def _prefix_hash_pick(self, prompt, candidates: List[int]
                          ) -> Optional[int]:
        """Stable prompt-prefix -> replica mapping among the candidates
        whose prefix cache is on: shared-system-prompt traffic lands on
        one replica, so its ``PrefixStore`` actually accumulates hits.
        The hashed window is CHUNK-aligned (the tail partial chunk is
        dropped, mirroring ``PrefixStore.storable_span``): requests
        sharing a system prompt but differing in their last few suffix
        tokens still hash together."""
        capable = [i for i in candidates
                   if self.engines[i].prefix_store is not None]
        if not capable:
            return None
        try:
            import numpy as np

            chunk = max(
                self.engines[capable[0]].kv_policy.prefill_chunk, 1)
            if isinstance(prompt, str):
                ids = np.frombuffer(
                    prompt.encode()[: PREFIX_AFFINITY_TOKENS * 4],
                    dtype=np.uint8)
            else:
                ids = np.asarray(prompt).reshape(-1)
            span = min((ids.size // chunk) * chunk,
                       PREFIX_AFFINITY_TOKENS)
            if span <= 0:
                return None
            key = ids[:span].tobytes()
        except Exception:       # noqa: BLE001 — affinity is best-effort
            return None
        import zlib

        return capable[zlib.crc32(key) % len(capable)]

    def _route_order(self, prompt, params: SamplingParams
                     ) -> List[Tuple[int, Optional[str]]]:
        """The dispatch plan: (replica, affinity-label) candidates in
        preference order. Affinity targets (adapter residency, prefix
        hash) come first sorted by predicted completion; deadline-aware
        spill moves candidates predicted to MISS the request's deadline
        behind every candidate predicted to make it."""
        live = self._live()
        if not live:
            return []
        snaps = {i: self.engines[i].service_snapshot() for i in live}
        est = {i: self._estimate(snaps[i], params.max_new_tokens)
               for i in live}
        aff: List[int] = []
        label: Optional[str] = None

        def sort_key(i):
            return (est[i] if est[i] is not None else 0.0,
                    snaps[i]["queue_depth"], i)

        if params.adapter is not None:
            # adapter traffic can ONLY go where the adapter is resident
            # (a non-resident replica would 400 it): candidates are the
            # residents, spill is a routed hot-load (here on full miss;
            # in submit() when every resident refuses)
            aff = [i for i in live
                   if self.engines[i].adapters is not None
                   and self.engines[i].adapters.lookup(params.adapter)
                   is not None]
            label = "adapter"
            if not aff:
                target = self._hot_load(params.adapter, live, est)
                if target is not None:
                    aff = [target]
            order = [(i, label) for i in sorted(aff, key=sort_key)]
            if params.deadline_s is not None:
                ok = [c for c in order if est[c[0]] is None
                      or est[c[0]] <= params.deadline_s]
                order = ok + [c for c in order if c not in ok]
            return order
        if self.prefix_affinity:
            target = self._prefix_hash_pick(prompt, live)
            if target is not None:
                aff = [target]
                label = "prefix"
        rest = sorted((i for i in live if i not in aff), key=sort_key)
        order = [(i, label) for i in sorted(aff, key=sort_key)]
        order += [(i, None) for i in rest]
        if params.deadline_s is not None:
            # load-spill: an affinity target predicted to blow the
            # deadline yields to ANY replica predicted to make it (the
            # per-engine shed would 429 there; a colder replica serves)
            ok = [c for c in order if est[c[0]] is None
                  or est[c[0]] <= params.deadline_s]
            miss = [c for c in order if c not in ok]
            order = ok + miss
        return order

    def _hot_load(self, adapter: str, live: List[int],
                  est: Dict[int, Optional[float]]) -> Optional[int]:
        """Fleet-wide residency miss: load the tenant's artifact into
        the least-loaded live replica's registry. Returns the replica,
        or None when the router has no path / no registry / the load
        fails (the chosen engine's own submit then rejects the unknown
        adapter exactly as a single engine would)."""
        path = self._adapter_paths.get(adapter)
        if path is None:
            return None
        for i in sorted(live, key=lambda j: (est[j] or 0.0, j)):
            reg = self.engines[i].adapters
            if reg is None:
                continue
            try:
                reg.load(adapter, path)
            except Exception as e:  # noqa: BLE001 — registry full, race
                logger.warning("Hot-load of '%s' on replica %d failed: "
                               "%s", adapter, i, e)
                continue
            with self._lock:
                self.hot_loads += 1
            logger.info("Adapter '%s' hot-loaded onto replica %d "
                        "(routed miss).", adapter, i)
            return i
        return None

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, timeout: Optional[float] = None,
               on_token=None) -> Request:
        """Dispatch one request to the best replica; returns that
        replica's ``Request`` handle (``result()``/``stream()`` ride it
        unchanged). Raises only when EVERY live replica refuses:
        ``SLOShedError``/``QueueFullError``/``EngineDrainingError`` with
        the best replica's Retry-After — fleet-wide admission."""
        params = params or SamplingParams()
        t0 = time.perf_counter()
        order = self._route_order(prompt, params)
        route_s = round(time.perf_counter() - t0, 6)
        if not order:
            if params.adapter is not None and self._live():
                # live replicas exist but none holds (or could load) the
                # adapter — the single-engine unknown-adapter 400
                raise ValueError(
                    f"adapter '{params.adapter}' is not loaded on any "
                    "replica (and no artifact path is known to the "
                    "router)")
            raise RuntimeError("no live replicas")
        last: Optional[BaseException] = None
        for rank, (i, affinity) in enumerate(order):
            eng = self.engines[i]
            route = {"replica": i, "affinity": affinity,
                     "route_s": route_s, "spill": rank > 0}
            try:
                req = eng.submit(prompt, params, block=False,
                                 timeout=timeout, on_token=on_token,
                                 route=route)
            except (EngineDrainingError, QueueFullError,
                    SLOShedError) as e:
                # keep the FIRST refusal: candidates are best-first, so
                # its Retry-After is the soonest the fleet has room —
                # raising a worse replica's would over-back-off clients
                last = last or e
                continue
            except RuntimeError as e:           # replica died under us
                last = last or e
                continue
            self._count_route(affinity, rank)
            return req
        if params.adapter is not None:
            # load-spill for tenant traffic: every RESIDENT refused
            # (full/draining/shed) — hot-load the artifact onto a live
            # non-resident and serve there instead of bouncing
            tried = {i for i, _ in order}
            spill_live = [i for i in self._live() if i not in tried]
            if spill_live:
                est = {i: self._estimate(
                    self.engines[i].service_snapshot(),
                    params.max_new_tokens) for i in spill_live}
                target = self._hot_load(params.adapter, spill_live, est)
                if target is not None:
                    try:
                        req = self.engines[target].submit(
                            prompt, params, block=False, timeout=timeout,
                            on_token=on_token,
                            route={"replica": target,
                                   "affinity": "adapter",
                                   "route_s": route_s, "spill": True})
                        self._count_route("adapter", 1)
                        return req
                    except (EngineDrainingError, QueueFullError,
                            SLOShedError, RuntimeError) as e:
                        last = last or e
        if block and order:
            # every replica refused non-blocking; honor backpressure on
            # the best candidate instead of bouncing the caller
            i, affinity = order[0]
            req = self.engines[i].submit(
                prompt, params, block=True, timeout=timeout,
                on_token=on_token,
                route={"replica": i, "affinity": affinity,
                       "route_s": route_s, "spill": False})
            self._count_route(affinity, 0)
            return req
        assert last is not None
        raise last

    def _count_route(self, affinity: Optional[str], rank: int) -> None:
        with self._lock:
            self.routed_total += 1
            if affinity is not None and rank == 0:
                self.routed_affinity += 1
            if rank > 0:
                self.routed_spill += 1

    def cancel(self, req: Request) -> bool:
        """Client gave up: cancel on the owning replica (the route
        record tracks ownership across re-dispatch)."""
        i = (req.route or {}).get("replica")
        if i is not None and 0 <= i < len(self.engines):
            return self.engines[i].cancel(req)
        for eng in self.engines:            # ownership unknown: flag all
            if req.done:
                return False
            eng.cancel(req)
        return not req.done

    # -- drain / restart ---------------------------------------------------

    def drain_replica(self, i: int, timeout: float = 30.0,
                      redispatch: bool = True) -> dict:
        """Drain ONE replica without dropping fleet work: it leaves
        dispatch, its QUEUED requests move to live replicas (same
        ``Request`` handles — ``router_redispatch`` events record each
        hop), and its in-flight requests finish within ``timeout``."""
        eng = self.engines[i]
        with self._lock:
            self._out.add(i)
        snap = eng.service_snapshot()
        get_metrics().event("replica_drain", replica=i, phase="start",
                            timeout_s=timeout,
                            n_active=snap["n_active"],
                            queue_depth=snap["queue_depth"])
        moved = 0
        if redispatch:
            while True:
                req = eng.queue.get_nowait()
                if req is None:
                    break
                if self._redispatch(req, i):
                    moved += 1
                else:
                    # no live target took it: hand it back so the
                    # drain below finishes it (or preempts it loudly)
                    # rather than leaving a stolen handle unfinished
                    self._return_to_queue(eng, req)
                    break
        summary = eng.drain(timeout=timeout)
        get_metrics().event("replica_drain", replica=i, phase="end",
                            n_redispatched=moved,
                            n_preempted=summary.get("n_preempted"),
                            seconds=summary.get("seconds"))
        logger.warning("Replica %d drained: %d queued re-dispatched, "
                       "%s preempted.", i, moved,
                       summary.get("n_preempted"))
        return summary

    @staticmethod
    def _return_to_queue(eng: DecodeEngine, req: Request) -> None:
        """Hand a stolen-but-unplaceable request back to its source
        replica. The source may have refilled meanwhile (get_nowait
        woke a blocked submitter), so wait briefly for space; if it
        stays full, fail the request LOUDLY instead of letting it
        propagate out of the drain with the handle enqueued nowhere
        (a client blocked in result() forever)."""
        from building_llm_from_scratch_tpu.serving.request import (
            FINISH_PREEMPTED,
        )

        try:
            eng.queue.put(req, block=True, timeout=5.0)
            return
        except QueueFullError:
            pass
        # mirrors DecodeEngine.cancel's timed-acquire discipline: the
        # fail path mutates engine counters under the engine lock, but a
        # wedged tick must not hang the drain — we own the request (it
        # is in no queue), so the lock-free fallback cannot race a commit
        lock = eng._lock
        locked = lock.acquire(timeout=2.0)
        try:
            eng._fail_request(
                None, req,
                "drain re-dispatch found no live target and the source "
                "queue refilled", reason="preempted",
                finish=FINISH_PREEMPTED)
        finally:
            if locked:
                lock.release()

    def _redispatch(self, req: Request, from_i: int) -> bool:
        """Move one stolen QUEUED request onto a live replica. Prefers
        adapter residents; hot-loads the tenant's artifact when no
        resident survives; falls through targets on backpressure."""
        live = self._live()
        if not live:
            return False
        snaps = {j: self.engines[j].service_snapshot() for j in live}
        est = {j: self._estimate(snaps[j], req.params.max_new_tokens)
               for j in live}
        order = sorted(live, key=lambda j: (est[j] or 0.0,
                                            snaps[j]["queue_depth"], j))
        if req.params.adapter is not None:
            # tenant work can ONLY move where its adapter is resident
            # (or hot-loadable): adopt() bypasses submit-time adapter
            # validation, so a non-resident target would fail the
            # request at admission — returning False instead hands it
            # back to the draining replica, where the adapter IS
            # resident and the drain finishes it
            res = [j for j in order
                   if self.engines[j].adapters is not None
                   and self.engines[j].adapters.lookup(req.params.adapter)
                   is not None]
            if not res:
                target = self._hot_load(req.params.adapter, live, est)
                res = [target] if target is not None else []
            order = res
        for j in order:
            try:
                self.engines[j].adopt(req)
            except (EngineDrainingError, QueueFullError, RuntimeError):
                continue
            req.route = {**(req.route or {}), "replica": j,
                         "redispatched_from": from_i}
            with self._lock:
                self.redispatched += 1
            get_metrics().event("router_redispatch", request_id=req.id,
                                from_replica=from_i, to_replica=j,
                                adapter=req.params.adapter)
            return True
        return False

    def restart_replica(self, i: int) -> DecodeEngine:
        """Bring a drained (or dead) replica back: fresh engine from the
        build factory, warmed, started, re-entered into dispatch. The
        fresh engine compiles its own program family (a warmup, not a
        recompile — its watchers freeze after), then serves."""
        if self._factory is None:
            raise RuntimeError(
                "restart_replica needs a router built via "
                "EngineRouter.build (no engine factory)")
        t0 = time.monotonic()
        old = self.engines[i]
        old.shutdown(drain=False)
        eng = self._factory(i)
        eng.warmup()
        eng.start()
        self.engines[i] = eng
        with self._lock:
            self._out.discard(i)
        get_metrics().event("replica_restart", replica=i,
                            seconds=round(time.monotonic() - t0, 3))
        logger.warning("Replica %d restarted (%.1fs).", i,
                       time.monotonic() - t0)
        return eng

    def drain(self, timeout: float = 30.0) -> dict:
        """Fleet drain (the SIGTERM path): ROLLING — each replica's
        queued work re-dispatches onto the replicas still serving, the
        last one drains plain. ``timeout`` applies per replica."""
        live = [i for i in range(len(self.engines))
                if i not in self._out]
        out: dict = {"n_preempted": 0, "n_redispatched": 0}
        for k, i in enumerate(live):
            s = self.drain_replica(i, timeout=timeout,
                                   redispatch=(k < len(live) - 1))
            out["n_preempted"] += s.get("n_preempted", 0)
        with self._lock:
            out["n_redispatched"] = self.redispatched
        return out

    # -- engine-shaped introspection --------------------------------------

    @property
    def draining(self) -> bool:
        return all(eng.draining or i in self._out
                   for i, eng in enumerate(self.engines))

    @property
    def _dead(self) -> Optional[str]:
        msgs = [eng._dead for eng in self.engines]
        if all(m is not None for m in msgs):
            return f"all {len(msgs)} replicas dead: {msgs[0]}"
        return None

    @property
    def warmed_up(self) -> bool:
        return all(eng.warmed_up for eng in self.engines)

    @property
    def default_max_new_tokens(self) -> int:
        return self.engines[0].default_max_new_tokens

    @property
    def n_recompiles(self) -> int:
        return sum(eng.n_recompiles for eng in self.engines)

    @property
    def n_replicas(self) -> int:
        return len(self.engines)

    def queue_capacity(self) -> int:
        return sum(eng.queue.max_size for eng in self.engines)

    def estimate_queue_clear_s(self) -> Optional[float]:
        """Fleet Retry-After: the BEST live replica's backlog estimate
        (a retrying client should come back when somewhere has room)."""
        from building_llm_from_scratch_tpu.serving.engine import (
            queue_clear_estimate,
        )

        ests = []
        for i in self._live():
            snap = self.engines[i].service_snapshot()
            est = queue_clear_estimate(
                snap["queue_depth"], snap["n_active"], snap["n_slots"],
                snap["tpot_ewma"], snap["tokens_ewma"])
            if est is not None:
                ests.append(est)
        return round(min(ests), 3) if ests else None

    def stats(self) -> dict:
        with self._lock:
            out = {
                "n_replicas": len(self.engines),
                "routed_total": self.routed_total,
                "routed_affinity": self.routed_affinity,
                "routed_spill": self.routed_spill,
                "hot_loads": self.hot_loads,
                "redispatched": self.redispatched,
            }
            if self.routed_total:
                out["routed_by_affinity_ratio"] = round(
                    self.routed_affinity / self.routed_total, 6)
        out["replicas"] = [eng.stats() for eng in self.engines]
        for key in ("requests_finished", "requests_failed",
                    "requests_shed", "requests_expired",
                    "tokens_generated", "n_recompiles"):
            out[key] = sum(r.get(key, 0) for r in out["replicas"])
        return out

    def metrics_snapshot(self) -> tuple:
        """Fleet (counters, gauges, histograms): every replica's series
        re-keyed with a ``{replica="i"}`` label (merged into existing
        label sets), plus unlabeled fleet-level aggregates."""
        counters: dict = {}
        gauges: dict = {}
        hists: dict = {}
        up = 0
        occ = []
        qdepth = 0
        for i, eng in enumerate(self.engines):
            c, g, h = eng.metrics_snapshot()
            for k, v in c.items():
                counters[_labeled(k, i)] = v
            for k, v in g.items():
                gauges[_labeled(k, i)] = v
            for k, v in h.items():
                hists[_labeled(k, i)] = v
            if eng._dead is None:
                up += 1
            occ.append(g.get("slot_occupancy", 0.0))
            qdepth += g.get("queue_depth", 0)
        with self._lock:
            counters["routed_requests"] = self.routed_total
            counters["routed_affinity"] = self.routed_affinity
            counters["routed_spill"] = self.routed_spill
            counters["adapter_hot_loads"] = self.hot_loads
            counters["redispatched_requests"] = self.redispatched
            ratio = (self.routed_affinity / self.routed_total
                     if self.routed_total else 0.0)
        gauges["replicas_up"] = up
        gauges["replicas_total"] = len(self.engines)
        gauges["fleet_occupancy"] = round(sum(occ) / max(len(occ), 1), 6)
        gauges["fleet_queue_depth"] = qdepth
        gauges["routed_by_affinity_ratio"] = round(ratio, 6)
        return counters, gauges, hists

    def prometheus_text(self) -> str:
        counters, gauges, hists = self.metrics_snapshot()
        return render_prometheus(counters, gauges, hists,
                                 prefix="bllm_serve_")

    def healthz_payload(self) -> dict:
        replicas = []
        for i, eng in enumerate(self.engines):
            p = eng.healthz_payload()
            replicas.append({
                "replica": i,
                "status": ("out" if i in self._out and p["status"] ==
                           "serving" else p["status"]),
                "active": p["active"],
                "queue_depth": p["queue_depth"],
                "occupancy": p["occupancy"],
                "restarts": p["restarts"],
                "slo_miss_ratio": p["slo_miss_ratio"],
            })
        up = [r for r in replicas if r["status"] == "serving"]
        if self._dead is not None:
            status = "dead"
        elif self.draining:
            status = "draining"
        elif not up:
            status = "degraded"
        else:
            status = "serving"
        with self._lock:
            routing = {
                "routed_total": self.routed_total,
                "routed_affinity": self.routed_affinity,
                "routed_spill": self.routed_spill,
                "redispatched": self.redispatched,
            }
        return {
            "status": status,
            "replicas_up": len(up),
            "replicas_total": len(self.engines),
            "queue_depth": sum(r["queue_depth"] for r in replicas),
            "queue_capacity": self.queue_capacity(),
            "warmed_up": self.warmed_up,
            "draining": self.draining,
            "routing": routing,
            "replicas": replicas,
        }
