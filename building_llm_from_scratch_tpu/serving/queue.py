"""Bounded FIFO request queue with explicit backpressure.

The engine's admission control: ``put`` on a full queue either rejects
immediately (``QueueFullError`` — the HTTP frontend turns this into a 429)
or blocks until a slot retirement drains the queue (the JSONL batch
frontend's backpressure). Deliberately NOT stdlib ``queue.Queue``: the
scheduler needs non-destructive inspection (``peek``/depth) and the
rejection path must be an exception the frontends can map to a status,
not a sentinel.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from building_llm_from_scratch_tpu.serving.request import Request


class QueueFullError(Exception):
    """The bounded request queue is at capacity (reject-over-capacity)."""


class SLOShedError(Exception):
    """Admission predicted the request would blow its deadline before a
    slot could serve it (queue position x the live TPOT-EWMA service
    estimate), so it was shed at submit time — a useful 429 now instead
    of a useless 504 later. ``retry_after_s`` is the estimate of when the
    backlog will have drained enough to try again."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class EngineDrainingError(Exception):
    """The engine is draining (SIGTERM/shutdown in progress): admission is
    closed, in-flight work is finishing. The HTTP frontend maps this to
    503 + Retry-After."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class PromptTooLongError(ValueError):
    """The prompt (or prompt + decode budget) exceeds what this engine can
    admit. A ``ValueError`` subclass so callers that mapped the old
    generic rejection keep working, but typed so the HTTP frontend can
    answer 413 (the client must shorten the payload, not retry it).

    ``limit`` is the engine's admission ceiling in prompt tokens; on a
    sequence-sharded engine (``--serve_sp``) it is the SEQ-SHARDED
    ceiling — ``pane_tokens`` per device x ``sp`` devices — so the error
    reports how far the long-context path actually lifted admission."""

    def __init__(self, msg: str, *, prompt_tokens: int, limit: int,
                 pane_tokens: Optional[int] = None, sp: int = 1):
        super().__init__(msg)
        self.prompt_tokens = prompt_tokens
        self.limit = limit
        self.pane_tokens = pane_tokens
        self.sp = sp


class RequestQueue:
    def __init__(self, max_size: int = 64):
        if max_size < 1:
            raise ValueError("max_size must be >= 1")
        self.max_size = max_size
        self._lock = threading.Lock()
        # the condvar WRAPS _lock, so `with self._not_full:` and
        # `with self._lock:` acquire the same mutex (graft-lint GL03x
        # understands the alias)
        self._not_full = threading.Condition(self._lock)
        self._q: "collections.deque[Request]" = (
            collections.deque())                        # guarded-by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def put(self, req: Request, block: bool = False,
            timeout: Optional[float] = None) -> None:
        """Enqueue FCFS; raises ``QueueFullError`` when at capacity (or
        after ``timeout`` when ``block=True``)."""
        with self._not_full:
            if len(self._q) >= self.max_size:
                if not block:
                    raise QueueFullError(
                        f"request queue full ({self.max_size})")
                if not self._not_full.wait_for(
                        lambda: len(self._q) < self.max_size,
                        timeout=timeout):
                    raise QueueFullError(
                        f"request queue still full ({self.max_size}) "
                        f"after {timeout}s")
            self._q.append(req)

    def put_front(self, req: Request) -> None:
        """Re-queue a request at the HEAD, bypassing the capacity check.

        The paged engine's oversubscription path: admission popped the
        request (the scheduler's ``admit_from`` is destructive) and THEN
        found the page pool too drained for its worst-case need — the
        request must go back where it was, ahead of everything behind
        it, even if callers filled the queue meanwhile. Capacity was
        already charged when it was first admitted; bouncing it now
        would turn a transient full pool into a spurious reject."""
        with self._not_full:
            self._q.appendleft(req)

    def get_nowait(self) -> Optional[Request]:
        """Pop the oldest request, or None when empty."""
        with self._not_full:
            if not self._q:
                return None
            req = self._q.popleft()
            self._not_full.notify()
            return req

    def remove(self, req: Request) -> bool:
        """Drop one specific queued request (client cancellation). Returns
        False when it is not in the queue (already admitted or popped)."""
        with self._not_full:
            try:
                self._q.remove(req)
            except ValueError:
                return False
            self._not_full.notify()
            return True

    def peek(self) -> Optional[Request]:
        with self._lock:
            return self._q[0] if self._q else None
