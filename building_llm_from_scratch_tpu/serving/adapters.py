"""Multi-tenant LoRA adapter registry for the serving engine.

The serving gap this closes: ``models/lora.py`` applies adapters by
MERGING — ``W' = W + s·A@B`` — which is one weight copy per adapter and
therefore one tenant per engine. The registry instead keeps adapters
device-resident in a STACKED pool:

    pool leaf shapes = (n_adapters_max, ...adapter leaf...)
    scaling          = (n_adapters_max,) fp32  (alpha/rank per row)

Adapter COUNT is a static capacity baked into the compiled programs;
adapter IDENTITY is a data dimension (per-slot ``adapter_id`` arrays flow
into the engine's prefill/decode programs, id −1 = base model). So:

  - any mix of adapters + base traffic decodes in the engine's ONE
    compiled decode program (CompileWatcher-asserted in tests/CI);
  - hot-loading an adapter is a functional ``pool.at[row].set(...)`` —
    new device arrays, same shapes, ZERO recompiles;
  - evicting frees the name/row immediately but NEVER zeroes the pool
    row: an in-flight request keeps decoding against the weights it was
    admitted with, and the row is only reused once no active slot
    references it (the engine's in-use probe).

Artifacts come from finetuning's ``--save_adapter`` (models/lora.py npz
format: A/B tree + rank/alpha/base-config fingerprint). The registry
refuses artifacts whose fingerprint mismatches its base model — a LoRA
delta against different base weights is silent garbage, not an error
XLA would ever raise.

Concurrency contract (mirrors the engine's lock discipline): mutations
(``load``/``evict``) serialize on the registry lock; the engine-side
reads (``lookup`` per admission, ``pool_args`` per tick) are LOCK-FREE
snapshot reads of copy-on-write references — the tick path never takes
the registry lock, so a slow artifact load cannot stall decode, and the
load -> engine-lock (in-use probe) edge cannot deadlock against the
tick's engine-lock -> registry reads.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models.lora import (
    adapter_fingerprint,
    init_lora_params,
    load_adapter,
)
from building_llm_from_scratch_tpu.obs.metrics import get_metrics
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

Params = Dict[str, Any]

#: adapter name the telemetry uses for un-adapted (base-model) requests
BASE_ADAPTER = "base"

#: legal adapter names: these flow verbatim into Prometheus label values
#: and log lines — quotes/braces/backslashes/whitespace would corrupt the
#: whole /metrics exposition, so they are refused at load time (the one
#: gate every served name passes through)
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.:-]*$")


class AdapterRegistryFullError(RuntimeError):
    """No free pool row: every row is loaded or still referenced by an
    active slot. Raise capacity (``--serve_adapter_slots``) or evict."""


class AdapterMismatchError(ValueError):
    """Artifact's base-config fingerprint does not match the loaded
    model — the A/B deltas would multiply against the wrong weights."""


def _leaf_pad_axis(path) -> int:
    """Which axis of an adapter leaf is the RANK axis: A leaves are
    (..., in, r) — last; B leaves are (..., r, out) — second-to-last."""
    name = path[-1].key
    return -1 if name == "A" else -2


class AdapterRegistry:
    """Device-resident stacked pool of LoRA adapters, hot-load/evictable
    under live traffic.

    Build one per engine (same ``cfg``/``params`` base), load artifacts,
    then hand it to ``DecodeEngine(..., adapters=registry)``:

        reg = AdapterRegistry(cfg, params, capacity=8, max_rank=16)
        reg.load("tenant-a", "adapters/a.npz")
        engine = DecodeEngine(cfg, params, tok, adapters=reg)
        engine.submit(prompt, SamplingParams(adapter="tenant-a"))

    ``capacity`` and ``max_rank`` are STATIC (they size the pool the
    compiled programs close over); lower-rank artifacts zero-pad up to
    ``max_rank`` — zero columns/rows contribute an exactly-zero delta.
    """

    def __init__(self, cfg: ModelConfig, params: Params, *,
                 capacity: int = 8, max_rank: int = 16):
        import jax
        import jax.numpy as jnp

        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if max_rank < 1:
            raise ValueError("max_rank must be >= 1")
        self.cfg = cfg
        self.capacity = int(capacity)
        self.max_rank = int(max_rank)
        self.fingerprint = adapter_fingerprint(cfg)
        # template defines the pool's tree structure + leaf shapes; the
        # random A init is discarded (rows start zero)
        template = init_lora_params(cfg, params, jax.random.PRNGKey(0),
                                    rank=self.max_rank)
        self._paths = {
            tuple(p.key for p in path)
            for path, _ in jax.tree_util.tree_flatten_with_path(template)[0]
        }
        pool = jax.tree_util.tree_map(
            lambda a: jnp.zeros((self.capacity,) + a.shape, a.dtype),
            template)
        self._lock = threading.Lock()
        # (pool, scaling) swapped as ONE tuple: lock-free readers see a
        # consistent pair. Mutations replace, never write in place.
        self._device: Tuple[Params, Any] = (
            pool, jnp.zeros((self.capacity,), jnp.float32)
        )                                   # guarded-by: _lock [writes]
        self._by_name: Dict[str, int] = {}  # guarded-by: _lock [writes]
        self._meta: Dict[str, dict] = {}    # guarded-by: _lock [writes]
        self._rows: List[Optional[str]] = (
            [None] * self.capacity)         # guarded-by: _lock
        # name -> "name#<install-seq>": the per-INSTALL identity consumers
        # key derived state on (the prefix store namespaces cached KV by
        # it, so evict-and-reload with different weights can never serve
        # a stale pane). Copy-on-write like _by_name for lock-free reads.
        self._tags: Dict[str, str] = {}     # guarded-by: _lock [writes]
        self._in_use_probe: Optional[Callable[[], Set[int]]] = None
        self.n_loads = 0                    # guarded-by: _lock
        self.n_evicts = 0                   # guarded-by: _lock

    @classmethod
    def from_artifacts(cls, cfg: ModelConfig, params: Params,
                       specs: Dict[str, str], *,
                       capacity: int = 0,
                       max_rank: int = 0) -> "AdapterRegistry":
        """Build + load a registry from {name: artifact_path}. With
        ``capacity=0`` leave one spare row of hot-load headroom; with
        ``max_rank=0`` size the rank to the largest artifact. Each
        artifact is parsed ONCE (meta sizes the pool, then the same
        parse installs)."""
        parsed = {name: (path, load_adapter(path))
                  for name, path in specs.items()}
        if not max_rank:
            max_rank = max((meta["rank"] for _p, (_l, meta)
                            in parsed.values()), default=8)
        if not capacity:
            capacity = max(2, len(specs) + 1)
        reg = cls(cfg, params, capacity=capacity, max_rank=max_rank)
        for name, (path, (lora, meta)) in parsed.items():
            reg._install(name, path, lora, meta, time.monotonic())
        return reg

    # -- engine-side reads (lock-free snapshots; see module docstring) ----

    def pool_args(self) -> Tuple[Params, Any]:
        """(stacked pool tree, (capacity,) scaling) — the per-call device
        arguments the engine threads into its compiled programs. One
        atomic tuple read; called every tick."""
        return self._device

    def lookup(self, name: str) -> Optional[int]:
        """Pool row for ``name``; None when not loaded (engine fails the
        request with reason ``adapter_not_loaded``). Called per admission."""
        return self._by_name.get(name)

    def resolve(self, name: str) -> int:
        """Like ``lookup`` but raising — the submit-time rejection path."""
        row = self._by_name.get(name)
        if row is None:
            raise KeyError(
                f"adapter '{name}' is not loaded (loaded: "
                f"{sorted(self._by_name) or 'none'})")
        return row

    def load_tag(self, name: str) -> Optional[str]:
        """Per-install identity for ``name`` (``name#<seq>``), or None
        when not loaded. Lock-free snapshot read (called per admission
        by the engine's prefix-store namespacing): a reloaded adapter
        gets a fresh tag, so state derived from the OLD install — cached
        prefix KV panes above all — silently stops matching instead of
        serving stale weights' output."""
        return self._tags.get(name)

    def names(self) -> List[str]:
        return sorted(self._by_name)

    @property
    def n_loaded(self) -> int:
        return len(self._by_name)

    # -- engine wiring -----------------------------------------------------

    def place_pool(self, put: Callable[[Any], Any]) -> None:
        """Re-place the stacked pool through ``put`` (a ``device_put``
        closure — e.g. ``MeshPlan.put_replicated``): a tensor-parallel
        or device-pinned engine needs the pool on ITS mesh, or the
        compiled programs would see arguments spanning two device sets.
        Later ``load``/``evict`` updates are functional ``at[row].set``
        on the placed arrays, so they inherit the placement."""
        import jax

        with self._lock:
            pool, scaling = self._device
            self._device = (jax.tree_util.tree_map(put, pool),
                            put(scaling))

    def set_in_use_probe(self, fn: Callable[[], Set[int]]) -> None:
        """The engine's view of which pool rows active slots reference —
        ``load`` will not reuse those rows even after an evict, so
        hot-load/evict never corrupts an in-flight request's weights."""
        self._in_use_probe = fn

    def _rows_in_use(self) -> Set[int]:
        if self._in_use_probe is None:
            return set()
        try:
            return set(self._in_use_probe())
        except Exception:           # noqa: BLE001 — a wedged engine must
            # not block registry admin; worst case we skip reusing a row
            return set(range(self.capacity))

    # -- mutations ---------------------------------------------------------

    def load(self, name: str, path: str) -> int:
        """Load one artifact into a free pool row; returns the row id.

        Fingerprint-checked against the registry's base model; rank
        zero-padded to ``max_rank``. The pool update is functional
        (``at[row].set``) — same shapes, so the engine's frozen compiled
        programs accept the new arrays with zero recompiles."""
        t0 = time.monotonic()
        lora, meta = load_adapter(path)
        return self._install(name, path, lora, meta, t0)

    def _install(self, name: str, path: str, lora: Params, meta: dict,
                 t0: float) -> int:
        """Validate + write one already-parsed artifact into the pool."""
        import jax
        import jax.numpy as jnp

        if not _NAME_RE.match(name):
            raise ValueError(
                f"adapter name '{name}' is invalid: names flow into "
                "metrics labels and must match "
                "[A-Za-z0-9][A-Za-z0-9_.:-]*")
        if name == BASE_ADAPTER:
            raise ValueError(
                f"adapter name '{BASE_ADAPTER}' is reserved: it is the "
                "telemetry bucket for un-adapted (base-model) traffic")
        if meta["fingerprint"] != self.fingerprint:
            raise AdapterMismatchError(
                f"adapter '{name}' ({path}) was trained against base "
                f"config {meta.get('model')}/{meta['fingerprint']}, but "
                f"this registry serves {self.cfg.name}/{self.fingerprint}")
        rank = int(meta["rank"])
        if rank > self.max_rank:
            raise ValueError(
                f"adapter '{name}' rank {rank} exceeds the pool's static "
                f"max_rank {self.max_rank} (rebuild the registry larger)")
        flat = jax.tree_util.tree_flatten_with_path(lora)[0]
        got = {tuple(p.key for p in path) for path, _ in flat}
        if got != self._paths:
            missing = sorted(".".join(p) for p in self._paths - got)
            extra = sorted(".".join(p) for p in got - self._paths)
            raise ValueError(
                f"adapter '{name}' tree mismatch: missing {missing}, "
                f"unexpected {extra}")
        with self._lock:
            if name in self._by_name:
                raise ValueError(f"adapter '{name}' is already loaded "
                                 "(evict it first to replace)")
            in_use = self._rows_in_use()
            row = next((r for r in range(self.capacity)
                        if self._rows[r] is None and r not in in_use), None)
            if row is None:
                raise AdapterRegistryFullError(
                    f"no free adapter row: {self.n_loaded}/{self.capacity} "
                    f"loaded, {sorted(in_use)} still referenced by active "
                    "slots")

            def write_row(pool_leaf, path_leaf):
                path, leaf = path_leaf
                pad_axis = _leaf_pad_axis(path) % leaf.ndim
                pads = [(0, 0)] * leaf.ndim
                pads[pad_axis] = (0, self.max_rank - rank)
                padded = np.pad(np.asarray(leaf), pads)
                return pool_leaf.at[row].set(
                    jnp.asarray(padded, pool_leaf.dtype))

            pool, scaling = self._device
            flat_pool, treedef = jax.tree_util.tree_flatten(pool)
            # flatten orders match: both trees share the template paths
            new_pool = jax.tree_util.tree_unflatten(
                treedef, [write_row(pl, fl)
                          for pl, fl in zip(flat_pool, flat)])
            new_scaling = scaling.at[row].set(
                float(meta["alpha"]) / float(rank))
            self._device = (new_pool, new_scaling)
            self._rows[row] = name
            self._by_name = {**self._by_name, name: row}
            self._meta = {**self._meta, name: meta}
            self.n_loads += 1
            self._tags = {**self._tags, name: f"{name}#{self.n_loads}"}
            n_loaded = self.n_loaded
        get_metrics().event(
            "adapter_load", name=name, path=path, row=row, rank=rank,
            alpha=float(meta["alpha"]), n_loaded=n_loaded,
            capacity=self.capacity,
            seconds=round(time.monotonic() - t0, 4))
        logger.info("Adapter '%s' loaded into row %d (rank %d, %d/%d).",
                    name, row, rank, n_loaded, self.capacity)
        return row

    def replace(self, name: str, path: str) -> int:
        """Evict-if-present then load — the continuous train→deploy hop
        (training/lora_fusion.py): a fleet job that finishes REDEPLOYS
        its tenant's adapter under the same name. The evicted install's
        row stays untouched until in-flight requests retire (the in-use
        probe), the reload gets a fresh ``load_tag`` so derived state
        (cached prefix panes) auto-invalidates, and requests queued
        between evict and load fail alone with ``adapter_not_loaded`` —
        exactly the evicted-while-queued semantics already tested."""
        if self._by_name.get(name) is not None:
            self.evict(name)
        return self.load(name, path)

    def evict(self, name: str) -> int:
        """Unload ``name``: new submits for it are rejected immediately;
        the pool row's weights stay in place until every active slot
        referencing it retires (in-use probe guards reuse), so in-flight
        requests finish untouched. Returns the freed row."""
        with self._lock:
            row = self._by_name.get(name)
            if row is None:
                raise KeyError(f"adapter '{name}' is not loaded")
            self._rows[row] = None
            by = dict(self._by_name)
            del by[name]
            self._by_name = by
            meta = dict(self._meta)
            meta.pop(name, None)
            self._meta = meta
            tags = dict(self._tags)
            tags.pop(name, None)
            self._tags = tags
            self.n_evicts += 1
            n_loaded = self.n_loaded
        get_metrics().event("adapter_evict", name=name, row=row,
                            n_loaded=n_loaded)
        logger.info("Adapter '%s' evicted from row %d (%d loaded).",
                    name, row, n_loaded)
        return row

    # -- introspection -----------------------------------------------------

    def pool_nbytes(self) -> int:
        """Total device bytes of the stacked pool (+ scaling vector),
        measured from the live arrays — the memory ledger's
        ``adapter_pool`` component. Metadata only: never syncs."""
        from building_llm_from_scratch_tpu.obs.memory import pytree_nbytes

        pool, scaling = self._device
        return pytree_nbytes(pool) + int(scaling.nbytes)

    def bytes_by_adapter(self) -> Dict[str, int]:
        """Per-tenant attribution: each loaded adapter owns 1/capacity
        of the (fixed-shape, zero-padded) pool. Unloaded rows are the
        pool's standing headroom and stay unattributed — the component
        total still reports them."""
        per_row = self.pool_nbytes() // max(self.capacity, 1)
        return {name: per_row for name in self._by_name}

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "max_rank": self.max_rank,
                "n_loaded": self.n_loaded,
                "n_loads": self.n_loads,
                "n_evicts": self.n_evicts,
                "adapters": {
                    name: {"row": row,
                           "rank": self._meta[name]["rank"],
                           "alpha": self._meta[name]["alpha"]}
                    for name, row in sorted(self._by_name.items())
                },
            }
