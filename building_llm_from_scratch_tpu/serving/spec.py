"""Speculative-decoding drafters for the serving engine.

TPOT — the per-token decode latency — is one full forward pass per tick,
the one axis continuous batching cannot attack. Speculative decoding
breaks it: a cheap DRAFTER proposes k candidate tokens per slot per
tick and ONE compiled verify program (``models/transformer.verify_slots``,
a Tq=k+1 sibling of the decode step) scores all k+1 positions in a
single forward; the engine commits the longest valid prefix
(``generate.accept_draft_tokens`` — exact-match acceptance, which for
the point-mass drafts below IS Leviathan rejection sampling and keeps
engine tokens bit-identical to the spec-off path). k is static, the
drafts and the accepted counts are DATA — the engine keeps its
one-compiled-program invariant at any acceptance rate.

This module is the drafting side. The first drafter is PROMPT-LOOKUP /
n-gram drafting (Saxena's prompt-lookup decoding; the self-history
variant): suffix-match the slot's last n committed tokens against its
OWN token history (prompt + generated) and propose the continuation of
the most recent earlier occurrence. Zero extra model, zero extra HBM,
pure host-side numpy on arrays the engine already keeps — and extremely
effective exactly where decode latency hurts most (templated prompts,
extraction/summarization over a context, code, any self-repetitive
generation).

The ``Drafter`` interface is deliberately tiny so a model-based drafter
(a small GPT-2 proposing for a large target) can slot in later: one
``propose(history, k) -> (k,) int32`` per active slot per tick. A draft
is a POINT MASS — the accept rule relies on that (see
``accept_draft_tokens``); a future distribution-emitting drafter would
extend the accept rule, not this interface.
"""

from __future__ import annotations

import numpy as np

__all__ = ["Drafter", "NgramDrafter"]


class Drafter:
    """Drafter interface: propose k candidate next tokens for one slot.

    ``history`` is the slot's committed token ids (prompt + generated so
    far, most recent last) as a 1-D int32 numpy array — host state the
    engine already tracks; ``propose`` must be pure host compute (it runs
    inside the engine tick, registered as a GL01x hot path: a device
    sync here would stall every co-resident slot).

    Must return exactly ``k`` int32 token ids. There is no "no draft"
    return: with a static verify width, a low-confidence draft costs
    nothing extra to verify and simply gets rejected — propose the best
    guess available (the base class repeats the last token, a fixed
    point of greedy decode loops).
    """

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        return np.full((k,), history[-1], np.int32)

    def describe(self) -> str:
        return type(self).__name__


class NgramDrafter(Drafter):
    """Prompt-lookup / n-gram drafting against the slot's own history.

    For n from ``max_n`` down to ``min_n``: take the history's last n
    tokens as the query, find the MOST RECENT earlier occurrence of that
    n-gram, and propose the k tokens that followed it. Longer matches
    are tried first (more context = better continuation); the first hit
    wins. No occurrence at any n falls back to repeating the last token
    (``Drafter.propose``) — still a valid point-mass draft, and the
    fixed point greedy decode converges to anyway.

    The scan is one vectorized sliding-window comparison per n
    (O(len(history) * n) numpy ops, no Python loop over positions), so a
    full slot batch drafts in well under the cost of one model forward.
    """

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if min_n < 1 or max_n < min_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got min_n={min_n} "
                f"max_n={max_n}")
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        L = history.shape[0]
        for n in range(self.max_n, self.min_n - 1, -1):
            if L < n + 1:
                continue            # history too short to match AND continue
            suffix = history[L - n:]
            # windows[s] = history[s : s+n]; candidate starts s < L-n (the
            # window at L-n is the query suffix itself) — every candidate
            # therefore has >= 1 continuation token at s+n
            windows = np.lib.stride_tricks.sliding_window_view(history, n)
            hits = np.flatnonzero(
                (windows[: L - n] == suffix).all(axis=1))
            if hits.size == 0:
                continue
            s = hits[-1]            # most recent occurrence wins
            cont = history[s + n: s + n + k]
            if cont.shape[0] < k:   # ran off the end: pad with last token
                cont = np.concatenate(
                    [cont, np.full((k - cont.shape[0],), cont[-1],
                                   history.dtype)])
            return cont.astype(np.int32, copy=False)
        return super().propose(history, k)

    def describe(self) -> str:
        return f"ngram(max_n={self.max_n},min_n={self.min_n})"
