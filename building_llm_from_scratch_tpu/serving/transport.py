"""Stdlib-only RPC transport for the cross-process fleet.

Framing is deliberately boring: a 4-byte big-endian length prefix
followed by a UTF-8 JSON payload over an AF_UNIX stream socket. Boring
is the point — the supervisor must classify every way a worker can
misbehave into a TYPED error it can act on:

  - ``PeerGoneError``    — EOF / reset / refused connection: the process
    on the other end is dead (or never existed). The supervisor's cue to
    run the death path (re-dispatch + restart).
  - ``PeerTimeoutError`` — the peer is alive but slow past the per-call
    deadline. NOT a death signal: a wedged worker gets killed by the
    heartbeat monitor, not by an impatient caller.
  - ``FrameTooLargeError`` — the declared length exceeds the bound. The
    reader rejects on the HEADER, before allocating or reading a single
    payload byte, so a hostile/corrupt peer can never OOM the router.
  - ``FrameCorruptError`` — undecodable JSON or a non-object payload.

After ``FrameTooLargeError``/``FrameCorruptError`` the stream offset is
unrecoverable (we no longer know where the next frame starts) — callers
must close the connection; both server and client do.

Application-level errors cross the wire as ``{"err": {"type": ..}}``
responses and re-raise CLIENT-side as the same typed exceptions the
in-process engine raises (``QueueFullError``, ``SLOShedError``,
``EngineDrainingError``, ...) so the frontends' status-code mapping
works unchanged whether the engine is a thread away or a process away.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    QueueFullError,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import RequestExpiredError
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

#: Frame-size bound. Prefix-pane handoff ships KV panes (a few MB per
#: pane at toy scale, tens of MB for real configs), so the default is
#: generous; control traffic is a few KB. The bound is enforced on the
#: HEADER — an oversized declaration is rejected without reading (or
#: allocating) the payload.
MAX_FRAME_BYTES = 256 * 1024 * 1024

_HDR = struct.Struct(">I")


class TransportError(RuntimeError):
    """Base class for transport-layer failures."""


class PeerGoneError(TransportError):
    """The peer closed / reset / refused the connection: it is dead."""


class PeerTimeoutError(TransportError):
    """The peer did not answer within the per-call deadline (alive but
    slow — distinct from dead)."""


class FrameTooLargeError(TransportError):
    """Declared frame length exceeds the bound; payload never read."""


class FrameCorruptError(TransportError):
    """Frame payload is not valid JSON (or not a JSON object)."""


# application errors that cross the wire typed; each entry maps the wire
# tag to (exception class, carries_retry_after)
_ERR_TYPES: Dict[str, Tuple[type, bool]] = {
    "queue_full": (QueueFullError, False),
    "slo_shed": (SLOShedError, True),
    "draining": (EngineDrainingError, True),
    "expired": (RequestExpiredError, False),
    "value_error": (ValueError, False),
    "runtime": (RuntimeError, False),
}
_ERR_TAGS = {cls: tag for tag, (cls, _) in _ERR_TYPES.items()}


def error_payload(exc: BaseException) -> dict:
    """Serialize an exception into the wire error object."""
    tag = _ERR_TAGS.get(type(exc))
    if tag is None:
        for cls, t in _ERR_TAGS.items():
            if isinstance(exc, cls):
                tag = t
                break
    err: Dict[str, Any] = {"type": tag or "runtime", "message": str(exc)}
    retry = getattr(exc, "retry_after_s", None)
    if retry is not None:
        err["retry_after_s"] = retry
    return err


def raise_typed(err: dict) -> None:
    """Re-raise a wire error object as its typed exception."""
    tag = err.get("type", "runtime")
    msg = err.get("message", "remote error")
    cls, has_retry = _ERR_TYPES.get(tag, (RuntimeError, False))
    if has_retry:
        raise cls(msg, retry_after_s=err.get("retry_after_s"))
    raise cls(msg)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            raise PeerTimeoutError(
                f"peer did not answer within {sock.gettimeout()}s")
        except OSError as e:
            raise PeerGoneError(f"peer connection lost: {e}")
        if not chunk:
            raise PeerGoneError(
                "peer closed the connection"
                + (" mid-frame" if buf else ""))
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, obj: dict,
               max_frame_bytes: int = MAX_FRAME_BYTES) -> None:
    payload = json.dumps(obj).encode("utf-8")
    if len(payload) > max_frame_bytes:
        raise FrameTooLargeError(
            f"refusing to send {len(payload)}B frame "
            f"(bound {max_frame_bytes}B)")
    try:
        sock.sendall(_HDR.pack(len(payload)) + payload)
    except socket.timeout:
        raise PeerTimeoutError(
            f"send blocked past {sock.gettimeout()}s (peer slow)")
    except OSError as e:
        raise PeerGoneError(f"peer connection lost on send: {e}")


def recv_frame(sock: socket.socket,
               max_frame_bytes: int = MAX_FRAME_BYTES) -> dict:
    (length,) = _HDR.unpack(_read_exact(sock, _HDR.size))
    if length > max_frame_bytes:
        # reject on the header — the payload is never read, so a
        # hostile length can't make us allocate
        raise FrameTooLargeError(
            f"peer declared {length}B frame (bound {max_frame_bytes}B)")
    payload = _read_exact(sock, length)
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameCorruptError(f"undecodable frame: {e}")
    if not isinstance(obj, dict):
        raise FrameCorruptError(
            f"frame decodes to {type(obj).__name__}, expected object")
    return obj


class RpcClient:
    """Serialized request/response calls over one connection.

    One in-flight call at a time (``_lock``): the protocol has no
    request ids on the response path, so ordering IS the correlation.
    Per-call timeouts via ``settimeout``; a timeout raises
    ``PeerTimeoutError`` and poisons the connection (the late response
    would desynchronize correlation), so the client closes it.
    """

    def __init__(self, path: str, *, timeout: float = 10.0,
                 max_frame_bytes: int = MAX_FRAME_BYTES):
        self.path = path
        self.timeout = timeout
        self.max_frame_bytes = max_frame_bytes
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None     # guarded-by: _lock
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        try:
            sock.connect(path)
        except socket.timeout:
            sock.close()
            raise PeerTimeoutError(f"connect to {path} timed out")
        except OSError as e:
            sock.close()
            raise PeerGoneError(f"connect to {path} failed: {e}")
        self._sock = sock

    def call(self, method: str, rpc_timeout: Optional[float] = None,
             **args: Any) -> Any:
        """Invoke ``method`` on the peer; returns its result object.
        ``rpc_timeout`` overrides the client deadline for this one call
        (named to never collide with application kwargs like ``timeout``).

        Application errors re-raise typed (see ``raise_typed``);
        transport failures raise ``TransportError`` subclasses and close
        the connection (it is not reusable after either a timeout or a
        framing fault).
        """
        poisoned = None
        try:
            with self._lock:
                sock = self._sock
                if sock is None:
                    raise PeerGoneError("client closed")
                sock.settimeout(self.timeout if rpc_timeout is None
                                else rpc_timeout)
                try:
                    send_frame(sock, {"method": method, "args": args},
                               self.max_frame_bytes)
                    resp = recv_frame(sock, self.max_frame_bytes)
                except TransportError:
                    self._sock = None        # detach under the lock ...
                    poisoned = sock
                    raise
        finally:
            if poisoned is not None:         # ... close outside it
                try:
                    poisoned.close()
                except OSError:
                    pass
        if "err" in resp:
            raise_typed(resp["err"])
        return resp.get("result")

    def close(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


#: sentinel result: the handler took ownership of the socket (event
#: subscription); the server acks and stops reading that connection
DETACH = object()

Handler = Callable[[str, dict, socket.socket], Any]


class RpcServer:
    """Threaded unix-socket RPC server.

    ``handler(method, args, sock) -> result`` runs on the connection's
    thread. A handler may return ``(DETACH, result)`` to take ownership
    of the socket after the ack (the worker's event-push channel).
    Handler exceptions become typed error responses — the server loop
    NEVER dies on a bad request; framing faults (oversized/garbage)
    get a best-effort error frame and the connection is closed, because
    the stream offset is gone.
    """

    def __init__(self, path: str, handler: Handler, *,
                 max_frame_bytes: int = MAX_FRAME_BYTES):
        self.path = path
        self.handler = handler
        self.max_frame_bytes = max_frame_bytes
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: set = set()                       # guarded-by: _lock
        self._threads: list = []

    def start(self) -> None:
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.path)
        listener.listen(16)
        self._listener = listener
        t = threading.Thread(target=self._accept_loop,
                             name="rpc-accept", daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return                                 # listener closed
            with self._lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="rpc-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        detached = False
        try:
            while not self._stop.is_set():
                try:
                    frame = recv_frame(conn, self.max_frame_bytes)
                except (PeerGoneError, PeerTimeoutError):
                    return
                except (FrameTooLargeError, FrameCorruptError) as e:
                    # stream offset unrecoverable: answer typed, close
                    try:
                        send_frame(conn, {"err": {
                            "type": "runtime",
                            "message": f"bad frame: {e}"}})
                    except TransportError:
                        pass
                    return
                method = frame.get("method")
                args = frame.get("args") or {}
                if not isinstance(method, str) or not isinstance(args, dict):
                    try:
                        send_frame(conn, {"err": {
                            "type": "value_error",
                            "message": "malformed request frame"}})
                        continue
                    except TransportError:
                        return
                try:
                    result = self.handler(method, args, conn)
                except TransportError:
                    return
                except BaseException as e:             # typed error reply
                    try:
                        send_frame(conn, {"err": error_payload(e)})
                        continue
                    except TransportError:
                        return
                if isinstance(result, tuple) and len(result) == 2 \
                        and result[0] is DETACH:
                    try:
                        send_frame(conn, {"result": result[1]},
                                   self.max_frame_bytes)
                    except TransportError:
                        return
                    detached = True
                    return                             # handler owns sock
                try:
                    send_frame(conn, {"result": result},
                               self.max_frame_bytes)
                except TransportError:
                    return
        finally:
            if not detached:
                with self._lock:
                    self._conns.discard(conn)
                try:
                    conn.close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
