"""Stdlib-only RPC transport for the cross-process fleet.

Framing is deliberately boring: a 4-byte big-endian length prefix
followed by a UTF-8 JSON payload over an AF_UNIX stream socket. Boring
is the point — the supervisor must classify every way a worker can
misbehave into a TYPED error it can act on:

  - ``PeerGoneError``    — EOF / reset / refused connection: the process
    on the other end is dead (or never existed). The supervisor's cue to
    run the death path (re-dispatch + restart).
  - ``PeerTimeoutError`` — the peer is alive but slow past the per-call
    deadline. NOT a death signal: a wedged worker gets killed by the
    heartbeat monitor, not by an impatient caller.
  - ``FrameTooLargeError`` — the declared length exceeds the bound. The
    reader rejects on the HEADER, before allocating or reading a single
    payload byte, so a hostile/corrupt peer can never OOM the router.
  - ``FrameCorruptError`` — undecodable JSON or a non-object payload.

After ``FrameTooLargeError``/``FrameCorruptError`` the stream offset is
unrecoverable (we no longer know where the next frame starts) — callers
must close the connection; both server and client do.

Application-level errors cross the wire as ``{"err": {"type": ..}}``
responses and re-raise CLIENT-side as the same typed exceptions the
in-process engine raises (``QueueFullError``, ``SLOShedError``,
``EngineDrainingError``, ...) so the frontends' status-code mapping
works unchanged whether the engine is a thread away or a process away.

Observability (PR 17): the transport sits on every fleet request's
critical path, so it carries its own telemetry. Request frames may carry
a ``trace`` object (request_id + parent-span context) that the server
injects into handler args as ``args["_trace"]``; a request that carries
the client's wall clock as ``ts`` gets its reply stamped with the
server's paired ``{"wall", "mono"}`` clocks, which gives the client a
free NTP-style offset sample per call (offset = server wall minus the
round-trip midpoint, uncertainty = rtt/2 — the ``clock_sync`` event's
math). ``RpcClient`` always sends ``ts``; hand-rolled raw-frame peers
that omit it get byte-identical pre-PR-17 replies. ``RpcStats`` aggregates per-method latency histograms and
frame-byte counters on both ends; all of it is host-side arithmetic on
already-host floats — zero device syncs (GL01x-registered).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from building_llm_from_scratch_tpu.obs.metrics import Histogram

from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    QueueFullError,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import RequestExpiredError
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

#: Frame-size bound. Prefix-pane handoff ships KV panes (a few MB per
#: pane at toy scale, tens of MB for real configs), so the default is
#: generous; control traffic is a few KB. The bound is enforced on the
#: HEADER — an oversized declaration is rejected without reading (or
#: allocating) the payload.
MAX_FRAME_BYTES = 256 * 1024 * 1024

_HDR = struct.Struct(">I")


class TransportError(RuntimeError):
    """Base class for transport-layer failures."""


class PeerGoneError(TransportError):
    """The peer closed / reset / refused the connection: it is dead."""


class PeerTimeoutError(TransportError):
    """The peer did not answer within the per-call deadline (alive but
    slow — distinct from dead)."""


class FrameTooLargeError(TransportError):
    """Declared frame length exceeds the bound; payload never read."""


class FrameCorruptError(TransportError):
    """Frame payload is not valid JSON (or not a JSON object)."""


# application errors that cross the wire typed; each entry maps the wire
# tag to (exception class, carries_retry_after)
_ERR_TYPES: Dict[str, Tuple[type, bool]] = {
    "queue_full": (QueueFullError, False),
    "slo_shed": (SLOShedError, True),
    "draining": (EngineDrainingError, True),
    "expired": (RequestExpiredError, False),
    "value_error": (ValueError, False),
    "runtime": (RuntimeError, False),
}
_ERR_TAGS = {cls: tag for tag, (cls, _) in _ERR_TYPES.items()}


def error_payload(exc: BaseException) -> dict:
    """Serialize an exception into the wire error object."""
    tag = _ERR_TAGS.get(type(exc))
    if tag is None:
        for cls, t in _ERR_TAGS.items():
            if isinstance(exc, cls):
                tag = t
                break
    err: Dict[str, Any] = {"type": tag or "runtime", "message": str(exc)}
    retry = getattr(exc, "retry_after_s", None)
    if retry is not None:
        err["retry_after_s"] = retry
    return err


def raise_typed(err: dict) -> None:
    """Re-raise a wire error object as its typed exception."""
    tag = err.get("type", "runtime")
    msg = err.get("message", "remote error")
    cls, has_retry = _ERR_TYPES.get(tag, (RuntimeError, False))
    if has_retry:
        raise cls(msg, retry_after_s=err.get("retry_after_s"))
    raise cls(msg)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            raise PeerTimeoutError(
                f"peer did not answer within {sock.gettimeout()}s")
        except OSError as e:
            raise PeerGoneError(f"peer connection lost: {e}")
        if not chunk:
            raise PeerGoneError(
                "peer closed the connection"
                + (" mid-frame" if buf else ""))
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, obj: dict,
               max_frame_bytes: int = MAX_FRAME_BYTES) -> int:
    """Send one frame; returns the payload byte count (for the
    frame-bytes counters — header bytes excluded, they're constant)."""
    payload = json.dumps(obj).encode("utf-8")
    if len(payload) > max_frame_bytes:
        raise FrameTooLargeError(
            f"refusing to send {len(payload)}B frame "
            f"(bound {max_frame_bytes}B)")
    try:
        sock.sendall(_HDR.pack(len(payload)) + payload)
    except socket.timeout:
        raise PeerTimeoutError(
            f"send blocked past {sock.gettimeout()}s (peer slow)")
    except OSError as e:
        raise PeerGoneError(f"peer connection lost on send: {e}")
    return len(payload)


def recv_frame_sized(sock: socket.socket,
                     max_frame_bytes: int = MAX_FRAME_BYTES
                     ) -> Tuple[dict, int]:
    """``recv_frame`` plus the payload byte count."""
    (length,) = _HDR.unpack(_read_exact(sock, _HDR.size))
    if length > max_frame_bytes:
        # reject on the header — the payload is never read, so a
        # hostile length can't make us allocate
        raise FrameTooLargeError(
            f"peer declared {length}B frame (bound {max_frame_bytes}B)")
    payload = _read_exact(sock, length)
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameCorruptError(f"undecodable frame: {e}")
    if not isinstance(obj, dict):
        raise FrameCorruptError(
            f"frame decodes to {type(obj).__name__}, expected object")
    return obj, length


def recv_frame(sock: socket.socket,
               max_frame_bytes: int = MAX_FRAME_BYTES) -> dict:
    return recv_frame_sized(sock, max_frame_bytes)[0]


class RpcStats:
    """Thread-safe per-method RPC telemetry: latency histograms plus
    call/error and frame-byte counters. One instance is shared across
    every ``RpcClient`` the fleet owns (so /metrics shows ONE
    ``rpc_client_seconds{method=..}`` family), and one per
    ``RpcServer``. Pure host arithmetic — safe on the request path.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._methods: Dict[str, Dict[str, Any]] = {}

    def _entry(self, method: str) -> Dict[str, Any]:
        e = self._methods.get(method)
        if e is None:
            e = {"calls": 0, "errors": 0, "bytes_sent": 0,
                 "bytes_received": 0, "latency": Histogram()}
            self._methods[method] = e
        return e

    def record(self, method: str, seconds: float, *, sent: int = 0,
               received: int = 0, error: bool = False) -> None:
        with self._lock:
            e = self._entry(method)
            e["calls"] += 1
            if error:
                e["errors"] += 1
            e["bytes_sent"] += sent
            e["bytes_received"] += received
        e["latency"].observe(seconds)          # Histogram has its own lock

    def add_bytes(self, method: str, *, sent: int = 0,
                  received: int = 0) -> None:
        """Bytes-only bump (no call counted) — for the reply frame the
        server sends after ``record`` already counted the handle."""
        with self._lock:
            e = self._entry(method)
            e["bytes_sent"] += sent
            e["bytes_received"] += received

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """method -> {calls, errors, bytes_sent, bytes_received,
        latency: histogram snapshot dict}."""
        with self._lock:
            methods = {m: dict(e) for m, e in self._methods.items()}
        return {m: {"calls": e["calls"], "errors": e["errors"],
                    "bytes_sent": e["bytes_sent"],
                    "bytes_received": e["bytes_received"],
                    "latency": e["latency"].snapshot()}
                for m, e in methods.items()}


class ClockSample:
    """One NTP-style offset estimate of the peer's wall clock.

    ``offset_s`` = peer wall − our wall (subtract it from a peer
    timestamp to land on our timeline); true offset lies within
    ``offset_s ± uncertainty_s`` where uncertainty = rtt/2 (the reply
    could have been stamped anywhere inside the round trip).
    """

    __slots__ = ("offset_s", "uncertainty_s", "rtt_s", "wall",
                 "n_samples")

    def __init__(self, offset_s: float, uncertainty_s: float,
                 rtt_s: float, wall: float, n_samples: int = 1):
        self.offset_s = offset_s
        self.uncertainty_s = uncertainty_s
        self.rtt_s = rtt_s
        self.wall = wall                       # when WE took the sample
        self.n_samples = n_samples


class RpcClient:
    """Serialized request/response calls over one connection.

    One in-flight call at a time (``_lock``): the protocol has no
    request ids on the response path, so ordering IS the correlation.
    Per-call timeouts via ``settimeout``; a timeout raises
    ``PeerTimeoutError`` and poisons the connection (the late response
    would desynchronize correlation), so the client closes it.

    ``stats`` (a shared ``RpcStats``) collects per-method latency and
    frame bytes; ``self.clock`` holds the minimum-uncertainty
    ``ClockSample`` of the peer's wall clock seen so far (every reply
    carries the server's paired timestamps, so each call is a free
    offset sample — tightest rtt wins).
    """

    def __init__(self, path: str, *, timeout: float = 10.0,
                 max_frame_bytes: int = MAX_FRAME_BYTES,
                 stats: Optional[RpcStats] = None):
        self.path = path
        self.timeout = timeout
        self.max_frame_bytes = max_frame_bytes
        self.stats = stats
        self.clock: Optional[ClockSample] = None
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None     # guarded-by: _lock
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        try:
            sock.connect(path)
        except socket.timeout:
            sock.close()
            raise PeerTimeoutError(f"connect to {path} timed out")
        except OSError as e:
            sock.close()
            raise PeerGoneError(f"connect to {path} failed: {e}")
        self._sock = sock

    def call(self, method: str, rpc_timeout: Optional[float] = None,
             trace_ctx: Optional[dict] = None,
             on_timing: Optional[Callable[[dict], None]] = None,
             **args: Any) -> Any:
        """Invoke ``method`` on the peer; returns its result object.
        ``rpc_timeout`` overrides the client deadline for this one call
        (named to never collide with application kwargs like ``timeout``).
        ``trace_ctx`` rides the frame as its ``trace`` object (the server
        injects it into handler args as ``_trace``); ``on_timing``
        receives this call's client-side timing dict
        (t0/send_s/wait_s/dur_s/bytes) after the reply, outside the lock
        — the hook that turns one call into an ``rpc:<method>`` child
        span on the caller's request tree.

        Application errors re-raise typed (see ``raise_typed``);
        transport failures raise ``TransportError`` subclasses and close
        the connection (it is not reusable after either a timeout or a
        framing fault).
        """
        poisoned = None
        t0_wall = time.time()
        t0 = time.monotonic()
        # ``ts`` opts the reply into the server's clock stamp — raw-frame
        # peers that omit it see the stamp-free wire format.
        frame: Dict[str, Any] = {"method": method, "args": args,
                                 "ts": t0_wall}
        if trace_ctx is not None:
            frame["trace"] = trace_ctx
        n_sent = n_recv = 0
        try:
            with self._lock:
                sock = self._sock
                if sock is None:
                    raise PeerGoneError("client closed")
                sock.settimeout(self.timeout if rpc_timeout is None
                                else rpc_timeout)
                try:
                    n_sent = send_frame(sock, frame, self.max_frame_bytes)
                    t_sent = time.monotonic()
                    resp, n_recv = recv_frame_sized(sock,
                                                    self.max_frame_bytes)
                except TransportError:
                    self._sock = None        # detach under the lock ...
                    poisoned = sock
                    if self.stats is not None:
                        self.stats.record(method, time.monotonic() - t0,
                                          sent=n_sent, received=n_recv,
                                          error=True)
                    raise
        finally:
            if poisoned is not None:         # ... close outside it
                try:
                    poisoned.close()
                except OSError:
                    pass
        t1 = time.monotonic()
        t1_wall = time.time()
        if self.stats is not None:
            self.stats.record(method, t1 - t0, sent=n_sent,
                              received=n_recv, error="err" in resp)
        srv = resp.get("srv")
        if isinstance(srv, dict) and isinstance(srv.get("wall"),
                                                (int, float)):
            # NTP midpoint: the server stamped its reply somewhere inside
            # [t0_wall, t1_wall]; assuming the midpoint bounds the error
            # by rtt/2. Keep the tightest sample — short round trips are
            # the most honest clocks.
            rtt = t1 - t0
            sample = ClockSample(
                offset_s=srv["wall"] - (t0_wall + t1_wall) / 2.0,
                uncertainty_s=rtt / 2.0, rtt_s=rtt, wall=t1_wall,
                n_samples=1 if self.clock is None
                else self.clock.n_samples + 1)
            if (self.clock is None
                    or sample.uncertainty_s <= self.clock.uncertainty_s):
                self.clock = sample
            else:
                self.clock.n_samples = sample.n_samples
        if on_timing is not None:
            on_timing({"method": method, "t0": t0_wall,
                       "send_s": t_sent - t0, "wait_s": t1 - t_sent,
                       "dur_s": t1 - t0, "bytes_sent": n_sent,
                       "bytes_received": n_recv})
        if "err" in resp:
            raise_typed(resp["err"])
        return resp.get("result")

    def close(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


#: sentinel result: the handler took ownership of the socket (event
#: subscription); the server acks and stops reading that connection
DETACH = object()

Handler = Callable[[str, dict, socket.socket], Any]


class RpcServer:
    """Threaded unix-socket RPC server.

    ``handler(method, args, sock) -> result`` runs on the connection's
    thread. A handler may return ``(DETACH, result)`` to take ownership
    of the socket after the ack (the worker's event-push channel).
    Handler exceptions become typed error responses — the server loop
    NEVER dies on a bad request; framing faults (oversized/garbage)
    get a best-effort error frame and the connection is closed, because
    the stream offset is gone.

    A frame carrying a ``trace`` object has it injected into handler
    args as ``args["_trace"]`` (handlers that don't know about tracing
    must tolerate — or pop — the key); ``span_hook(method, trace,
    t0_wall, dur_s, ok)`` then fires after the handler for each traced
    frame (the worker logs these as ``rpc`` server-handle spans). Every
    reply is stamped with ``srv: {wall, mono}`` so clients can estimate
    this process's clock offset. ``stats`` aggregates per-method handle
    latency and frame bytes.
    """

    def __init__(self, path: str, handler: Handler, *,
                 max_frame_bytes: int = MAX_FRAME_BYTES,
                 stats: Optional[RpcStats] = None,
                 span_hook: Optional[
                     Callable[[str, dict, float, float, bool],
                              None]] = None):
        self.path = path
        self.handler = handler
        self.max_frame_bytes = max_frame_bytes
        self.stats = stats
        self.span_hook = span_hook
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: set = set()                       # guarded-by: _lock
        self._threads: list = []

    def start(self) -> None:
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.path)
        listener.listen(16)
        self._listener = listener
        t = threading.Thread(target=self._accept_loop,
                             name="rpc-accept", daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return                                 # listener closed
            with self._lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="rpc-conn", daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _srv_stamp() -> dict:
        """Paired server clocks stamped on replies to ``ts``-carrying
        requests (the client's offset-sample input)."""
        return {"wall": time.time(), "mono": time.monotonic()}

    def _reply(self, body: dict, stamped: bool) -> dict:
        """Attach the server clock stamp iff the request opted in via
        ``ts`` — raw-frame peers keep the unstamped wire format."""
        if stamped:
            body["srv"] = self._srv_stamp()
        return body

    def _finish(self, method: str, trace: Any, t0_wall: float,
                dur_s: float, n_recv: int, *, ok: bool) -> None:
        """Post-handler bookkeeping: per-method handle stats + the
        traced-frame span hook. Hook failures are swallowed — telemetry
        must never kill the serving loop."""
        if self.stats is not None:
            self.stats.record(method, dur_s, received=n_recv,
                              error=not ok)
        if self.span_hook is not None and isinstance(trace, dict):
            try:
                self.span_hook(method, trace, t0_wall, dur_s, ok)
            except Exception:
                logger.exception("rpc span hook failed (ignored)")

    def _serve_conn(self, conn: socket.socket) -> None:
        detached = False
        try:
            while not self._stop.is_set():
                try:
                    frame, n_recv = recv_frame_sized(conn,
                                                     self.max_frame_bytes)
                except (PeerGoneError, PeerTimeoutError):
                    return
                except (FrameTooLargeError, FrameCorruptError) as e:
                    # stream offset unrecoverable: answer typed, close
                    # (no frame, so no stamp opt-in to honour)
                    try:
                        send_frame(conn, {"err": {
                            "type": "runtime",
                            "message": f"bad frame: {e}"}})
                    except TransportError:
                        pass
                    return
                stamped = isinstance(frame.get("ts"), (int, float))
                method = frame.get("method")
                args = frame.get("args") or {}
                if not isinstance(method, str) or not isinstance(args, dict):
                    try:
                        send_frame(conn, self._reply({"err": {
                            "type": "value_error",
                            "message": "malformed request frame"}},
                            stamped))
                        continue
                    except TransportError:
                        return
                trace = frame.get("trace")
                if isinstance(trace, dict):
                    args = dict(args)
                    args["_trace"] = trace
                t0_wall = time.time()
                t0 = time.monotonic()
                try:
                    result = self.handler(method, args, conn)
                except TransportError:
                    return
                except BaseException as e:             # typed error reply
                    self._finish(method, trace, t0_wall,
                                 time.monotonic() - t0, n_recv, ok=False)
                    try:
                        send_frame(conn, self._reply(
                            {"err": error_payload(e)}, stamped))
                        continue
                    except TransportError:
                        return
                self._finish(method, trace, t0_wall,
                             time.monotonic() - t0, n_recv, ok=True)
                if isinstance(result, tuple) and len(result) == 2 \
                        and result[0] is DETACH:
                    try:
                        send_frame(conn, self._reply(
                            {"result": result[1]}, stamped),
                            self.max_frame_bytes)
                    except TransportError:
                        return
                    detached = True
                    return                             # handler owns sock
                try:
                    n_sent = send_frame(conn, self._reply(
                        {"result": result}, stamped),
                        self.max_frame_bytes)
                except TransportError:
                    return
                if self.stats is not None:
                    self.stats.add_bytes(method, sent=n_sent)
        finally:
            if not detached:
                with self._lock:
                    self._conns.discard(conn)
                try:
                    conn.close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
