"""Serving subsystem: continuous-batching decode engine.

The reference stops at one-shot batch sampling (generate.py:4-75); this
package is the runtime that turns the repo's decode primitives (static
KV cache, fused decode step) into a server: a bounded ``RequestQueue``,
an FCFS slot ``Scheduler``, the ``DecodeEngine`` tick loop, and two
dependency-free frontends (JSONL batch, stdlib HTTP).

    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine, SamplingParams)
    engine = DecodeEngine(cfg, params, tokenizer, n_slots=8)
    engine.warmup(); engine.start()
    req = engine.submit("Every effort moves you",
                        SamplingParams(max_new_tokens=64, seed=7))
    for piece in req.stream():
        print(piece, end="")
    engine.shutdown()

CLI: ``python -m building_llm_from_scratch_tpu --mode serve ...`` (or the
installed ``bllm-tpu`` entry point) — see README "Serving".
"""

from building_llm_from_scratch_tpu.serving.adapters import (
    AdapterMismatchError,
    AdapterRegistry,
    AdapterRegistryFullError,
)
from building_llm_from_scratch_tpu.serving.engine import DecodeEngine
from building_llm_from_scratch_tpu.serving.fleet import (
    ProcessFleet,
    WorkerSupervisor,
)
from building_llm_from_scratch_tpu.serving.kvcache import (
    KVCachePolicy,
    PrefixStore,
)
from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    PromptTooLongError,
    QueueFullError,
    RequestQueue,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import (
    Request,
    RequestExpiredError,
    SamplingParams,
)
from building_llm_from_scratch_tpu.serving.router import EngineRouter
from building_llm_from_scratch_tpu.serving.scheduler import Scheduler
from building_llm_from_scratch_tpu.serving.spec import (
    Drafter,
    NgramDrafter,
)
from building_llm_from_scratch_tpu.serving.supervisor import (
    EngineSupervisor,
    FaultHooks,
)
from building_llm_from_scratch_tpu.serving.transport import (
    FrameCorruptError,
    FrameTooLargeError,
    PeerGoneError,
    PeerTimeoutError,
    TransportError,
)
from building_llm_from_scratch_tpu.serving.worker import (
    EngineSpec,
    FakeEngine,
)

__all__ = [
    "AdapterMismatchError",
    "AdapterRegistry",
    "AdapterRegistryFullError",
    "DecodeEngine",
    "Drafter",
    "EngineDrainingError",
    "EngineRouter",
    "EngineSpec",
    "EngineSupervisor",
    "FakeEngine",
    "FaultHooks",
    "FrameCorruptError",
    "FrameTooLargeError",
    "KVCachePolicy",
    "NgramDrafter",
    "PeerGoneError",
    "PeerTimeoutError",
    "PrefixStore",
    "ProcessFleet",
    "PromptTooLongError",
    "QueueFullError",
    "Request",
    "RequestExpiredError",
    "RequestQueue",
    "SLOShedError",
    "SamplingParams",
    "Scheduler",
    "TransportError",
    "WorkerSupervisor",
]
