"""Request lifecycle for the serving engine.

A ``Request`` is one generation job: prompt tokens + ``SamplingParams`` in,
a stream of generated tokens out. The object doubles as the caller's
handle — ``result()`` blocks until completion, ``stream()`` yields
detokenized text pieces as the engine produces them — and carries the
timestamps the serving telemetry is built from (queue wait, TTFT, TPOT).
"""

from __future__ import annotations

import dataclasses
import queue as _stdqueue
import threading
import time
from typing import Any, Callable, Iterator, List, Optional

#: request states
QUEUED = "queued"
RUNNING = "running"      # admitted to a slot (prefill or decode)
FINISHED = "finished"
REJECTED = "rejected"

#: finish reasons
FINISH_EOS = "eos"       # sampled the request's eos (token dropped)
FINISH_LENGTH = "length"  # hit max_new_tokens
FINISH_ERROR = "error"   # engine failure (req.error holds the message)
FINISH_EXPIRED = "expired"      # deadline passed while queued (shed)
FINISH_PREEMPTED = "preempted"  # drain timeout hit before it finished
FINISH_CANCELLED = "cancelled"  # client gave up (timeout/disconnect)
FINISH_SHED = "shed"            # SLO-rejected at submit (predicted miss)
FINISH_REJECTED = "rejected"    # bounded queue at capacity at submit


class RequestExpiredError(RuntimeError):
    """The request's deadline passed before it reached a slot — the engine
    shed it at an admission boundary instead of burning decode time on a
    result nobody is waiting for (``result()`` raises this; the HTTP
    frontend maps it to 504)."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decode controls (all co-batchable in one compiled
    program — serving/engine.py samples with per-slot dynamic values).

    ``seed`` pins the request's PRNG: token i is drawn with
    ``generate.token_rng(PRNGKey(seed), i)`` regardless of slot placement
    or co-batched traffic, so identical (prompt, seed, params) requests
    reproduce — and match one-shot ``generate(rng=PRNGKey(seed))``.

    ``eos_id=None`` means the engine's model default; ``ignore_eos=True``
    disables eos stopping entirely (decode runs to the token budget).

    ``deadline_s`` is the client's patience in seconds from submission:
    past it the request is useless to whoever sent it, so the engine sheds
    it from the queue instead of decoding into the void (and rejects at
    submit time when the queue is already predicted to blow the deadline).
    ``None`` = no deadline (the engine may apply its default).

    ``adapter`` names a LoRA adapter in the engine's ``AdapterRegistry``
    (serving/adapters.py): the request decodes through base weights + that
    adapter's delta, co-batched with any other adapters' traffic in the
    same compiled program. ``None`` = the base model. Unknown names are
    rejected at submit (HTTP 400).

    ``spec`` opts this request out of speculative decoding
    (``--serve_spec_k`` engines) when False: its rows commit exactly one
    token per tick. Tokens are bit-identical either way (the accept rule
    is exact) — the opt-out exists for workloads whose acceptance rate is
    too low to be worth the drafting, e.g. high-entropy sampling. No-op
    on spec-off engines.
    """

    max_new_tokens: int = 128
    temperature: float = 0.0
    top_k: Optional[int] = None
    seed: int = 0
    eos_id: Optional[int] = None
    ignore_eos: bool = False
    deadline_s: Optional[float] = None
    adapter: Optional[str] = None
    spec: bool = True


class Request:
    """One generation request + its result handle."""

    def __init__(self, req_id: int, prompt_ids, params: SamplingParams,
                 on_token: Optional[Callable[["Request", int, str], None]]
                 = None):
        self.id = req_id
        self.prompt_ids = prompt_ids            # np.int32 (Tp,)
        self.params = params
        self.on_token = on_token
        self.state = QUEUED
        self.finish_reason: Optional[str] = None
        self.output_ids: List[int] = []
        self.text = ""
        self._detok_start = 0    # first output_ids index not yet in text
        self.slot: Optional[int] = None
        self.error: Optional[str] = None
        self._cancelled = False  # client gave up; retired at next boundary
        # router dispatch record (serving/router.py): {"replica": i,
        # "affinity": "adapter"|"prefix"|None, "route_s": seconds} — set
        # by the engine at submit (the decision precedes the Request's
        # existence), updated on a drain re-dispatch. None outside a
        # router: single-engine requests are unchanged.
        self.route: Optional[dict] = None
        # speculative-decoding ledger (spec engines only): drafted = k per
        # decode tick; accepted = the in-graph accepted-draft count
        self.spec_drafted = 0
        self.spec_accepted = 0
        # memory-ledger fields (obs/memory.py): peak slot-KV bytes this
        # request occupied (set at retirement, before the slot is freed)
        # and the KV bytes prefix-cache hits spared it from recomputing
        self.kv_bytes_peak = 0
        self.prefix_bytes_saved = 0
        # long-context tier: prompt longer than one device's prefill pane
        # (set at submit by a --serve_sp engine; the long-vs-short TTFT
        # split in summarize_metrics keys on it)
        self.long_prompt = False
        # timestamps (time.monotonic): submit -> admit (queue wait) ->
        # first token (TTFT) -> finish (TPOT over the decode tail).
        # wall_submit anchors the monotonic timeline to unix time so the
        # request's trace spans land on the same clock as every other
        # JSONL row (obs/trace.py joins them into one timeline)
        self.t_submit = time.monotonic()
        self.wall_submit = time.time()
        self.t_deadline: Optional[float] = (
            self.t_submit + params.deadline_s
            if params.deadline_s is not None else None)
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_finish: Optional[float] = None
        self._done = threading.Event()
        self._stream: "_stdqueue.Queue[Optional[str]]" = _stdqueue.Queue()

    # -- caller-side handle ----------------------------------------------

    def result(self, timeout: Optional[float] = None) -> "Request":
        """Block until the request finishes; returns self. Raises
        ``RequestExpiredError`` when the deadline shed it in the queue,
        ``RuntimeError`` for any other engine-side failure (fault
        isolation, restart, preemption, cancellation)."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not finished "
                               f"within {timeout}s")
        if self.finish_reason == FINISH_EXPIRED:
            raise RequestExpiredError(
                f"request {self.id} expired: {self.error}")
        if self.error is not None:
            raise RuntimeError(
                f"request {self.id} failed: {self.error}")
        return self

    def expired(self, now: Optional[float] = None) -> bool:
        """True when the request's deadline has passed (False without
        one). The engine checks this at admission boundaries."""
        if self.t_deadline is None:
            return False
        return (time.monotonic() if now is None else now) > self.t_deadline

    def stream(self, timeout: Optional[float] = None) -> Iterator[str]:
        """Yield detokenized text pieces as they are generated (ends when
        the request finishes). Raises ``TimeoutError`` — same as
        ``result()`` — when no piece arrives within ``timeout``."""
        while True:
            try:
                piece = self._stream.get(timeout=timeout)
            except _stdqueue.Empty:
                raise TimeoutError(
                    f"request {self.id}: no stream piece within "
                    f"{timeout}s") from None
            if piece is None:
                return
            yield piece

    @property
    def done(self) -> bool:
        return self._done.is_set()

    # -- engine-side metrics ---------------------------------------------

    def queue_wait_s(self) -> Optional[float]:
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first (None with < 2)."""
        if (self.t_first_token is None or self.t_finish is None
                or len(self.output_ids) < 2):
            return None
        return ((self.t_finish - self.t_first_token)
                / (len(self.output_ids) - 1))

    def e2e_s(self) -> Optional[float]:
        if self.t_finish is None:
            return None
        return self.t_finish - self.t_submit

    def summary(self) -> dict:
        """The ``request_done`` telemetry payload."""
        out: dict = {
            "request_id": self.id,
            "n_prompt_tokens": int(len(self.prompt_ids)),
            "n_tokens": len(self.output_ids),
            "finish_reason": self.finish_reason,
            "slot": self.slot,
        }
        if self.params.deadline_s is not None:
            out["deadline_s"] = self.params.deadline_s
        if self.params.adapter is not None:
            out["adapter"] = self.params.adapter
        if self.route is not None:
            out["replica"] = self.route.get("replica")
        if self.spec_drafted:
            # acceptance telemetry (ISSUE 14): how much of this request's
            # decode the drafter paid for
            out["spec_drafted"] = self.spec_drafted
            out["spec_accepted"] = self.spec_accepted
        if self.kv_bytes_peak:
            out["kv_bytes_peak"] = self.kv_bytes_peak
        if self.prefix_bytes_saved:
            out["prefix_bytes_saved"] = self.prefix_bytes_saved
        if self.long_prompt:
            out["long_prompt"] = True
        for name, fn in (("queue_wait_s", self.queue_wait_s),
                         ("ttft_s", self.ttft_s), ("tpot_s", self.tpot_s),
                         ("e2e_s", self.e2e_s)):
            v = fn()
            if v is not None:
                out[name] = round(v, 6)
        return out

    # -- tracing ----------------------------------------------------------

    def _wall(self, t_mono: Optional[float]) -> Optional[float]:
        """Monotonic timestamp -> unix wall time via the submit anchor."""
        if t_mono is None:
            return None
        return self.wall_submit + (t_mono - self.t_submit)

    def outcome(self) -> str:
        """Terminal label for the span row: the finish reason, or the
        state for requests that never got one (rejected at submit)."""
        return self.finish_reason or self.state

    def trace_row(self) -> dict:
        """The request's ``span`` row (obs/metrics.log_span kwargs): one
        root ``request`` span [submit, terminal] with ``queued`` /
        ``prefill`` / ``decode`` children for every phase the request
        actually reached. Emitted ONCE, at the terminal transition — so
        a trace join on ``request_id`` sees exactly one closed tree per
        request, whatever its outcome."""
        t_end = self.t_finish if self.t_finish is not None else (
            time.monotonic())
        children = []
        if self.route is not None:
            # the router hop: the dispatch decision's wall time, pinned
            # at the root's start (the decision strictly precedes the
            # Request, so its duration is data on the route record)
            children.append({"name": "router", "t0": self.wall_submit,
                             "dur_s": max(float(
                                 self.route.get("route_s") or 0.0), 0.0)})
        children.append({"name": "queued", "t0": self.wall_submit,
                         "dur_s": (self.t_admit if self.t_admit is not None
                                   else t_end) - self.t_submit})
        if self.t_admit is not None:
            t_ft = (self.t_first_token if self.t_first_token is not None
                    else min(t_end, self.t_admit))
            children.append({"name": "prefill",
                             "t0": self._wall(self.t_admit),
                             "dur_s": max(t_ft - self.t_admit, 0.0)})
            if self.t_first_token is not None:
                children.append({"name": "decode",
                                 "t0": self._wall(self.t_first_token),
                                 "dur_s": max(t_end - self.t_first_token,
                                              0.0)})
        row = {
            "name": "request", "cat": "request",
            "t0": self.wall_submit,
            "dur_s": max(t_end - self.t_submit, 0.0),
            "children": children,
            "request_id": self.id,
            "outcome": self.outcome(),
            "n_prompt_tokens": int(len(self.prompt_ids)),
            "n_tokens": len(self.output_ids),
        }
        if self.slot is not None:
            row["slot"] = self.slot
        if self.params.adapter is not None:
            row["adapter"] = self.params.adapter
        if self.route is not None:
            row["replica"] = self.route.get("replica")
            if self.route.get("affinity"):
                row["affinity"] = self.route["affinity"]
        if self.error is not None:
            row["error"] = self.error
        return row

    # -- engine internals -------------------------------------------------

    def _push_piece(self, piece: str) -> None:
        self._stream.put(piece)

    def _mark_done(self) -> None:
        self._stream.put(None)
        self._done.set()


def resolve_eos(params: SamplingParams, default_eos: Optional[int]
                ) -> Optional[int]:
    """The eos id this request actually stops on (None = never)."""
    if params.ignore_eos:
        return None
    return params.eos_id if params.eos_id is not None else default_eos


_COUNTER = threading.Lock()
_next_id = [0]


def next_request_id() -> int:
    with _COUNTER:
        _next_id[0] += 1
        return _next_id[0]


def seed_request_ids(start: int) -> None:
    """Move the id counter to ``start`` (next id = start + 1). Fleet
    worker processes seed a disjoint per-(replica, incarnation) range so
    their LOCAL request ids can never collide with the supervisor's
    fleet-wide ids in merged telemetry — a trace join on ``request_id``
    must mean one request, whichever process stamped the row. Only moves
    forward: a late seed never re-issues ids already handed out."""
    with _COUNTER:
        _next_id[0] = max(_next_id[0], int(start))


__all__: List[Any] = [
    "QUEUED", "RUNNING", "FINISHED", "REJECTED",
    "FINISH_EOS", "FINISH_LENGTH", "FINISH_ERROR",
    "FINISH_EXPIRED", "FINISH_PREEMPTED", "FINISH_CANCELLED",
    "FINISH_SHED", "FINISH_REJECTED",
    "RequestExpiredError",
    "SamplingParams", "Request", "resolve_eos", "next_request_id",
    "seed_request_ids",
]
