"""Continuous-batching decode engine (Orca-style, slot-scheduled).

One fixed ``(n_slots, Tmax)`` KV cache; requests are admitted into free
slots at step boundaries and retired the moment they finish, so XLA
compiles exactly ONE decode program (and one prefill per prompt-length
bucket) no matter how traffic arrives. The host loop per tick:

    retire finished -> admit queued into free slots (prefill, bucketed)
    -> one fused decode step for ALL slots (per-slot masks) -> stream

Slot independence is total: every row carries its own length, sampling
params and PRNG stream (``generate.token_rng`` fold-in on the request
seed), so a request's tokens are identical whether it runs alone, in any
slot, or next to arbitrary co-batched traffic — and identical to the
one-shot ``generate()`` path (test-pinned).

Telemetry (obs/metrics.py sink): per-request ``request_done`` events with
queue-wait/TTFT/TPOT, slot-occupancy + queue-depth gauges, periodic
``metrics`` rows with the decode token rate, and compile/recompile events
from the ``CompileWatcher``-wrapped prefill/decode programs — after
warmup, a prompt outside the warmed bucket set surfaces as a ``recompile``
event with the leaf diff instead of a silent latency cliff.

Resilience (this round — the serving counterpart of PR 1's training
fault tolerance):

  - DEADLINE-AWARE ADMISSION: requests carry ``deadline_s``; the queue
    sheds expired requests at admission boundaries (``request_expired``)
    and ``submit()`` rejects up front when queue position x the live
    TPOT-EWMA service estimate already blows the deadline
    (``request_shed`` / ``SLOShedError`` -> HTTP 429 + Retry-After).
  - FAULT ISOLATION: a poison request (raising callback, prefill fault,
    NaN-poisoned KV) fails ALONE with a ``request_failed{reason}`` event
    and frees its slot; co-resident requests' tokens are bit-identical
    to a fault-free run. An in-graph finite-logit guard retires a slot
    streaming non-finite logits instead of feeding garbage to a client.
  - SUPERVISED RESTART: a hung tick (``serving/supervisor.py`` watchdog
    on ``obs/stall.py``) dumps a flight record, fails in-flight requests,
    and restarts the decode loop with bounded backoff (``engine_restart``)
    — the compiled programs and their CompileWatchers survive, so the
    restarted engine serves with ZERO recompiles; queued requests are
    kept.
  - GRACEFUL DRAIN: ``drain()`` closes admission (``EngineDrainingError``
    -> HTTP 503 + Retry-After), finishes in-flight + queued work within
    a timeout, and fails the remainder with reason ``preempted``
    (``drain`` events bracket it).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Union

import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import (
    _bucket,
    sample_tokens_dynamic,
    token_rng,
)
from building_llm_from_scratch_tpu.models.transformer import (
    decode_slots,
    init_slot_cache,
    paged_decode_slots,
    paged_prefill_chunk_into_slot,
    paged_verify_slots,
    prefill_chunk_into_slot,
    prefill_into_slot,
    unstack_blocks,
    verify_slots,
)
from building_llm_from_scratch_tpu.obs.compile import CompileWatcher
from building_llm_from_scratch_tpu.obs.memory import (
    MemoryLedger,
    pytree_nbytes,
)
from building_llm_from_scratch_tpu.obs.metrics import (
    Histogram,
    RollingRatio,
    get_metrics,
    render_prometheus,
)
from building_llm_from_scratch_tpu.obs.schema import TICK_PHASES
from building_llm_from_scratch_tpu.serving.adapters import BASE_ADAPTER
from building_llm_from_scratch_tpu.serving.kvcache import (
    KVCachePolicy,
    PagePool,
    PrefixStore,
    cache_nbytes,
    copy_prefix_into_slot,
    extract_prefix_panes,
)
from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    PromptTooLongError,
    QueueFullError,
    RequestQueue,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import (
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_EXPIRED,
    FINISH_LENGTH,
    FINISH_PREEMPTED,
    FINISH_REJECTED,
    FINISH_SHED,
    FINISHED,
    QUEUED,
    REJECTED,
    RUNNING,
    Request,
    SamplingParams,
    next_request_id,
    resolve_eos,
)
from building_llm_from_scratch_tpu.serving.scheduler import Scheduler
from building_llm_from_scratch_tpu.serving.supervisor import (
    EngineSupervisor,
    FaultHooks,
)
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


class DecodeEngine:
    """The serving runtime: slot-batched KV cache + request lifecycle.

    Drive it either manually (``step()`` / ``run_until_idle()`` — what the
    deterministic tests do) or with the background thread
    (``start()`` / ``shutdown()`` — what the frontends do). ``submit()``
    is thread-safe either way.
    """

    def __init__(self, cfg: ModelConfig, params, tokenizer=None, *,
                 n_slots: int = 4, max_len: Optional[int] = None,
                 max_queue: int = 64, max_top_k: int = 64,
                 default_max_new_tokens: int = 128,
                 warmup_prompt_cap: int = 256, metrics_every: int = 32,
                 watch_compiles: bool = True,
                 default_deadline_s: Optional[float] = None,
                 tick_timeout_s: float = 0.0, max_restarts: int = 3,
                 restart_backoff_s: float = 0.5,
                 hooks: Optional[FaultHooks] = None,
                 adapters=None,
                 kv_policy: Optional[KVCachePolicy] = None,
                 spec_k: int = 0, drafter=None,
                 mesh_plan=None, replica: Optional[int] = None,
                 max_prompt: Optional[int] = None):
        import jax

        self.cfg = cfg
        #: parallel/sharding.MeshPlan (or None = the historical
        #: single-device engine, byte-for-byte). tp>1 runs the whole
        #: prefill/decode/verify program family with NamedSharding'd
        #: weights and heads-sharded slot KV over the ``model`` mesh
        #: axis; tp=1 plans pin a replica to its own device (the
        #: router's replica-per-device layout).
        self.mesh_plan = mesh_plan
        #: fleet position (serving/router.py): labels this engine's
        #: telemetry events/metrics with ``replica=<i>``. None outside a
        #: router — single-engine telemetry is unchanged.
        self.replica = replica
        if mesh_plan is not None:
            # copy=False: the engine never donates params, so aliasing
            # the caller's buffers is safe (and skips a full weight copy
            # when build_components already placed them on this plan)
            params = mesh_plan.shard_params(params, copy=False)
        self.params = params
        self.tokenizer = tokenizer
        #: serving/kvcache.KVCachePolicy — KV layout/dtype + prefix
        #: policy. STATIC per engine: it decides which prefill tier
        #: compiles (monolithic-bucketed vs ONE chunk program) and the
        #: cache pytree's dtypes; hits/misses/spans are per-call data.
        self.kv_policy = kv_policy or KVCachePolicy()
        #: serving/adapters.AdapterRegistry (or None = base model only).
        #: The stacked pool + per-slot adapter ids become per-call data
        #: arguments of the compiled programs — multi-tenant traffic
        #: keeps the ONE-decode-program invariant.
        self.adapters = adapters
        self.n_slots = int(n_slots)
        self.max_len = min(int(max_len or cfg.context_length),
                           cfg.context_length)
        self.max_top_k = min(int(max_top_k), cfg.vocab_size)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.warmup_prompt_cap = min(int(warmup_prompt_cap), self.max_len)
        self.metrics_every = int(metrics_every)
        self.default_deadline_s = default_deadline_s
        self.hooks = hooks or FaultHooks()
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        self.supervisor = (EngineSupervisor(self, tick_timeout_s,
                                            max_restarts=max_restarts,
                                            backoff_s=restart_backoff_s)
                           if tick_timeout_s > 0 else None)

        #: speculative decoding (serving/spec.py): k drafted tokens per
        #: slot per tick, verified by ONE Tq=k+1 compiled program. 0 =
        #: off (the engine is then byte-for-byte the historical one —
        #: same programs, same signatures, same cache shapes).
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        if self.spec_k >= self.max_len:
            raise ValueError(
                f"spec_k={self.spec_k} must be < the slot capacity "
                f"{self.max_len}")
        self.drafter = None
        if self.spec_k > 0:
            from building_llm_from_scratch_tpu.serving.spec import (
                NgramDrafter,
            )

            self.drafter = drafter or NgramDrafter()
        #: cache rows carry ``spec_k`` headroom positions past ``max_len``:
        #: the verify program appends k+1 candidate entries at the row's
        #: length, and the LAST legitimate decode position is max_len-1 —
        #: without headroom the batched DUS would clamp the write start
        #: and silently overwrite committed KV near capacity
        self._cache_len = self.max_len + self.spec_k

        #: long-context tier: sequence-sharded prefill. A plan with a
        #: live ``seq`` axis runs THE one chunk-prefill program with the
        #: chunk's token axis sharded over ``seq`` (GSPMD gathered
        #: attention: queries split across devices, the slot's cached KV
        #: replicated, the chunk's new KV gathered back into the slot
        #: row) — per-device prefill compute and activation memory drop
        #: by sp while decode keeps the existing replicated programs.
        #: The sharding is STATIC (part of the compiled signature), so
        #: long/short mixed traffic never recompiles, and the math is
        #: per-query-identical to the unsharded program, so tokens stay
        #: bit-exact vs single-device ``generate()``.
        self._sp = int(mesh_plan.n_seq) if mesh_plan is not None else 1
        self._sp_sharding = None
        if self._sp > 1:
            if self.kv_policy.prefill_chunk <= 0:
                raise ValueError(
                    "sequence-sharded prefill (mesh_plan with a seq "
                    "axis > 1) needs chunked prefill "
                    "(KVCachePolicy.prefill_chunk > 0): the seq axis "
                    "shards the chunk's token dimension")
            if self.kv_policy.prefill_chunk % self._sp != 0:
                raise ValueError(
                    f"prefill_chunk {self.kv_policy.prefill_chunk} must "
                    f"be divisible by the seq-parallel degree "
                    f"{self._sp}: every device owns an equal token "
                    "slice of the chunk")
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from building_llm_from_scratch_tpu.parallel.mesh import (
                SEQ_AXIS,
            )

            self._sp_sharding = NamedSharding(mesh_plan.mesh,
                                              P(None, SEQ_AXIS))
        #: where chunk-prefill wall books: sp engines split it out as
        #: ``prefill_shard`` (identically 0 elsewhere, like ``draft``)
        self._prefill_phase = ("prefill_shard" if self._sp > 1
                               else "prefill")
        #: per-device prefill pane in prompt tokens, and the admission
        #: ceiling it implies. ``max_prompt`` (the --serve_max_prompt
        #: flag) declares what ONE device's pane may prefill; the
        #: engine-level ceiling is ``pane x sp`` — it LIFTS with the
        #: seq-parallel degree. Default pane = slot capacity / sp, so an
        #: unconfigured engine admits exactly what capacity allows.
        self.prompt_pane = (int(max_prompt) if max_prompt
                            else -(-self.max_len // self._sp))
        self.max_prompt = min(self.max_len - 1,
                              self.prompt_pane * self._sp)

        #: paged KV (``KVCachePolicy.paged``): slot rows map their
        #: logical positions onto fixed-size pages of ONE shared pool
        #: through a host-owned (n_slots, max_pages) int32 page table
        #: that rides every compiled program as traced DATA (the
        #: adapter-pool trick: identity is data, capacity is static) —
        #: page churn (hits, frees, eviction, oversubscription) never
        #: recompiles anything. Pool membership, refcounts and the
        #: admission reservation are pure host bookkeeping (PagePool);
        #: the device owns only the pool arrays.
        self._paged = self.kv_policy.paged
        self.page_pool: Optional[PagePool] = None
        self._page_table: Optional[np.ndarray] = None
        if self._paged:
            if mesh_plan is not None and mesh_plan.n_model > 1:
                raise ValueError(
                    "paged KV cannot ride a tensor-parallel mesh plan "
                    "yet: the pool leaves' (n_pages, ...) layout has no "
                    "heads-sharded placement — run paged engines "
                    "planless or seq-sharded only (replica-per-device "
                    "fleets are fine)")
            self._pages_per_slot = self.kv_policy.pages_per_slot(
                self._cache_len)
            self.page_pool = PagePool(
                self.kv_policy.total_pool_pages(self.n_slots,
                                                self._cache_len),
                self.kv_policy.page_bytes(cfg))
            self._page_table = np.zeros(
                (self.n_slots, self._pages_per_slot),
                np.int32)                               # guarded-by: _lock
            #: table columns each slot has allocated (col 0 upward) and
            #: the admission reservation still owed to it — invariant:
            #: reserved[slot] == worst-case need − cols referenced
            self._slot_cols = np.zeros(
                (self.n_slots,), np.int32)              # guarded-by: _lock
            self._pages_reserved = np.zeros(
                (self.n_slots,), np.int32)              # guarded-by: _lock
            # one page_pool_exhausted event per exhaustion episode (the
            # head request would re-refuse every tick until pages free)
            self._pool_exhausted_logged = False         # guarded-by: _lock
        #: pane-copy spy: counts contiguous prefix-hit pane COPIES (the
        #: duplicated-bytes path paged mode deletes) — a paged engine
        #: must hold this at zero (bench + CI assert it)
        self.pane_copies = 0                            # guarded-by: _lock

        self.queue = RequestQueue(max_queue)
        self.scheduler = Scheduler(self.n_slots)
        self.cache = self._place_cache(init_slot_cache(
            cfg, self.n_slots, self._cache_len,
            policy=self.kv_policy))                     # guarded-by: _lock
        # pin the cache pytree's shardings for the life of the engine:
        # every compiled program constrains its cache OUTPUT to these, so
        # the donated rebind can never drift to a GSPMD-chosen layout
        # that would change the next call's arg signature (a recompile)
        self._cache_shardings = (jax.tree_util.tree_map(
            lambda x: x.sharding, self.cache)
            if mesh_plan is not None else None)
        self._blocks = unstack_blocks(self.params, cfg)
        if self.adapters is not None and mesh_plan is not None:
            # the stacked pool rides every compiled call as data — it has
            # to live on THIS engine's mesh (replicated: every model
            # shard reads all adapter columns it needs), or jit would see
            # arguments spanning two device sets
            self.adapters.place_pool(mesh_plan.put_replicated)
        #: chunked-prefill progress per slot (slot -> host dict); a slot
        #: present here is ADMITTED but not yet decoding — the decode
        #: tick computes (and ignores) its row, and its next-write
        #: position doubles as the row's length so the decode step's
        #: garbage append lands exactly where the next chunk overwrites
        self._prefill_state: dict = {}                  # guarded-by: _lock
        #: static pane width for prefix panes (copy/extract programs):
        #: one width -> ONE copy + ONE extract program, hit spans are
        #: data against it
        self._prefix_pane_len = self._bucket_len(
            max(self.warmup_prompt_cap, 1))
        self.prefix_store: Optional[PrefixStore] = None
        if self.kv_policy.prefix_cache:
            from building_llm_from_scratch_tpu.models.lora import (
                adapter_fingerprint,
            )

            self.prefix_store = PrefixStore(
                adapter_fingerprint(cfg),
                chunk_tokens=self.kv_policy.prefill_chunk,
                budget_bytes=self.kv_policy.prefix_budget_bytes,
                pane_tokens=self._prefix_pane_len,
                page_pool=self.page_pool)

        S = self.n_slots
        # host-owned per-slot state; the device owns only the big k/v.
        # PRNG key width depends on the configured impl (threefry (2,),
        # rbg (4,)) — probe it instead of assuming
        probe_key = np.asarray(_prng_key(0))
        self._lengths = np.zeros((S,), np.int32)        # guarded-by: _lock
        self._last_tokens = np.zeros((S,), np.int32)    # guarded-by: _lock
        self._n_gen = np.zeros((S,), np.int32)          # guarded-by: _lock
        self._base_keys = np.zeros(
            (S,) + probe_key.shape, probe_key.dtype)    # guarded-by: _lock
        self._temps = np.zeros((S,), np.float32)        # guarded-by: _lock
        self._topks = np.zeros((S,), np.int32)          # guarded-by: _lock
        # per-slot adapter pool row; −1 = base model (exact zero delta)
        self._adapter_ids = np.full((S,), -1, np.int32)  # guarded-by: _lock
        # per-slot committed-token history (prompt + generated), the
        # n-gram drafter's haystack; host-only, maintained iff spec is on
        self._hist = (np.zeros((S, self.max_len), np.int32)
                      if self.spec_k else None)          # guarded-by: _lock
        self._hist_len = np.zeros((S,), np.int32)        # guarded-by: _lock
        # per-adapter request accounting ("base" for un-adapted traffic):
        # name -> {finished, failed, tokens} — feeds the labeled /metrics
        # series and serve_summary
        self._adapter_counts = {}                        # guarded-by: _lock
        if self.adapters is not None:
            # the registry's load() must not reuse a pool row an active
            # slot still decodes against (hot-evict-then-load safety)
            self.adapters.set_in_use_probe(self._adapter_rows_in_use)

        # donate the cache pytree: the caller always rebinds self.cache
        # to the outputs, so XLA may alias input->output and the pallas
        # in-place append really is in place (no per-tick full-cache
        # copy). The prefix-EXTRACT program deliberately does NOT donate
        # — it only reads the cache (the next donating call reuses the
        # same arrays).
        import functools

        prefill_jit = jax.jit(self._prefill_impl, donate_argnums=(0,))
        # paged: the chunk/step programs take the page table as one more
        # traced argument and write/read through it; the monolithic
        # prefill and the prefix copy/extract pair are never CALLED
        # (paged implies chunked prefill, and a paged hit is a host
        # table write) — they stay built so the watcher set is stable
        chunk_jit = jax.jit(self._paged_chunk_impl if self._paged
                            else self._chunk_impl, donate_argnums=(0,))
        copy_jit = jax.jit(self._copy_impl, donate_argnums=(0,))
        extract_jit = jax.jit(functools.partial(
            extract_prefix_panes, pane_len=self._prefix_pane_len))
        # spec on: the Tq=k+1 verify program IS the tick program — the
        # plain decode step is never built (every slot, spec-opted-out
        # rows included, rides verify; their commit count is clamped to 1
        # on the host). spec off: the historical decode step, untouched.
        if self._paged:
            step_impl = (self._paged_verify_impl if self.spec_k
                         else self._paged_decode_impl)
        else:
            step_impl = (self._verify_impl if self.spec_k
                         else self._decode_impl)
        step_jit = jax.jit(step_impl, donate_argnums=(0,))
        step_label = "serve_verify" if self.spec_k else "serve_decode"
        if watch_compiles:
            self._prefill = CompileWatcher(prefill_jit,
                                           label="serve_prefill",
                                           multi_program=True)
            self._prefill_chunk = CompileWatcher(
                chunk_jit, label="serve_prefill_chunk", multi_program=True)
            self._prefix_copy = CompileWatcher(
                copy_jit, label="serve_prefix_copy", multi_program=True)
            self._prefix_extract = CompileWatcher(
                extract_jit, label="serve_prefix_extract",
                multi_program=True)
            step_watched = CompileWatcher(step_jit, label=step_label,
                                          multi_program=True)
        else:
            self._prefill = prefill_jit
            self._prefill_chunk = chunk_jit
            self._prefix_copy = copy_jit
            self._prefix_extract = extract_jit
            step_watched = step_jit
        if self.spec_k:
            self._verify = step_watched
            self._decode = None
        else:
            self._decode = step_watched
            self._verify = None

        #: memory observatory (obs/memory.py): the per-token KV cost the
        #: live-attribution math scales host lengths by, and the ledger
        #: itself — built AFTER the cache/store/pool exist so every
        #: provider closes over live engine state
        self._kv_bytes_per_token = self.kv_policy.bytes_per_slot(
            self.cfg, self._cache_len)["bytes_per_token"]
        self.memory_ledger = self._build_memory_ledger()

        self._lock = threading.RLock()
        self._work = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # _dead/_draining: written by _fail_all/drain; racy READS are the
        # design (submit's fast-path check repeats its decision under a
        # real barrier), so only writes are lock-checked
        self._dead: Optional[str] = None    # guarded-by: _lock [writes]
        self._draining = False              # guarded-by: _lock [writes]
        # bumped on every supervisor restart; a stale loop thread (one
        # that eventually un-wedges after being abandoned) sees the bump
        # and exits WITHOUT committing any state (see step()). Reads are
        # deliberately lock-free generation checks — a stale read only
        # delays the abandonment by one commit point.
        self._restart_lock = threading.Lock()
        self._generation = 0        # guarded-by: _restart_lock [writes]
        self.n_restarts = 0         # guarded-by: _restart_lock [writes]
        self.warmed_up = False
        # live service-time estimate for SLO-aware admission: EWMAs of
        # per-token decode time and tokens-per-request over finished
        # requests (alpha 0.2 — a few requests of history dominate)
        self._tpot_ewma: Optional[float] = None     # guarded-by: _lock
        self._tokens_ewma: Optional[float] = None   # guarded-by: _lock

        # rolling serve accounting: fixed-bucket histograms (obs/metrics
        # Histogram — Prometheus semantics, O(buckets) memory forever;
        # replaces the 8192-deque reservoirs whose percentiles silently
        # covered only the most recent window of a long-running server)
        # plus a rolling deadline-miss ratio for SLO burn-rate alerting
        self.n_ticks = 0                    # guarded-by: _lock
        self.tokens_generated = 0           # guarded-by: _lock
        self.requests_finished = 0          # guarded-by: _lock
        self.requests_rejected = 0          # guarded-by: _lock
        self.requests_failed = 0            # guarded-by: _lock
        self.requests_shed = 0              # guarded-by: _lock
        self.requests_expired = 0           # guarded-by: _lock
        self.ttft_hist = Histogram()
        self.tpot_hist = Histogram()
        self.queue_wait_hist = Histogram()
        self.e2e_hist = Histogram()
        #: per-tick prefill+prefix-copy wall (ticks that did prefill
        #: work): the chunked-prefill scoreboard — its p95 is the
        #: head-of-line bound chunking exists to shrink. Finer buckets
        #: than the request-latency default: chunked-vs-monolithic A/Bs
        #: differ by small factors the 2.5x latency ladder can't resolve
        self.tick_prefill_hist = Histogram(bounds=(
            0.0002, 0.0005, 0.001, 0.002, 0.004, 0.008, 0.015, 0.03,
            0.06, 0.12, 0.2, 0.3, 0.45, 0.7, 1.0, 1.5, 2.2, 3.3, 5.0,
            7.5, 11.0, 17.0, 26.0, 40.0, 60.0))
        self.slo_window = RollingRatio(window_s=300.0)
        self._t_start_mono = time.monotonic()
        self._window_tokens = 0             # guarded-by: _lock
        self._window_t0 = time.monotonic()  # guarded-by: _lock
        # per-tick phase breakdown (obs/trace.TICK_PHASES): wall-clock
        # accumulated with perf_counter ONLY — the instrumentation adds
        # zero device fetches (guard-tested). `_tick_acc` is the current
        # metrics window (reset at cadence, logged into the metrics row);
        # `tick_phase_totals` is cumulative for the /metrics counters.
        self._tick_acc = {ph: 0.0
                          for ph in TICK_PHASES}         # guarded-by: _lock
        self._tick_acc_total = 0.0                       # guarded-by: _lock
        self.tick_phase_totals = {ph: 0.0
                                  for ph in TICK_PHASES}  # guarded-by: _lock
        self.tick_seconds_total = 0.0                    # guarded-by: _lock
        self._window_ticks = 0                           # guarded-by: _lock
        self._win_t0_wall = time.time()                  # guarded-by: _lock
        # KV-engine window counters (chunked prefill + prefix cache):
        # drained into the cadence metrics row like the tick phases
        self._window_prefill_chunks = 0                  # guarded-by: _lock
        self._window_prefix_hits = 0                     # guarded-by: _lock
        self._window_prefix_misses = 0                   # guarded-by: _lock
        self._tick_pf0 = 0.0                             # guarded-by: _lock
        # speculative-decoding accounting: drafted = k per spec-enabled
        # decoding row per tick; accepted = the in-graph n_acc (draft
        # tokens the verify committed). Cumulative totals feed /metrics
        # and the acceptance-ratio gauge; window counters drain into the
        # cadence metrics row
        self.spec_tokens_drafted = 0                     # guarded-by: _lock
        self.spec_tokens_accepted = 0                    # guarded-by: _lock
        self._window_spec_drafted = 0                    # guarded-by: _lock
        self._window_spec_accepted = 0                   # guarded-by: _lock

    # -- mesh placement (tp-sharded engine) --------------------------------

    def _place_cache(self, cache):
        """Place a fresh slot cache on the engine's mesh (identity for
        planless engines — the historical allocation untouched)."""
        if self.mesh_plan is None:
            return cache
        return self.mesh_plan.shard_cache(cache)

    def _pin_cache(self, cache):
        """In-graph sharding constraint pinning a program's cache OUTPUT
        to the engine's fixed cache layout (no-op when planless). Keeps
        the donate->rebind->call cycle signature-stable under GSPMD."""
        if self._cache_shardings is None:
            return cache
        import jax

        return jax.tree_util.tree_map(jax.lax.with_sharding_constraint,
                                      cache, self._cache_shardings)

    # -- telemetry ---------------------------------------------------------

    def _ev(self, kind: str, **fields) -> None:
        """Engine-scoped event: labels with this engine's fleet position
        (``replica=<i>``) when it has one, so a router's merged JSONL
        stays attributable per replica. Single engines emit the exact
        historical rows (no replica field at all)."""
        if self.replica is not None:
            fields["replica"] = self.replica
        get_metrics().event(kind, **fields)

    # -- memory observatory (obs/memory.py) --------------------------------

    def _build_memory_ledger(self) -> MemoryLedger:
        """Register every device-memory consumer this engine owns as a
        ledger component, measured from the LIVE arrays (providers close
        over ``self`` — a donated-cache rebind or a restart's fresh
        allocation is picked up on the next snapshot automatically).
        Expectations are the byte-exact analytic sizes, so any
        measured-vs-expected gap is a ``memory_drift``."""
        ledger = MemoryLedger(emit=self._ev, source="engine")
        ledger.register("model_params",
                        lambda: pytree_nbytes(self.params))
        bps = self.kv_policy.bytes_per_slot(self.cfg, self.max_len)
        n = self.n_slots
        if self._paged:
            # the pool IS the KV allocation: one component, byte-exact
            # by construction (every leaf is n_pages x one page's slice,
            # so measured == total_pool_pages x page_bytes, always —
            # any gap means the pool arrays were rebuilt wrong).
            # Providers read self.page_pool dynamically: a restart swaps
            # in a fresh pool and the next snapshot follows it.
            ledger.register(
                "page_pool",
                lambda: cache_nbytes(self.cache),  # graft-ok: GL031 nbytes metadata, runs at ledger cadence under the engine lock
                expected=lambda: (self.page_pool.n_pages
                                  * self.page_pool.page_bytes))
        else:
            ledger.register("slot_kv",
                            lambda: self._cache_component_bytes()[0],
                            expected=lambda: bps["kv_bytes"] * n)
            if bps["scale_bytes"]:
                ledger.register("kv_scales",
                                lambda: self._cache_component_bytes()[1],
                                expected=lambda: bps["scale_bytes"] * n)
            if self.spec_k:
                bps_full = self.kv_policy.bytes_per_slot(self.cfg,
                                                         self._cache_len)
                ledger.register(
                    "spec_headroom",
                    lambda: self._cache_component_bytes()[2],
                    expected=lambda: (bps_full["total_bytes"]
                                      - bps["total_bytes"]) * n)
        if self.prefix_store is not None:
            store = self.prefix_store
            # paged: stored entries hold REFERENCES to pool pages — the
            # bytes already live inside the page_pool component, so the
            # store series is attribution only (device=False keeps it
            # out of the pressure/headroom device sum: no double count)
            ledger.register("prefix_store", lambda: store.bytes_total,
                            device=not self._paged)
            ledger.register_labeled("prefix_store_bytes", "namespace",
                                    store.bytes_by_tag)
            ledger.register_probe("prefix_store",
                                  self._prefix_pinned_probe)
        if self.adapters is not None:
            ledger.register("adapter_pool", self.adapters.pool_nbytes)
            ledger.register_labeled("adapter_pool_bytes", "tenant",
                                    self.adapters.bytes_by_adapter)
        ledger.register("compile_temps", self._compile_temp_bytes)
        ledger.register_labeled("kv_live_bytes", "tenant",
                                self._kv_live_by_tenant)
        ledger.track_host_rss()
        return ledger

    # called under _lock from the cadence observe and the scrape's timed
    # acquire; a failed timed acquire reads stale-but-safe metadata,
    # like the rest of metrics_snapshot
    # graft: hot-path
    def _cache_component_bytes(self) -> tuple:  # holds: _lock
        """(slot_kv, kv_scales, spec_headroom) bytes of the live slot
        cache, measured from the actual arrays' ``nbytes`` (metadata —
        never a sync). The spec headroom tail (``spec_k`` positions past
        ``max_len``) is carved out along the time axis; the three parts
        sum to ``cache_nbytes(self.cache)`` byte-exactly because every
        array's byte count is divisible by its time extent."""
        kv_nb = sum(a.nbytes for key in ("k", "v")
                    for a in self.cache.get(key, ()))
        scale_nb = sum(a.nbytes for key in ("k_scale", "v_scale")
                       for a in self.cache.get(key, ()))
        slot_kv = kv_nb * self.max_len // self._cache_len
        kv_scales = scale_nb * self.max_len // self._cache_len
        return slot_kv, kv_scales, kv_nb + scale_nb - slot_kv - kv_scales

    def _compile_temp_bytes(self) -> int:
        """Peak compile-time scratch across the engine's programs (HLO
        memory analysis via CompileWatcher): programs execute one at a
        time, so the RESIDENT scratch is the max, not the sum."""
        peak = 0
        for w in self._watchers():
            mem = getattr(w, "memory", None) or {}
            peak = max(peak, mem.get("temp_bytes", 0))
        return peak

    # graft: hot-path
    def _kv_live_by_tenant(self) -> dict:  # holds: _lock
        """Live KV attribution: each occupied slot's committed length x
        bytes/token, rolled up by tenant (adapter name; "base" for
        un-adapted traffic). Host numpy state only."""
        out: dict = {}
        for slot, req in self.scheduler.active():
            nm = req.params.adapter or BASE_ADAPTER
            if self._paged:
                # page-exact: mapped columns x page bytes. A shared page
                # is charged to EVERY sharer (attribution answers "who
                # depends on this memory", not "who allocated it"), so
                # the tenant sum can exceed pool-used — by design
                cols = int(self._slot_cols[slot])  # graft-ok: GL011 host numpy
                out[nm] = (out.get(nm, 0)
                           + cols * self.page_pool.page_bytes)
                continue
            live = int(self._lengths[slot])  # graft-ok: GL011 host numpy
            out[nm] = out.get(nm, 0) + live * self._kv_bytes_per_token
        return out

    def _prefix_pinned_probe(self) -> Optional[dict]:
        """Pins are held only across one in-flight pane copy under the
        engine lock — an entry still pinned when the cadence observes is
        leaked (its bytes can never be evicted). The ledger turns a
        non-None return into ``memory_drift(component="prefix_store")``."""
        pinned, keys = self.prefix_store.pinned_bytes()
        if not pinned:
            return None
        return {"reason": "pinned_orphan", "pinned_bytes": pinned,
                "pinned_entries": keys[:8], "measured_bytes": pinned}

    # -- jitted programs (close over params/cfg/blocks so per-tick call
    # signatures carry only the small mutable state + caches) -------------

    def _prefill_impl(self, cache, tokens, prompt_len, slot,
                      base_key, temp, topk, pool=None, pool_scale=None,
                      adapter_id=None):
        import jax.numpy as jnp

        adapter = None
        if pool is not None:
            adapter = {"pool": pool, "scaling": pool_scale,
                       "ids": jnp.reshape(adapter_id, (1,))}
        logits, cache = prefill_into_slot(
            self.params, self.cfg, tokens, prompt_len, slot,
            cache, self._blocks, adapter=adapter)
        key0 = token_rng(base_key, 0)
        tok = sample_tokens_dynamic(
            logits[None], key0[None], jnp.reshape(temp, (1,)),
            jnp.reshape(topk, (1,)), self.max_top_k)[0]
        # in-graph finite guard: non-finite logits mean the slot would
        # stream garbage — the host retires the request with an error
        # status instead (scalar flag; adds one all-reduce over V)
        ok = jnp.all(jnp.isfinite(logits))
        return tok, ok, self._pin_cache(cache)

    def _chunk_impl(self, cache, tokens, chunk_start, prompt_len, slot,
                    base_key, temp, topk, pool=None, pool_scale=None,
                    adapter_id=None):
        """One C-token prefill chunk (the chunked tier's ONE compiled
        prefill program). Samples the would-be first token every call —
        the host only reads it (and the finite flag) on the FINAL chunk,
        so non-final chunks cost zero device->host syncs.

        Seq-sharded engines (``--serve_sp``): the chunk's token axis is
        constrained onto the ``seq`` mesh axis and GSPMD propagates the
        split through the whole chunk forward — each device embeds,
        normalizes and attends its C/sp queries against the replicated
        slot KV (per-query math identical to unsharded, so tokens stay
        bit-exact), then the chunk's new KV is gathered back into the
        replicated slot row by the output's pinned sharding."""
        import jax
        import jax.numpy as jnp

        if self._sp_sharding is not None:
            tokens = jax.lax.with_sharding_constraint(tokens,
                                                      self._sp_sharding)
        adapter = None
        if pool is not None:
            adapter = {"pool": pool, "scaling": pool_scale,
                       "ids": jnp.reshape(adapter_id, (1,))}
        logits, cache = prefill_chunk_into_slot(
            self.params, self.cfg, tokens, chunk_start, prompt_len, slot,
            cache, self._blocks, adapter=adapter)
        key0 = token_rng(base_key, 0)
        tok = sample_tokens_dynamic(
            logits[None], key0[None], jnp.reshape(temp, (1,)),
            jnp.reshape(topk, (1,)), self.max_top_k)[0]
        ok = jnp.all(jnp.isfinite(logits))
        return tok, ok, self._pin_cache(cache)

    def _copy_impl(self, cache, panes, slot):
        """Prefix HIT: one batched DUS per layer writes the stored panes
        into row ``slot`` — the whole cached-span compute (no forward)."""
        return self._pin_cache(copy_prefix_into_slot(cache, panes, slot))

    def _decode_impl(self, cache, tokens, lengths, base_keys,
                     n_gen, temps, topks, pool=None, pool_scale=None,
                     adapter_ids=None):
        import jax
        import jax.numpy as jnp

        adapter = None
        if pool is not None:
            adapter = {"pool": pool, "scaling": pool_scale,
                       "ids": adapter_ids}
        logits, cache = decode_slots(
            self.params, self.cfg, tokens[:, None], lengths,
            cache, self._blocks, adapter=adapter)
        keys = jax.vmap(token_rng)(base_keys, n_gen)
        nxt = sample_tokens_dynamic(logits, keys, temps, topks,
                                    self.max_top_k)
        # per-row finite guard: slot independence means a numerically
        # poisoned row (bad KV state) goes non-finite ALONE — the host
        # retires just that slot (reason non_finite_logits)
        ok = jnp.all(jnp.isfinite(logits), axis=-1)
        return nxt, ok, self._pin_cache(cache)

    def _verify_impl(self, cache, tokens, lengths, base_keys,
                     n_gen, temps, topks, pool=None, pool_scale=None,
                     adapter_ids=None):
        """Speculative tick: ONE Tq=k+1 forward scores every slot's
        [last_token, d_1..d_k] and the in-graph accept rule commits the
        longest valid prefix. Position j of row s samples with the
        fold-in key for token index n_gen[s]+j — the exact key the
        non-speculative path would use for that token — so committed
        tokens are bit-identical to spec-off at any acceptance rate.
        Returns (tokens (S, k+1), n_accepted (S,), ok (S,), cache)."""
        import jax
        import jax.numpy as jnp

        from building_llm_from_scratch_tpu.generate import (
            accept_draft_tokens,
        )

        adapter = None
        if pool is not None:
            adapter = {"pool": pool, "scaling": pool_scale,
                       "ids": adapter_ids}
        logits, cache = verify_slots(
            self.params, self.cfg, tokens, lengths, cache, self._blocks,
            adapter=adapter)
        Tq = tokens.shape[1]
        offsets = n_gen[:, None] + jnp.arange(Tq)[None, :]     # (S, Tq)
        keys = jax.vmap(jax.vmap(token_rng, in_axes=(None, 0)))(
            base_keys, offsets)
        toks, n_acc, ok = accept_draft_tokens(
            logits, tokens[:, 1:], keys, temps, topks, self.max_top_k)
        return toks, n_acc, ok, self._pin_cache(cache)

    # -- paged variants: identical sampling/accept tails, but the KV
    # cache is the shared page pool and a per-slot int32 page table rides
    # each call as TRACED DATA (one (S, max_pages) signature — page churn
    # never recompiles, mirroring the adapter-pool trick) ----------------

    def _paged_chunk_impl(self, cache, tokens, chunk_start, prompt_len,
                          slot, page_table, base_key, temp, topk,
                          pool=None, pool_scale=None, adapter_id=None):
        import jax
        import jax.numpy as jnp

        if self._sp_sharding is not None:
            # seq-sharded chunk (see _chunk_impl): queries split over
            # the seq axis, the page-pool KV stays replicated, the
            # chunk's page scatters gather back via the pinned output
            tokens = jax.lax.with_sharding_constraint(tokens,
                                                      self._sp_sharding)
        adapter = None
        if pool is not None:
            adapter = {"pool": pool, "scaling": pool_scale,
                       "ids": jnp.reshape(adapter_id, (1,))}
        logits, cache = paged_prefill_chunk_into_slot(
            self.params, self.cfg, tokens, chunk_start, prompt_len, slot,
            page_table, cache, self._blocks, adapter=adapter,
            cache_len=self._cache_len)
        key0 = token_rng(base_key, 0)
        tok = sample_tokens_dynamic(
            logits[None], key0[None], jnp.reshape(temp, (1,)),
            jnp.reshape(topk, (1,)), self.max_top_k)[0]
        ok = jnp.all(jnp.isfinite(logits))
        return tok, ok, self._pin_cache(cache)

    def _paged_decode_impl(self, cache, tokens, lengths, page_table,
                           base_keys, n_gen, temps, topks, pool=None,
                           pool_scale=None, adapter_ids=None):
        import jax
        import jax.numpy as jnp

        adapter = None
        if pool is not None:
            adapter = {"pool": pool, "scaling": pool_scale,
                       "ids": adapter_ids}
        logits, cache = paged_decode_slots(
            self.params, self.cfg, tokens[:, None], lengths, page_table,
            cache, self._blocks, adapter=adapter,
            cache_len=self._cache_len)
        keys = jax.vmap(token_rng)(base_keys, n_gen)
        nxt = sample_tokens_dynamic(logits, keys, temps, topks,
                                    self.max_top_k)
        ok = jnp.all(jnp.isfinite(logits), axis=-1)
        return nxt, ok, self._pin_cache(cache)

    def _paged_verify_impl(self, cache, tokens, lengths, page_table,
                           base_keys, n_gen, temps, topks, pool=None,
                           pool_scale=None, adapter_ids=None):
        import jax
        import jax.numpy as jnp

        from building_llm_from_scratch_tpu.generate import (
            accept_draft_tokens,
        )

        adapter = None
        if pool is not None:
            adapter = {"pool": pool, "scaling": pool_scale,
                       "ids": adapter_ids}
        logits, cache = paged_verify_slots(
            self.params, self.cfg, tokens, lengths, page_table, cache,
            self._blocks, adapter=adapter, cache_len=self._cache_len)
        Tq = tokens.shape[1]
        offsets = n_gen[:, None] + jnp.arange(Tq)[None, :]     # (S, Tq)
        keys = jax.vmap(jax.vmap(token_rng, in_axes=(None, 0)))(
            base_keys, offsets)
        toks, n_acc, ok = accept_draft_tokens(
            logits, tokens[:, 1:], keys, temps, topks, self.max_top_k)
        return toks, n_acc, ok, self._pin_cache(cache)

    def _pool_args(self) -> tuple:
        """Positional tail for the compiled programs: the registry's
        CURRENT stacked pool + scaling (lock-free snapshot — hot-loads
        swap these device arrays between ticks, same shapes, zero
        recompiles). Empty when no registry is attached, keeping the
        registry-less engine's historical call signature."""
        if self.adapters is None:
            return ()
        pool, scale = self.adapters.pool_args()
        return (pool, scale)

    def _pool_args_for(self, adapter_row) -> tuple:
        """Prefill's positional tail: pool + scaling + THIS request's row."""
        base = self._pool_args()
        return base + (adapter_row,) if base else ()

    def _adapter_rows_in_use(self):
        """Registry in-use probe: pool rows active slots reference. TIMED
        lock acquire — a wedged (or just slow) tick must not hang registry
        admin. On timeout the answer must be CONSERVATIVE: an in-flight
        ``_admit`` may have resolved a row but not yet committed it to
        ``_adapter_ids``, so a lock-free read could green-light reusing a
        row a just-admitted request is about to decode against (silent
        cross-tenant weight corruption). Report every row in use instead —
        a hot-load during a wedge waits or fails loudly, never corrupts."""
        lock = self._lock
        locked = lock.acquire(timeout=1.0)
        try:
            if not locked:
                return set(range(self.adapters.capacity))
            return {int(r) for r in self._adapter_ids if r >= 0}
        finally:
            if locked:
                lock.release()

    # -- admission --------------------------------------------------------

    def _bucket_len(self, n: int) -> int:
        return min(_bucket(n), self.max_len)

    def prompt_buckets(self) -> List[int]:
        """The prompt-length buckets warmup compiles (one prefill program
        each): every bucket value up to ``warmup_prompt_cap``. Prompts
        longer than the cap still work — their first arrival pays a
        compile, which the frozen watcher reports as a ``recompile``
        (bucket miss)."""
        vals = {self._bucket_len(1)}
        b = 64
        while b <= self.warmup_prompt_cap:
            vals.add(self._bucket_len(b))
            b += 64
        # the clamped terminal bucket: when max_len is not a multiple of
        # 64 the loop above never reaches it, yet in-capacity prompts
        # bucket there (e.g. max_len=48 -> bucket 48)
        vals.add(self._bucket_len(self.warmup_prompt_cap))
        return sorted(vals)

    def encode_prompt(self, prompt: Union[str, Sequence[int], np.ndarray]
                      ) -> np.ndarray:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("text prompt needs a tokenizer")
            ids = self.tokenizer.encode(prompt)
        else:
            ids = prompt
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if int(ids.min()) < 0 or int(ids.max()) >= self.cfg.vocab_size:
            # out-of-vocab ids make the embedding gather fill NaN and the
            # slot would stream garbage until the finite guard retires it
            # — reject the poison at submit instead of burning a slot
            raise ValueError(
                f"prompt token ids must be in [0, {self.cfg.vocab_size}); "
                f"got range [{int(ids.min())}, {int(ids.max())}]")
        return ids

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, timeout: Optional[float] = None,
               on_token=None, route: Optional[dict] = None) -> Request:
        """Enqueue one request (thread-safe). ``block=False`` rejects with
        ``QueueFullError`` when the bounded queue is at capacity;
        ``block=True`` waits for space (backpressure). Raises
        ``EngineDrainingError`` once ``drain()`` has closed admission and
        ``SLOShedError`` when the request's deadline is predicted
        unmeetable from the current backlog."""
        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        if self._draining:
            # the backlog estimate reads the service EWMAs, which mutate
            # under the engine lock (GL031). TIMED acquire: drain() sets
            # _draining at entry but only replaces a wedged lock after
            # its timeout wait, so an unbounded acquire here could park
            # the client's thread forever on the abandoned lock — on
            # timeout, skip the estimate (Retry-After is best-effort)
            # rather than delay the 503
            lock = self._lock
            retry = None
            locked = lock.acquire(timeout=0.5)
            try:
                if locked:
                    retry = self.estimate_queue_clear_s()
            finally:
                if locked:
                    lock.release()
            raise EngineDrainingError(
                "engine is draining: admission closed",
                retry_after_s=retry)
        params = params or SamplingParams()
        if params.deadline_s is None and self.default_deadline_s:
            import dataclasses

            params = dataclasses.replace(
                params, deadline_s=self.default_deadline_s)
        if params.deadline_s is not None and params.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        ids = self.encode_prompt(prompt)
        if params.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if params.top_k is not None and not (
                1 <= params.top_k <= self.max_top_k):
            raise ValueError(
                f"top_k={params.top_k} outside this engine's compiled "
                f"capacity 1..{self.max_top_k} (raise max_top_k)")
        if params.adapter is not None:
            # unknown adapters are poison at admission (the slot would
            # decode base-model garbage under the tenant's name) — reject
            # at submit (HTTP 400). Re-resolved at admit: a concurrent
            # evict between here and admission fails just that request.
            if self.adapters is None:
                raise ValueError(
                    f"request names adapter '{params.adapter}' but this "
                    "engine has no adapter registry (--serve_adapters)")
            try:
                self.adapters.resolve(params.adapter)
            except KeyError as e:
                # e.args[0], not str(e): KeyError.__str__ reprs its
                # message, which would wrap the 400 body in quotes
                raise ValueError(e.args[0]) from None
        if int(ids.size) > self.max_prompt:
            sharded = (f" ({self.prompt_pane} tokens/device pane x "
                       f"sp={self._sp}, seq-sharded)" if self._sp > 1
                       else "")
            raise PromptTooLongError(
                f"prompt ({ids.size} tokens) exceeds the engine's "
                f"prompt ceiling {self.max_prompt}{sharded}",
                prompt_tokens=int(ids.size), limit=self.max_prompt,
                pane_tokens=self.prompt_pane, sp=self._sp)
        total = int(ids.size) + params.max_new_tokens
        if total > self.max_len:
            # plain ValueError (HTTP 400), NOT PromptTooLongError: the
            # prompt itself fits under the ceiling — the client asked
            # for too many NEW tokens, so shrinking max_new_tokens (not
            # the payload) is the fix
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens "
                f"({params.max_new_tokens}) = {total} exceeds the "
                f"engine's slot capacity {self.max_len}")
        # the Request exists BEFORE any shed/reject decision: every
        # terminal outcome — even "never entered the queue" — must carry
        # a request_id on its event and close a span tree under that id,
        # or trace joins silently drop the requests that were turned away
        req = Request(next_request_id(), ids, params, on_token=on_token)
        # long-context telemetry: a prompt no single device's pane could
        # have prefilled alone (always False off the seq-sharded path)
        req.long_prompt = self._sp > 1 and int(ids.size) > self.prompt_pane
        # router hop (serving/router.py): the dispatch decision precedes
        # the Request's existence, so it arrives as data and lands on the
        # span tree as a `router` child — even for requests turned away
        # by the shed/queue-full decisions below
        req.route = route
        if params.deadline_s is not None:
            # SLO-aware rejection: estimated completion = (queue position
            # / n_slots) x EWMA per-request service time + the request's
            # own decode budget x TPOT. Predictably blowing the deadline
            # gets a useful 429 NOW instead of a useless 504 later.
            # The whole decision runs under the engine lock: the EWMAs
            # and the shed counter mutate under it, and the pre-fix
            # lock-free reads were exactly the unguarded-EWMA access
            # class graft-lint GL031 now flags. TIMED acquire: a wedged
            # tick may hold this lock forever (and drain/restart later
            # abandon it, not release it) — a submit racing the wedge
            # window must stay bounded, so on timeout the shed check is
            # skipped and the request admitted optimistically (the queue
            # TTL expiry still protects its deadline downstream).
            lock = self._lock
            shed = False
            locked = lock.acquire(timeout=1.0)
            try:
                if locked:
                    est = self.estimate_completion_s(
                        len(self.queue), params.max_new_tokens)
                    shed = est is not None and est > params.deadline_s
                    if shed:
                        self.requests_shed += 1
                        retry = round(max(self.estimate_queue_clear_s()
                                          or 0.0, 0.001), 3)
            finally:
                if locked:
                    lock.release()
            if shed:
                self.slo_window.observe(miss=True)
                req.error = (f"shed at submit: estimated completion "
                             f"{est:.2f}s > deadline {params.deadline_s}s")
                req.finish_reason = FINISH_SHED
                req.state = REJECTED
                req.t_finish = time.monotonic()
                self._ev(
                    "request_shed", request_id=req.id,
                    reason="slo_predicted_miss",
                    queue_depth=len(self.queue),
                    deadline_s=params.deadline_s,
                    estimated_e2e_s=round(est, 4), retry_after_s=retry)
                self._emit_span(req)
                req._mark_done()
                raise SLOShedError(
                    f"deadline {params.deadline_s}s unmeetable: estimated "
                    f"completion {est:.2f}s at queue depth "
                    f"{len(self.queue)}", retry_after_s=retry)
        try:
            self.queue.put(req, block=block, timeout=timeout)
        except QueueFullError:
            req.state = REJECTED
            req.finish_reason = FINISH_REJECTED
            req.t_finish = time.monotonic()
            with self._lock:                   # submit() is thread-safe
                self.requests_rejected += 1
            self._ev("request_rejected", request_id=req.id,
                                reason="queue_full",
                                queue_depth=len(self.queue))
            self._emit_span(req)
            req._mark_done()
            raise
        if self._dead is not None or self._draining:
            # raced _fail_all/drain: a blocked put() can be woken by the
            # death/drain queue sweep and append into an engine that will
            # never process it — fail it here instead of hanging result()
            msg = self._dead or "engine is draining"
            if self.queue.remove(req):
                # still queued: we own it — retire it here
                req.error = msg
                req.finish_reason = (FINISH_ERROR if self._dead
                                     else FINISH_PREEMPTED)
                req.state = FINISHED
                req._mark_done()
            elif self._draining and self._dead is None:
                # the decode loop popped it first: admission beat the
                # drain, the request IS being served and drain will let
                # it finish — force-finishing here would double-finish a
                # live request. Hand the caller its (valid) handle.
                with self._work:
                    self._work.notify()
                return req
            # remove failed + dead: the _fail_all sweep already retired it
            if self._dead is not None:
                raise RuntimeError(f"engine is dead: {self._dead}")
            raise EngineDrainingError("engine is draining: admission closed")
        with self._work:
            self._work.notify()
        return req

    def adopt(self, req: Request, timeout: float = 5.0) -> None:
        """Enqueue an EXISTING queued ``Request`` (the router's drain
        re-dispatch: work stolen from a draining replica's queue moves to
        a live one without the client's handle changing). BOUNDED
        blocking backpressure: past ``timeout`` a full (or wedged-loop)
        target raises ``QueueFullError`` so the re-dispatcher can fall
        through to another replica — an unbounded wait here would hang
        the whole rolling drain behind one stuck engine."""
        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        if self._draining:
            raise EngineDrainingError(
                "engine is draining: admission closed")
        self.queue.put(req, block=True, timeout=timeout)
        with self._work:
            self._work.notify()

    def service_snapshot(self) -> dict:
        """Router-facing load/liveness snapshot (one per dispatch
        decision). TIMED lock acquire: a wedged replica must never hang
        fleet dispatch — on timeout the lock-free attr reads are stale
        but safe (worst case one misrouted request, which the target's
        own admission stack still protects)."""
        lock = self._lock
        locked = lock.acquire(timeout=0.2)
        try:
            return {
                "queue_depth": len(self.queue),
                "queue_capacity": self.queue.max_size,
                "n_active": self.scheduler.n_active,
                "n_slots": self.n_slots,
                "tpot_ewma": self._tpot_ewma,
                "tokens_ewma": self._tokens_ewma,
                "draining": self._draining,
                "dead": self._dead is not None,
            }
        finally:
            if locked:
                lock.release()

    # -- SLO service estimate ---------------------------------------------

    # holds: _lock
    def estimate_completion_s(self, queue_depth: int,
                              max_new_tokens: int) -> Optional[float]:
        """Predicted submit->finish seconds for a request entering the
        queue at ``queue_depth``: (queue position + the already-RUNNING
        requests, counted half-done on average) x the EWMA per-request
        service time (spread over ``n_slots`` concurrent rows) + its own
        decode budget at the EWMA TPOT. Without the in-flight term a
        full-slots/empty-queue engine would predict zero wait and admit
        requests straight into a TTL expiry. None until at least one
        request has finished (no history — admission stays optimistic).
        The math itself lives in module-level ``service_estimate`` — the
        router's fleet dispatch computes the SAME estimate from replica
        snapshots, and the two deciding differently about "predicted
        miss" would route requests into immediate sheds."""
        return service_estimate(queue_depth, self.scheduler.n_active,
                                self.n_slots, self._tpot_ewma,
                                self._tokens_ewma, max_new_tokens)

    # holds: _lock
    def estimate_queue_clear_s(self) -> Optional[float]:
        """Rough seconds until the current backlog drains (Retry-After
        material for 429/503 responses)."""
        return queue_clear_estimate(len(self.queue),
                                    self.scheduler.n_active, self.n_slots,
                                    self._tpot_ewma, self._tokens_ewma)

    # holds: _lock
    def _observe_service_time(self, req: Request) -> None:
        """Fold one finished request into the TPOT/length EWMAs (only
        normal completions: failed/expired requests have no useful
        service signature)."""
        tpot = req.tpot_s()
        n_tok = len(req.output_ids)
        if tpot is None or n_tok < 1:
            return
        alpha = 0.2
        self._tpot_ewma = (tpot if self._tpot_ewma is None
                           else (1 - alpha) * self._tpot_ewma
                           + alpha * tpot)
        self._tokens_ewma = (float(n_tok) if self._tokens_ewma is None
                             else (1 - alpha) * self._tokens_ewma
                             + alpha * n_tok)

    # -- admission-boundary shed ------------------------------------------

    # holds: _lock
    def _admission_skip(self, req: Request) -> bool:
        """Scheduler skip hook: shed expired/cancelled requests the moment
        they reach the queue head, without consuming a slot."""
        if req._cancelled:
            self._fail_request(None, req, "cancelled while queued",
                               reason="cancelled", finish=FINISH_CANCELLED)
            return True
        if req.expired():
            self.requests_expired += 1
            self.slo_window.observe(miss=True)
            waited = time.monotonic() - req.t_submit
            req.error = (f"deadline {req.params.deadline_s}s passed after "
                         f"{waited:.2f}s in queue")
            req.finish_reason = FINISH_EXPIRED
            req.state = FINISHED
            req.t_finish = time.monotonic()
            self._ev("request_expired", request_id=req.id,
                                reason="deadline_expired",
                                deadline_s=req.params.deadline_s,
                                queue_wait_s=round(waited, 4),
                                queue_depth=len(self.queue))
            self._emit_span(req)
            req._mark_done()
            return True
        return False

    # holds: _lock
    def _admit(self, slot: int, req: Request, gen: int) -> None:
        """Prefill one admitted request into ``slot``. Fault-isolated: a
        host-side fault on THIS request's path (injected prefill fault,
        raising client callback, detok error) fails it alone and frees the
        slot — co-resident requests never see it. (Device-side faults that
        poison the whole batch escape to the loop and go through the
        supervisor restart instead.)

        ``gen`` is the caller's generation stamp: the prefill device call
        is a wedge point the supervisor may abandon, so a thread that
        un-wedges here must re-check before committing the new cache —
        otherwise it would overwrite the restarted engine's fresh KV."""
        import jax

        Tp = int(req.prompt_ids.size)   # graft-ok: GL011 host numpy size
        # explicit device_get: the ONLY sanctioned d->h idiom in the tick
        # path — the transfer-guard sentry (analysis/runtime.py) lets it
        # through while failing any implicit fetch that sneaks in
        base_key = jax.device_get(_prng_key(req.params.seed))
        temp = np.float32(req.params.temperature)
        topk = np.int32(req.params.top_k or 0)
        adapter_row = np.int32(-1)
        if req.params.adapter is not None:
            # re-resolve by NAME at admission: submit's check only gates
            # entry — a hot evict (or evict+reload into another row)
            # while the request sat queued must bind the CURRENT row, or
            # fail this one request in isolation, never serve stale rows
            row = (self.adapters.lookup(req.params.adapter)
                   if self.adapters is not None else None)
            if row is None:
                self._fail_request(
                    slot, req,
                    f"adapter '{req.params.adapter}' evicted while queued",
                    reason="adapter_not_loaded")
                return
            adapter_row = np.int32(row)
        try:
            self.hooks.before_prefill(req)
        except Exception as e:  # noqa: BLE001 — poison request, isolate
            if self._generation != gen:
                return      # restart already failed this request
            self._fail_request(slot, req, f"prefill failed: {e!r}",
                               reason="prefill_error")
            return
        if self.kv_policy.prefill_chunk > 0:
            self._admit_chunked(slot, req, gen, base_key, temp, topk,
                                adapter_row)
            return
        # monolithic tier only: bucket-pad the whole prompt (the chunked
        # tier builds its C-token chunk arrays per tick instead)
        Tpb = self._bucket_len(Tp)
        padded = np.zeros((1, Tpb), np.int32)
        padded[0, :Tp] = req.prompt_ids
        # the `prefill` phase spans dispatch THROUGH the ok-scalar sync:
        # the jitted call returns before the device finishes (async
        # dispatch), so timing the call alone would book the execution
        # wait into whatever host line happens to touch a result first
        t_pf = time.perf_counter()
        tok, ok, cache = self._prefill(self.cache, padded, np.int32(Tp),
                                       np.int32(slot), base_key, temp,
                                       topk,
                                       *self._pool_args_for(adapter_row))
        if self._generation != gen:
            return          # abandoned mid-prefill: commit nothing
        self.cache = cache
        req.state = RUNNING
        req.slot = slot
        req.t_admit = time.monotonic()
        self._lengths[slot] = Tp
        self._n_gen[slot] = 0
        self._base_keys[slot] = base_key
        self._temps[slot] = temp
        self._topks[slot] = topk
        self._adapter_ids[slot] = adapter_row
        if self._hist is not None:
            self._hist[slot, :Tp] = req.prompt_ids
            self._hist_len[slot] = Tp
        if self.hooks.poison_nan(req):
            self._poison_slot_cache(slot)      # fault injection (tests)
        # explicit fetch; blocks until prefill ran
        ok_host = bool(jax.device_get(ok))
        self._tick_add("prefill", time.perf_counter() - t_pf)
        if not ok_host:
            self._fail_request(slot, req,
                               "non-finite logits in prefill",
                               reason="non_finite_logits")
            return
        self._accept_token(slot, req, int(jax.device_get(tok)), gen)

    # -- chunked prefill + prefix cache ------------------------------------

    def _adapter_tag(self, req: Request) -> Optional[str]:
        """Prefix-store namespace for one request: the registry's LOAD
        tag (name + per-install sequence), so an adapter evicted and
        reloaded — possibly with different weights — can never hit the
        old install's panes. Base traffic shares one namespace. None —
        the adapter vanished between admission's row resolution and
        here (hot evict race) — means NO namespace: the request must
        neither hit another tenant's panes nor store its own under one,
        so the caller skips the prefix store entirely."""
        if req.params.adapter is None or self.adapters is None:
            return BASE_ADAPTER
        return self.adapters.load_tag(req.params.adapter)

    # holds: _lock
    def _admit_chunked(self, slot: int, req: Request, gen: int,
                       base_key, temp, topk, adapter_row) -> None:
        """Chunked admission: probe the prefix store, copy a hit's panes
        into the slot (one batched DUS program — zero forward FLOPs for
        the cached span), and queue the suffix for the per-tick chunk
        pump (``_chunk_tick``). The first sampled token arrives when the
        final chunk lands, so slot state is primed here but the request
        only joins the decode batch then."""
        Tp = int(req.prompt_ids.size)   # graft-ok: GL011 host numpy size
        pos = 0
        tag = (self._adapter_tag(req) if self.prefix_store is not None
               else None)
        if tag is not None:
            span, entry = self.prefix_store.match(req.prompt_ids, tag)
            if entry is not None:
                if not self._apply_prefix_hit(slot, req, gen, span, entry,
                                              late=False):
                    return      # abandoned mid-copy: commit nothing
                pos = span
            else:
                self._window_prefix_misses += 1
                self._ev("prefix_miss", request_id=req.id,
                                    prompt_tokens=Tp,
                                    adapter=req.params.adapter)
        req.state = RUNNING
        req.slot = slot
        req.t_admit = time.monotonic()
        # slot state primed now; `_lengths` tracks the NEXT write
        # position while prefilling, so the decode step's garbage append
        # for this row lands exactly where the next chunk overwrites
        self._lengths[slot] = pos
        self._n_gen[slot] = 0
        self._base_keys[slot] = base_key
        self._temps[slot] = temp
        self._topks[slot] = topk
        self._adapter_ids[slot] = adapter_row
        if self._hist is not None:
            self._hist[slot, :Tp] = req.prompt_ids
            self._hist_len[slot] = Tp
        self._prefill_state[slot] = {
            "req": req, "pos": pos, "Tp": Tp, "base_key": base_key,
            "temp": temp, "topk": topk, "adapter_row": adapter_row,
            "stored": False,
        }

    # holds: _lock
    def _apply_prefix_hit(self, slot: int, req: Request, gen: int,
                          span: int, entry, late: bool,
                          prev_pos: int = 0) -> bool:
        """Copy a matched (pinned) entry's panes into ``slot`` and emit
        the hit. Returns False on a generation abort (nothing committed).
        ``late``: the catch-up hit — a mid-prefill slot jumping ahead on
        a pane a co-resident sharer just stored (see ``_chunk_tick``);
        ``prev_pos`` is the slot's already-prefilled position then, so
        the request's ``prefix_bytes_saved`` ledger counts only the NEW
        tokens the copy spared it from recomputing."""
        if self._paged:
            return self._apply_paged_hit(slot, req, gen, span, entry,
                                         late, prev_pos)
        t_cp = time.perf_counter()
        try:
            cache = self._prefix_copy(self.cache, entry.panes,
                                      np.int32(slot))
        finally:
            self.prefix_store.release(entry)
        if self._generation != gen:
            return False
        self.cache = cache
        self.pane_copies += 1   # spy: paged mode asserts this stays 0
        self._window_prefix_hits += 1
        self._tick_add("prefix_copy", time.perf_counter() - t_cp)
        # the exact quantity ROADMAP item 1 (paged KV) optimizes: KV
        # bytes this hit spared the request from recomputing
        req.prefix_bytes_saved += ((span - prev_pos)
                                   * self._kv_bytes_per_token)
        Tp = int(req.prompt_ids.size)   # graft-ok: GL011 host numpy size
        self._ev(
            "prefix_hit", request_id=req.id, span_tokens=span,
            prompt_tokens=Tp, key=entry.key, late=late,
            n_suffix_chunks=-(-(Tp - span)
                              // self.kv_policy.prefill_chunk),
            adapter=req.params.adapter)
        return True

    # holds: _lock
    def _apply_paged_hit(self, slot: int, req: Request, gen: int,
                         span: int, entry, late: bool,
                         prev_pos: int = 0) -> bool:
        """Paged prefix HIT: a host page-table write. The slot's leading
        columns point at the entry's SHARED refcounted pages — no device
        program, no copy, zero FLOPs/bytes for the cached span (the
        whole point of the page table). Incref FIRST, then retire the
        slot's old columns: a late hit's entry may share physical pages
        with the columns being replaced (a sharer stored a longer pane
        over the same prefix), and incref-before-decref keeps those
        pages alive through the swap."""
        pages = entry.pages
        try:
            for p in pages:
                self.page_pool.incref(p)
        finally:
            self.prefix_store.release(entry)
        old_cols = int(self._slot_cols[slot])  # graft-ok: GL011 host numpy
        old = [int(p)                          # graft-ok: GL011 host numpy
               for p in self._page_table[slot, :old_cols]]
        n_new = len(pages)          # == span // page_tokens, by insert
        self._page_table[slot, :n_new] = pages
        self._slot_cols[slot] = n_new
        for p in old:
            self.page_pool.decref(p)
        # refund the reservation for every column the share just covered:
        # admission reserved the full worst-case need assuming NO hit;
        # shared columns will never draw a fresh page
        refund = min(n_new - old_cols,
                     int(self._pages_reserved[slot]))  # graft-ok: GL011 host numpy
        if refund > 0:
            self.page_pool.unreserve(refund)
            self._pages_reserved[slot] -= refund
        self._window_prefix_hits += 1
        req.prefix_bytes_saved += ((span - prev_pos)
                                   * self._kv_bytes_per_token)
        Tp = int(req.prompt_ids.size)   # graft-ok: GL011 host numpy size
        self._ev(
            "prefix_hit", request_id=req.id, span_tokens=span,
            prompt_tokens=Tp, key=entry.key, late=late,
            n_suffix_chunks=-(-(Tp - span)
                              // self.kv_policy.prefill_chunk),
            adapter=req.params.adapter)
        self._ev("page_share", request_id=req.id, slot=slot,
                 n_pages=n_new, span_tokens=span, late=late,
                 pool_free=self.page_pool.n_free)
        return True

    # holds: _lock
    def _chunk_tick(self, gen: int) -> bool:
        """One prefill chunk for every mid-prefill slot — the per-tick
        prefill work is bounded by n_prefilling x one C-token program,
        whatever the prompt lengths. Returns False on a generation
        abort (the caller books tick wall and bails)."""
        import jax

        C = self.kv_policy.prefill_chunk
        for slot in sorted(self._prefill_state):
            st = self._prefill_state[slot]
            req: Request = st["req"]
            Tp = st["Tp"]
            span_cap = (self.prefix_store.storable_span(Tp)
                        if self.prefix_store is not None else 0)
            # catch-up probe: a slot co-admitted with the FIRST sharer of
            # a prefix missed at admission (the store was empty), but the
            # sharer's pane may have landed since (early insertion below)
            # — jump ahead by pane copy instead of recomputing chunks.
            # count_miss=False: only admission misses are workload misses
            tag = (self._adapter_tag(req)
                   if self.prefix_store is not None and st["pos"] < span_cap
                   else None)
            if tag is not None:
                span, entry = self.prefix_store.match(
                    req.prompt_ids, tag,
                    min_span=st["pos"], count_miss=False)
                if entry is not None:
                    if not self._apply_prefix_hit(slot, req, gen, span,
                                                  entry, late=True,
                                                  prev_pos=st["pos"]):
                        return False
                    st["pos"] = span
                    self._lengths[slot] = span
            t_pf = time.perf_counter()
            lo = st["pos"]
            hi = min(lo + C, Tp)
            chunk = np.zeros((1, C), np.int32)
            chunk[0, : hi - lo] = req.prompt_ids[lo:hi]
            if self._paged:
                # back the chunk's real columns with pages; the pad
                # tail's columns stay unmapped and scatter into trash
                self._ensure_pages(slot, hi)
            tok, ok, cache = self._prefill_chunk(
                self.cache, chunk, np.int32(lo), np.int32(Tp),
                np.int32(slot),
                *((self._page_table,) if self._paged else ()),
                st["base_key"], st["temp"], st["topk"],
                *self._pool_args_for(st["adapter_row"]))
            if self._generation != gen:
                return False        # abandoned mid-chunk: commit nothing
            self.cache = cache
            st["pos"] = lo + C
            self._window_prefill_chunks += 1
            self._tick_add(self._prefill_phase,
                           time.perf_counter() - t_pf)
            # EARLY insertion: the moment the chunk covering the storable
            # span lands, the pane [0, span) is final — store it NOW so
            # co-admitted sharers (still mid-prefill behind us) catch up
            # this very tick instead of after our whole prompt
            if (self.prefix_store is not None and not st["stored"]
                    and 0 < span_cap <= st["pos"]):
                st["stored"] = True
                self._maybe_store_prefix(slot, req, gen)
                if self._generation != gen:
                    return False
            if st["pos"] < Tp:
                self._lengths[slot] = st["pos"]
                continue
            # final chunk: the request's first token. Explicit fetch —
            # the ONLY chunk that syncs (mirrors the legacy prefill)
            t_pf = time.perf_counter()
            ok_host = bool(jax.device_get(ok))
            self._tick_add(self._prefill_phase,
                           time.perf_counter() - t_pf)
            del self._prefill_state[slot]
            self._lengths[slot] = Tp
            if self.hooks.poison_nan(req):
                self._poison_slot_cache(slot)  # fault injection (tests)
            if not ok_host:
                self._fail_request(slot, req,
                                   "non-finite logits in prefill",
                                   reason="non_finite_logits")
                continue
            self._accept_token(slot, req, int(jax.device_get(tok)), gen)
            if self._generation != gen:
                return False
        return True

    # holds: _lock
    def _maybe_store_prefix(self, slot: int, req: Request,
                            gen: int) -> None:
        """After a completed prefill, extract the slot's chunk-aligned
        prefix pane and insert it into the store (miss path only — a
        present key is just touched). Runs BEFORE the first decode
        append, so the pane is a pure function of (prefix tokens,
        params, adapter); the extract program additionally zero-clamps
        everything past the span (byte-determinism — see
        ``kvcache.extract_prefix_panes``)."""
        if self.prefix_store is None:
            return
        Tp = int(req.prompt_ids.size)   # graft-ok: GL011 host numpy size
        span = self.prefix_store.storable_span(Tp)
        if span <= 0:
            return
        tag = self._adapter_tag(req)
        if tag is None:
            return      # adapter evicted mid-flight: no namespace to own
        prefix_ids = req.prompt_ids[:span]
        if self.prefix_store.contains(prefix_ids, tag):
            return
        if self._paged:
            # paged store = publish the slot's OWN leading pages under
            # the key (the store increfs them) — no extract program, no
            # copy, no new bytes allocated. span is chunk-aligned and
            # C % P == 0, so the span covers whole pages exactly.
            n_cols = span // self.kv_policy.page_tokens
            pages = [int(p)                    # graft-ok: GL011 host numpy
                     for p in self._page_table[slot, :n_cols]]
            nbytes = self.prefix_store.insert_pages(prefix_ids, tag,
                                                    pages)
            if nbytes:
                self._ev(
                    "prefix_insert", request_id=req.id,
                    span_tokens=span, bytes=nbytes,
                    entries=self.prefix_store.n_entries,
                    adapter=req.params.adapter)
            return
        t_ex = time.perf_counter()
        panes = self._prefix_extract(self.cache, np.int32(slot),
                                     np.int32(span))
        self._tick_add("prefix_copy", time.perf_counter() - t_ex)
        if self._generation != gen:
            return
        nbytes = self.prefix_store.insert(prefix_ids, tag, panes)
        if nbytes:
            self._ev(
                "prefix_insert", request_id=req.id, span_tokens=span,
                bytes=nbytes, entries=self.prefix_store.n_entries,
                adapter=req.params.adapter)

    # holds: _lock
    def _poison_slot_cache(self, slot: int) -> None:
        """Overwrite one slot's KV rows with NaN (fault-injection hook):
        the next decode tick's logits for that row go non-finite IN-GRAPH,
        exercising the finite guard through the real compiled program —
        same shapes, zero recompiles, co-resident rows untouched (their
        attention never reads another slot's rows). int8 caches poison
        through the FLOAT leaves (the scale sidecars): int8 codes can't
        hold NaN, but a NaN scale makes every dequantized value NaN.

        Paged: NaN only the slot's PRIVATE pages (refcount 1). Shared
        pages belong to other tenants too — poisoning them would fail
        innocent co-sharers, which the contiguous fault model (slot
        isolation) never does."""
        import jax.numpy as jnp

        if self._paged:
            self._rewrite_slot_pages(slot, np.nan)
            return

        def nan_row(layer):
            if not jnp.issubdtype(layer.dtype, jnp.floating):
                return layer
            host = np.asarray(layer).copy()
            host[slot] = np.nan
            if self.mesh_plan is not None:
                # keep the pinned cache sharding: a default-device
                # rebuild would change the compiled programs' arg
                # signature (a recompile) on a mesh-placed engine
                import jax

                return jax.device_put(host, layer.sharding)
            return jnp.asarray(host)

        self.cache = {name: [nan_row(buf) for buf in bufs]
                      for name, bufs in self.cache.items()}

    # -- paged page accounting (host bookkeeping; the jitted programs
    # only ever see the resulting table as traced data) -------------------

    # holds: _lock
    def _page_need(self, req: Request) -> int:
        """Worst-case page count for one request: whole prompt plus
        max_new_tokens plus spec headroom, capped at the slot window."""
        Tp = int(req.prompt_ids.size)   # graft-ok: GL011 host numpy size
        toks = min(Tp + req.params.max_new_tokens + self.spec_k,
                   self._cache_len)
        return -(-toks // self.kv_policy.page_tokens)

    # holds: _lock
    def _admit_pages(self, slot: int, req: Request) -> bool:
        """Paged admission gate: reserve the request's WORST-CASE page
        need up front — admission checks free pages, not free slots.
        Refusal is the oversubscription policy made explicit: the
        request bounces back to the queue head and waits for a
        retirement, instead of deadlocking mid-decode on a dry pool. A
        later prefix hit refunds the shared columns' reservation."""
        if not self._paged:
            return True
        need = self._page_need(req)
        pool = self.page_pool
        if need > pool.n_pages - 1:
            # can NEVER fit (worst case exceeds the whole usable pool):
            # bouncing would livelock the queue head — fail it loudly,
            # like an over-long prompt. Returns None so the admission
            # loop skips _admit (the slot was already freed here).
            self._fail_request(
                slot, req,
                f"request needs up to {need} KV pages but the pool "
                f"holds {pool.n_pages - 1}: shorten the request or "
                "size pool_pages for at least one worst-case request",
                reason="page_pool_too_small")
            return None
        if pool.available() < need:
            if not self._pool_exhausted_logged:
                # one-shot per exhaustion episode (cleared when a slot
                # next returns pages) — steady-state refusals must not
                # spam the event log
                self._pool_exhausted_logged = True
                self._ev("page_pool_exhausted", request_id=req.id,
                         pages_needed=need,
                         pages_available=pool.available())
            return False
        pool.reserve(need)
        self._pages_reserved[slot] = need
        self._ev("page_admit", request_id=req.id, slot=slot,
                 pages_reserved=need, pool_free=pool.n_free)
        return True

    # graft: hot-path
    # holds: _lock
    def _ensure_pages(self, slot: int, n_tokens: int) -> None:
        """Map enough table columns for ``n_tokens`` tokens, drawing
        from this slot's admission reservation (never from the open
        pool — reserving at admission is what makes mid-flight
        exhaustion impossible). Host numpy + integer bookkeeping only;
        unmapped columns stay 0 = the pinned trash page."""
        P = self.kv_policy.page_tokens
        cols = int(self._slot_cols[slot])   # graft-ok: GL011 host numpy
        want = -(-min(n_tokens, self._cache_len) // P)
        want = min(want, self._pages_per_slot,
                   cols + int(self._pages_reserved[slot]))  # graft-ok: GL011 host numpy
        while cols < want:
            page = self.page_pool.alloc(from_reserved=True)
            self._pages_reserved[slot] -= 1
            self._page_table[slot, cols] = page
            cols += 1
        self._slot_cols[slot] = cols

    # holds: _lock
    def _release_slot_pages(self, slot: int) -> None:
        """Retire/cancel/fail: decref every mapped column (pages shared
        with the prefix store or co-sharers survive; private ones return
        to the pool) and hand back the unused reservation — live
        capacity is bounded by tokens in flight, not n_slots x Tmax."""
        cols = int(self._slot_cols[slot])      # graft-ok: GL011 host numpy
        freed = 0
        for col in range(cols):
            if self.page_pool.decref(
                    int(self._page_table[slot, col])):  # graft-ok: GL011 host numpy
                freed += 1
        reserved = int(self._pages_reserved[slot])  # graft-ok: GL011 host numpy
        if reserved:
            self.page_pool.unreserve(reserved)
        self._page_table[slot, :] = 0
        self._slot_cols[slot] = 0
        self._pages_reserved[slot] = 0
        self._pool_exhausted_logged = False
        self._ev("page_release", slot=slot, n_pages=cols,
                 pages_freed=freed, pages_unreserved=reserved,
                 pool_free=self.page_pool.n_free)

    # holds: _lock
    def _rewrite_slot_pages(self, slot: int, value: float) -> None:
        """Host-rewrite the FLOAT leaves of the slot's PRIVATE pages
        (refcount 1; shared pages belong to co-sharers too). value=NaN
        is the fault-injection poison; value=0.0 is the recycling scrub:
        pool pages are read by every slot's gather, so a freed page
        still carrying NaN would re-enter the pool and poison whichever
        slot draws it next (masked attention weights are exactly 0.0,
        and 0.0 x NaN = NaN straight through the softmax) — a cross-slot
        blast radius the contiguous layout never had."""
        import jax.numpy as jnp

        mine = [int(p)
                for p in self._page_table[slot, :self._slot_cols[slot]]
                if int(p) != 0 and self.page_pool.refcount(int(p)) == 1]
        if not mine:
            return

        def rewrite(buf):
            if not jnp.issubdtype(buf.dtype, jnp.floating):
                return buf      # int8 codes: NaN rides the float scales
            host = np.asarray(buf).copy()
            host[mine] = value
            return jnp.asarray(host)

        self.cache = {name: [rewrite(buf) for buf in bufs]
                      for name, bufs in self.cache.items()}

    # -- tracing / tick accounting ----------------------------------------

    def _emit_span(self, req: Request) -> None:
        """Write the request's one terminal ``span`` row (request tree:
        queued/prefill/decode children under a root ``request`` span).
        Every terminal transition calls this exactly once."""
        get_metrics().log_span(**req.trace_row())

    # holds: _lock
    def _tick_add(self, phase: str, dt: float) -> None:
        """Accumulate wall-clock into one tick phase: the current metrics
        window (drained into the cadence row) and the cumulative totals
        (the ``/metrics`` counters). perf_counter only — NEVER a device
        fetch (the no-per-tick-host-sync guard test enforces this)."""
        self._tick_acc[phase] += dt
        self.tick_phase_totals[phase] += dt

    # holds: _lock
    def _book_tick_wall(self, t0: float) -> None:
        """Add a tick's elapsed wall time to the window/cumulative
        totals. Called on EVERY exit from the timed part of ``step()`` —
        including generation-abort returns, which have already booked
        phase seconds: skipping the total there would let a restart
        window's phases sum past its ``tick_total_s``. Also folds the
        tick's prefill+prefix-copy wall into ``tick_prefill_hist`` (the
        per-tick distribution the chunking A/B reads)."""
        dt = time.perf_counter() - t0
        self._tick_acc_total += dt
        self.tick_seconds_total += dt
        pf = (self.tick_phase_totals["prefill"]
              + self.tick_phase_totals["prefill_shard"]
              + self.tick_phase_totals["prefix_copy"]) - self._tick_pf0
        if pf > 0:
            self.tick_prefill_hist.observe(pf)

    # -- the tick ---------------------------------------------------------

    def step(self) -> bool:
        """One engine tick: admit into free slots, then one fused decode
        step over the slot batch. Returns False when fully idle (no active
        slots and nothing queued).

        Generation-guarded: ``_restart`` bumps ``self._generation`` and
        replaces the lock, so a tick that un-wedges AFTER the supervisor
        abandoned it discovers the bump at the next checkpoint and returns
        without committing any state into the restarted engine."""
        import jax

        gen = self._generation
        lock = self._lock
        with lock:
            if self._generation != gen or self._dead is not None:
                return False
            t_tick0 = time.perf_counter()
            self._tick_pf0 = (self.tick_phase_totals["prefill"]
                              + self.tick_phase_totals["prefill_shard"]
                              + self.tick_phase_totals["prefix_copy"])
            self.hooks.before_tick(self)       # injected hang/fault point
            if self._generation != gen:
                self._book_tick_wall(t_tick0)
                return False
            # tick-phase accounting: `admit` is the admission/cancel/
            # bookkeeping remainder — the nested prefill/prefix-copy
            # device calls and client callbacks accumulate into their own
            # phases, so they are subtracted out via before/after
            # snapshots
            nested0 = (self._tick_acc["prefill"]
                       + self._tick_acc["prefill_shard"]
                       + self._tick_acc["prefix_copy"]
                       + self._tick_acc["callback_detok"])
            t_adm0 = time.perf_counter()
            # re-run admission until no progress: a request can finish
            # DURING admission (eos on its first sampled token, or
            # max_new_tokens=1), freeing its slot after admit_from already
            # returned — without the retry those queued behind it would
            # strand (step() would report idle with a non-empty queue)
            while True:
                admitted = self.scheduler.admit_from(
                    self.queue, skip=self._admission_skip)
                bounced = None
                for i, (slot, req) in enumerate(admitted):
                    # paged oversubscription: admission is gated on FREE
                    # PAGES (this request's worst-case need), not free
                    # slots — a slot with no backing memory must not run
                    ok = self._admit_pages(slot, req)
                    if ok is None:
                        continue  # failed permanently (slot already freed)
                    if not ok:
                        bounced = i
                        break
                    self._admit(slot, req, gen)
                    if self._generation != gen:
                        self._book_tick_wall(t_tick0)
                        return False
                if bounced is not None:
                    # hand the refused head — and everything admit_from
                    # popped behind it — back to the queue in reverse, so
                    # FCFS order survives the bounce; retry next tick
                    # once retirements have returned pages to the pool
                    for slot, req in reversed(admitted[bounced:]):
                        self.scheduler.retire(slot)
                        self.queue.put_front(req)
                    break
                if not admitted:
                    break
            # client cancellations retire at the tick boundary: the slot
            # frees NOW instead of decoding to max_new_tokens for nobody
            # (mid-prefill slots included: _free_slot drops their state)
            for slot, req in self.scheduler.active():
                if req._cancelled:
                    self._fail_request(slot, req, "cancelled by client",
                                       reason="cancelled",
                                       finish=FINISH_CANCELLED)
            nested = (self._tick_acc["prefill"]
                      + self._tick_acc["prefill_shard"]
                      + self._tick_acc["prefix_copy"]
                      + self._tick_acc["callback_detok"]) - nested0
            self._tick_add("admit", max(
                time.perf_counter() - t_adm0 - nested, 0.0))
            # chunked-prefill pump: one C-token chunk per mid-prefill
            # slot, BEFORE the decode step — a slot whose final chunk
            # lands here joins this very tick's decode batch (the same
            # admit-then-decode cadence the monolithic path has)
            if self._prefill_state:
                if not self._chunk_tick(gen):
                    self._book_tick_wall(t_tick0)
                    return False
            active = self.scheduler.active()
            if not active:
                # all slots free. Legacy: admission drained the queue
                # too. Chunked: a first-token eos inside _chunk_tick can
                # free the last slot with requests still queued — report
                # progress so the next tick admits them (an admission-
                # only tick still books its wall time so phases keep
                # summing to it)
                self._book_tick_wall(t_tick0)
                return len(self.queue) > 0
            # mid-prefill slots ride through the fixed-shape decode step
            # as ignored rows (their garbage append lands at the next
            # chunk's write position — see _admit_chunked); with NO row
            # actually decoding, skip the step entirely
            decoding = [(s, r) for s, r in active
                        if s not in self._prefill_state]
            if not decoding:
                self.n_ticks += 1
                self._window_ticks += 1
                self._book_tick_wall(t_tick0)
                self._maybe_log_metrics()
                return True
            if self.spec_k:
                # speculative tick: draft k per slot, ONE verify forward,
                # multi-token commit (serving/spec.py + _verify_tick)
                return self._verify_tick(decoding, gen, t_tick0)
            if self._paged:
                # grow each decoding slot's table BEFORE dispatch: the
                # append lands at column lengths//P, which must point at
                # a real page (mid-prefill rows ride as ignored garbage
                # into the pinned trash page — no allocation for them)
                for slot, _req in decoding:
                    self._ensure_pages(
                        slot, int(self._lengths[slot]) + 1)  # graft-ok: GL011 host numpy
            t_dec = time.perf_counter()
            nxt, ok, cache = self._decode(
                self.cache, self._last_tokens, self._lengths,
                *((self._page_table,) if self._paged else ()),
                self._base_keys, self._n_gen, self._temps,
                self._topks, *(self._pool_args() + (self._adapter_ids,)
                               if self.adapters is not None else ()))
            self._tick_add("decode_dispatch", time.perf_counter() - t_dec)
            if self._generation != gen:
                self._book_tick_wall(t_tick0)
                return False
            # `host_fetch` covers the donated-cache rebind AND the two
            # device->host fetches: dropping the old (donated-away)
            # cache arrays and the device_get both block on the in-flight
            # step, so this phase is "waiting for the device to catch up".
            # EXPLICIT device_get, never np.asarray/float(): these are
            # the tick's only two sanctioned d->h transfers, and the
            # transfer-guard sentry test proves nothing implicit remains
            t_fetch = time.perf_counter()
            self.cache = cache
            nxt = jax.device_get(nxt)
            ok_rows = jax.device_get(ok)
            self._tick_add("host_fetch", time.perf_counter() - t_fetch)
            cb0 = self._tick_acc["callback_detok"]
            t_commit = time.perf_counter()
            for slot, req in decoding:
                # a slow-client hook inside _accept_token is a wedge point
                # the supervisor may abandon mid-loop — stop committing
                # rows the moment the generation moves on
                if self._generation != gen:
                    self._book_tick_wall(t_tick0)
                    return False
                # this tick wrote the slot's previous token at _lengths
                self._lengths[slot] += 1
                if not bool(ok_rows[slot]):
                    self._fail_request(
                        slot, req,
                        f"non-finite logits at token {len(req.output_ids)}",
                        reason="non_finite_logits")
                    continue
                self._accept_token(slot, req, int(nxt[slot]), gen)
            self._tick_add("sample_commit", max(
                time.perf_counter() - t_commit
                - (self._tick_acc["callback_detok"] - cb0), 0.0))
            self.n_ticks += 1
            self._window_ticks += 1
            self._book_tick_wall(t_tick0)
            self._maybe_log_metrics()
            return True

    def run_until_idle(self) -> None:
        while self.step():
            pass

    # holds: _lock
    def _verify_tick(self, decoding, gen: int, t_tick0: float) -> bool:
        """One speculative tick: propose k drafts per decoding slot
        (host-side, ``drafter.propose`` against the slot's own history),
        run THE one compiled verify program over all slots, and commit
        each row's longest-accepted prefix — 1..k+1 tokens per slot per
        tick, every count through the same program signature (zero
        recompiles across acceptance churn, watcher-enforced).

        Rows whose request opted out (``SamplingParams.spec=False``)
        ride the same program with their commit clamped to one token —
        per-request semantics cost no extra programs. Mid-prefill slots
        were already filtered out of ``decoding`` by the caller and ride
        as ignored rows inside the program, exactly as in the plain
        decode tick. Returns False on a generation abort (tick wall
        already booked), mirroring ``step()``'s decode block."""
        import jax

        k = self.spec_k
        t_draft = time.perf_counter()
        drafts = np.zeros((self.n_slots, k), np.int32)
        for slot, req in decoding:
            if req.params.spec:
                n_hist = self._hist_len[slot]
                drafts[slot] = self.drafter.propose(
                    self._hist[slot, :n_hist], k)
        tokens_in = np.concatenate(
            [self._last_tokens[:, None], drafts], axis=1)
        self._tick_add("draft", time.perf_counter() - t_draft)
        if self._paged:
            # verify appends k+1 candidates at lengths..lengths+k; the
            # spec headroom (_cache_len = max_len + spec_k) guarantees
            # those columns exist for decoding rows
            for slot, _req in decoding:
                self._ensure_pages(
                    slot, int(self._lengths[slot]) + 1 + k)  # graft-ok: GL011 host numpy
        t_dec = time.perf_counter()
        toks, n_acc, ok, cache = self._verify(
            self.cache, tokens_in, self._lengths,
            *((self._page_table,) if self._paged else ()),
            self._base_keys, self._n_gen, self._temps, self._topks,
            *(self._pool_args() + (self._adapter_ids,)
              if self.adapters is not None else ()))
        self._tick_add("decode_dispatch", time.perf_counter() - t_dec)
        if self._generation != gen:
            self._book_tick_wall(t_tick0)
            return False
        # ONE explicit fetch for the tick's three results (+ the donated
        # cache rebind) — the same sanctioned d->h discipline as the
        # plain decode tick
        t_fetch = time.perf_counter()
        self.cache = cache
        toks, n_acc, ok_rows = jax.device_get((toks, n_acc, ok))
        self._tick_add("host_fetch", time.perf_counter() - t_fetch)
        cb0 = self._tick_acc["callback_detok"]
        t_commit = time.perf_counter()
        for slot, req in decoding:
            if self._generation != gen:
                self._book_tick_wall(t_tick0)
                return False
            if not bool(ok_rows[slot]):
                self._fail_request(
                    slot, req,
                    f"non-finite logits at token {len(req.output_ids)}",
                    reason="non_finite_logits")
                continue
            is_spec = req.params.spec
            n_commit = 1 + (int(n_acc[slot]) if is_spec else 0)
            if is_spec:
                # acceptance telemetry counts the IN-GRAPH decision
                # (drafter quality), independent of host truncation at
                # eos/budget below
                accepted = int(n_acc[slot])
                req.spec_drafted += k
                req.spec_accepted += accepted
                self.spec_tokens_drafted += k
                self.spec_tokens_accepted += accepted
                self._window_spec_drafted += k
                self._window_spec_accepted += accepted
            for j in range(n_commit):
                # each commit advances the row's valid-KV prefix by one:
                # position j's entry was appended by THIS tick's verify
                # (the trailing rejected entries stay past the prefix,
                # masked everywhere and overwritten next tick)
                self._lengths[slot] += 1
                self._accept_token(slot, req, int(toks[slot, j]), gen)
                if self._generation != gen:
                    self._book_tick_wall(t_tick0)
                    return False
                if req.done:
                    break               # eos/budget/fault: slot already freed
        self._tick_add("sample_commit", max(
            time.perf_counter() - t_commit
            - (self._tick_acc["callback_detok"] - cb0), 0.0))
        self.n_ticks += 1
        self._window_ticks += 1
        self._book_tick_wall(t_tick0)
        self._maybe_log_metrics()
        return True

    # holds: _lock
    def _accept_token(self, slot: int, req: Request, tok: int,
                      gen: int) -> None:
        eos = resolve_eos(req.params, self.cfg.eos_id)
        if eos is not None and tok == eos:
            # the triggering eos is dropped (generate()'s per-row
            # semantics) and the slot frees this boundary
            self._finish(slot, req, FINISH_EOS)
            return
        now = time.monotonic()
        if req.t_first_token is None:
            req.t_first_token = now
        req.output_ids.append(tok)
        self._last_tokens[slot] = tok
        self._n_gen[slot] = len(req.output_ids)
        if self._hist is not None:
            # committed token enters the drafter's haystack (the dropped
            # eos above never does — it is not part of the sequence)
            self._hist[slot, self._hist_len[slot]] = tok
            self._hist_len[slot] += 1
        self.tokens_generated += 1
        self._window_tokens += 1
        t_cb = time.perf_counter()
        try:
            # the request's OWN host path: detok + client callback. A
            # fault here (raising on_token, tokenizer bug on this output)
            # is this request's problem alone — fail it, free the slot,
            # co-residents decode on undisturbed
            piece = self._detok_piece(req)
            if req.on_token is not None:
                req.on_token(req, tok, piece)
            self.hooks.after_token(req, tok)   # injected slow-client point
        except Exception as e:  # noqa: BLE001 — poison request, isolate
            self._tick_add("callback_detok", time.perf_counter() - t_cb)
            if self._generation != gen:
                return      # restart already failed this request
            self._fail_request(slot, req, f"token callback failed: {e!r}",
                               reason="callback_error")
            return
        self._tick_add("callback_detok", time.perf_counter() - t_cb)
        if self._generation != gen:
            # the callback/hook above is a wedge point — un-wedging after
            # a supervisor restart must not finish/free slots that now
            # belong to the restarted engine
            return
        if piece:
            req._push_piece(piece)
        if len(req.output_ids) >= req.params.max_new_tokens:
            self._finish(slot, req, FINISH_LENGTH)

    #: max tokens a partial multi-byte char may hold back detokenization
    #: before committing anyway (bounds the re-decoded tail per token)
    _DETOK_HOLD_MAX = 16

    def _detok_piece(self, req: Request, final: bool = False) -> str:
        """Incremental detokenization: decode only the uncommitted tail
        (O(tail) per token, not O(total)). A tail ending in a replacement
        char is a partial multi-byte sequence the next token may complete
        — hold it (return "") rather than commit a mangled boundary,
        up to ``_DETOK_HOLD_MAX`` tokens; ``final`` flushes regardless."""
        if self.tokenizer is None:
            return ""
        tail_ids = req.output_ids[req._detok_start:]
        if not tail_ids:
            return ""
        try:
            tail = self.tokenizer.decode([int(t) for t in tail_ids])
        except Exception:                      # partial byte sequences etc.
            return ""
        if (not final and tail.endswith("�")
                and len(tail_ids) < self._DETOK_HOLD_MAX):
            return ""
        req.text += tail
        req._detok_start = len(req.output_ids)
        return tail

    # holds: _lock
    def _free_slot(self, slot: int) -> None:
        if self._paged:
            self._release_slot_pages(slot)
        self.scheduler.retire(slot)
        self._prefill_state.pop(slot, None)    # mid-prefill retirement
        self._lengths[slot] = 0
        self._last_tokens[slot] = 0
        self._n_gen[slot] = 0
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        self._adapter_ids[slot] = -1
        self._hist_len[slot] = 0

    # holds: _lock
    def _count_adapter(self, req: Request, outcome: str) -> None:
        """Per-adapter request accounting (name "base" for un-adapted
        traffic): feeds the labeled /metrics series + serve_summary."""
        name = req.params.adapter or BASE_ADAPTER
        c = self._adapter_counts.setdefault(
            name, {"finished": 0, "failed": 0, "tokens": 0})
        c[outcome] += 1
        c["tokens"] += len(req.output_ids)

    # holds: _lock
    def _fail_request(self, slot: Optional[int], req: Request, msg: str,
                      reason: str, finish: str = FINISH_ERROR) -> None:
        """Fail ONE request (fault isolation): free its slot if it holds
        one, surface the error on the handle, emit ``request_failed`` with
        the machine-readable ``reason`` — the engine itself keeps serving.
        """
        if slot is not None and self.scheduler.slots[slot] is req:
            if self._paged and reason == "non_finite_logits":
                # scrub the failed slot's private pages to zero BEFORE
                # they return to the pool: unlike the contiguous layout,
                # freed pages are recycled into other slots, and a NaN
                # KV value reads through masked attention (0.0 x NaN)
                self._rewrite_slot_pages(slot, 0.0)
            self._free_slot(slot)
        req.error = msg
        req.finish_reason = finish
        req.state = FINISHED
        req.t_finish = time.monotonic()
        self.requests_failed += 1
        self._count_adapter(req, "failed")
        if req.params.deadline_s is not None and finish != FINISH_CANCELLED:
            # a failure is an SLO miss — except a client cancellation,
            # which is the CLIENT giving up; counting it would let
            # disconnect storms fire the burn-rate alert on a server
            # that met every deadline it was actually asked to meet
            self.slo_window.observe(miss=True)
        self._ev("request_failed", request_id=req.id,
                            reason=reason, error=msg, slot=slot,
                            n_tokens=len(req.output_ids),
                            adapter=req.params.adapter)
        self._emit_span(req)
        logger.warning("Request %d failed (%s): %s", req.id, reason, msg)
        req._mark_done()
        with self._work:
            self._work.notify_all()

    # holds: _lock
    def _finish(self, slot: int, req: Request, reason: str) -> None:
        tail = self._detok_piece(req, final=True)  # flush any held bytes
        if tail:
            req._push_piece(tail)
        req.state = FINISHED
        req.finish_reason = reason
        req.t_finish = time.monotonic()
        if self.scheduler.slots[slot] is req:  # not reassigned by restart
            # peak slot-KV attribution, read BEFORE the slot is freed:
            # lengths only grow over a request's residency, so the final
            # committed length IS the peak
            live = int(self._lengths[slot])  # graft-ok: GL011 host numpy
            req.kv_bytes_peak = live * self._kv_bytes_per_token
            self._free_slot(slot)
        self.requests_finished += 1
        self._count_adapter(req, "finished")
        self._observe_service_time(req)
        for hist, val in ((self.ttft_hist, req.ttft_s()),
                          (self.tpot_hist, req.tpot_s()),
                          (self.queue_wait_hist, req.queue_wait_s()),
                          (self.e2e_hist, req.e2e_s())):
            if val is not None:
                hist.observe(val)
        if req.params.deadline_s is not None:
            # SLO burn-rate: a completion is a miss when it beat the shed
            # machinery but still finished past its deadline
            e2e = req.e2e_s() or 0.0
            self.slo_window.observe(miss=e2e > req.params.deadline_s)
        sink = get_metrics()
        self._ev("request_done", **req.summary())
        self._emit_span(req)
        sink.gauge("slot_occupancy", self.scheduler.occupancy())
        sink.gauge("queue_depth", len(self.queue))
        req._mark_done()
        with self._work:
            self._work.notify_all()

    # holds: _lock
    def _maybe_log_metrics(self) -> None:
        if self.metrics_every <= 0 or self.n_ticks % self.metrics_every:
            return
        now = time.monotonic()
        now_wall = time.time()
        dt = max(now - self._window_t0, 1e-9)
        sink = get_metrics()
        sink.gauge("slot_occupancy", self.scheduler.occupancy())
        sink.gauge("queue_depth", len(self.queue))
        sink.gauge("draining", 1.0 if self._draining else 0.0)
        slo = self.slo_window.ratio()
        if slo is not None:
            sink.gauge("slo_miss_ratio", round(slo, 6))
        # the window's tick-phase breakdown: wall-clock aggregates only
        # (perf_counter), fetched device values are NOT involved — the
        # per-tick host syncs stay exactly the two the decode loop always
        # had (next-token + ok mask; guard-tested)
        phases = {f"tick_{ph}_s": round(self._tick_acc[ph], 6)
                  for ph in TICK_PHASES}
        kv = {}
        if self.kv_policy.prefill_chunk > 0:
            kv["prefill_chunks"] = self._window_prefill_chunks
        if self.prefix_store is not None:
            kv["prefix_hits"] = self._window_prefix_hits
            kv["prefix_misses"] = self._window_prefix_misses
        if self.spec_k:
            kv["spec_drafted"] = self._window_spec_drafted
            kv["spec_accepted"] = self._window_spec_accepted
        fleet = ({"replica": self.replica, "monotonic": False}
                 if self.replica is not None else {})
        sink.log_metrics(self.n_ticks, **fleet,
                         serve_tok_s=round(self._window_tokens / dt, 2),
                         requests_finished=self.requests_finished,
                         tokens_generated=self.tokens_generated,
                         ticks_in_window=self._window_ticks,
                         win_t0=round(self._win_t0_wall, 6),
                         win_dur_s=round(now_wall - self._win_t0_wall, 6),
                         tick_total_s=round(self._tick_acc_total, 6),
                         **phases, **kv)
        self._window_tokens = 0
        self._window_t0 = now
        self._window_ticks = 0
        self._win_t0_wall = now_wall
        self._window_prefill_chunks = 0
        self._window_prefix_hits = 0
        self._window_prefix_misses = 0
        self._window_spec_drafted = 0
        self._window_spec_accepted = 0
        self._tick_acc = {ph: 0.0 for ph in TICK_PHASES}
        self._tick_acc_total = 0.0
        # memory-ledger cadence: snapshot + drift/pressure detectors +
        # the memory_snapshot event the trace renders as counter tracks.
        # Pure nbytes/host math — the tick's device syncs stay the two
        # the decode loop always had (guard-tested)
        self.memory_ledger.observe(self.n_ticks)

    # -- warmup / compile discipline --------------------------------------

    def warmup(self) -> None:
        """Compile the legitimate program set up front, then freeze the
        watchers so any later signature is reported as a bucket-miss
        ``recompile``. Monolithic tier: one prefill per prompt bucket.
        Chunked tier (``kv_policy.prefill_chunk > 0``): ONE chunk
        program (+ the prefix copy/extract pair when the store is on) —
        chunk offset, prompt length, span and slot are all data, so the
        whole prefill tier warms in a constant number of compiles.
        Plus THE decode step either way. The warmup traffic runs through
        slot 0 with throwaway state; host state is reset after. Runs
        under the engine lock: warmup normally precedes ``start()``, but
        holding the lock makes a late warmup (or a concurrent early
        submit) safe instead of silently corrupting slot state."""
        import jax

        t0 = time.monotonic()
        with self._lock:
            zero_key = np.zeros_like(self._base_keys[0])
            # warm WITH the adapter-pool argument tail when a registry is
            # attached (id −1 = base): the adapter graph is part of THE
            # one decode program, so later adapter traffic — and every
            # hot-load, which swaps same-shaped pool arrays — hits the
            # frozen signature exactly
            if self.kv_policy.prefill_chunk > 0:
                buckets = [self.kv_policy.prefill_chunk]
                dummy = np.zeros((1, self.kv_policy.prefill_chunk),
                                 np.int32)
                # paged: the warmup table is ALL ZEROS — every scatter/
                # gather rides the pinned trash page, so warming compiles
                # the real programs without allocating a single page
                tok, _ok, cache = self._prefill_chunk(
                    self.cache, dummy, np.int32(0), np.int32(1),
                    np.int32(0),
                    *((self._page_table,) if self._paged else ()),
                    zero_key, np.float32(0.0), np.int32(0),
                    *self._pool_args_for(np.int32(-1)))
                self.cache = cache
                if self.prefix_store is not None and not self._paged:
                    # paged hit/store are host table writes — the copy/
                    # extract programs exist but are never dispatched
                    panes = self._prefix_extract(self.cache, np.int32(0),
                                                 np.int32(1))
                    self.cache = self._prefix_copy(self.cache, panes,
                                                   np.int32(0))
            else:
                buckets = self.prompt_buckets()
                for Tpb in buckets:
                    dummy = np.zeros((1, Tpb), np.int32)
                    tok, _ok, cache = self._prefill(
                        self.cache, dummy, np.int32(1),
                        np.int32(0), zero_key, np.float32(0.0),
                        np.int32(0), *self._pool_args_for(np.int32(-1)))
                    self.cache = cache
            if self.spec_k:
                # the Tq=k+1 verify program IS the tick program when
                # speculation is on — warm (and freeze) it instead of a
                # plain decode step that would never run
                warm_tokens = np.zeros((self.n_slots, self.spec_k + 1),
                                       np.int32)
                nxt, _n_acc, _ok, cache = self._verify(
                    self.cache, warm_tokens, self._lengths,
                    *((self._page_table,) if self._paged else ()),
                    self._base_keys, self._n_gen, self._temps,
                    self._topks, *(self._pool_args()
                                   + (self._adapter_ids,)
                                   if self.adapters is not None else ()))
            else:
                nxt, _ok, cache = self._decode(
                    self.cache, self._last_tokens, self._lengths,
                    *((self._page_table,) if self._paged else ()),
                    self._base_keys, self._n_gen,
                    self._temps, self._topks,
                    *(self._pool_args() + (self._adapter_ids,)
                      if self.adapters is not None else ()))
            self.cache = cache
            jax.device_get(nxt)               # block until compiled + ran
            if isinstance(self._prefill, CompileWatcher):
                for w in self._watchers():
                    w.freeze()
            self._lengths[:] = 0
            self._last_tokens[:] = 0
            self._n_gen[:] = 0
            self._adapter_ids[:] = -1
            # re-anchor the metrics window: the first cadence row should
            # describe serving, not a window stretched over compile time
            self._window_t0 = time.monotonic()
            self._win_t0_wall = time.time()
            self._window_tokens = 0
            self.warmed_up = True
        bps = self.kv_policy.bytes_per_slot(self.cfg, self._cache_len)
        spec_fields = ({"spec_k": self.spec_k,
                        "drafter": self.drafter.describe()}
                       if self.spec_k else {})
        kv_fields = self.kv_policy.describe()
        if self._paged:
            # the RESOLVED usable pool (policy.pool_pages=0 means "sized
            # to n_slots full rows" — report what was actually built)
            kv_fields["pool_pages"] = self.page_pool.n_pages - 1
        sp_fields = ({"sp": self._sp,
                      "prompt_pane_tokens": self.prompt_pane,
                      "max_prompt": self.max_prompt}
                     if self._sp > 1 else {})
        self._ev(
            "serve_warmup", n_prefill_buckets=len(buckets),
            buckets=buckets, seconds=round(time.monotonic() - t0, 3),
            n_slots=self.n_slots, max_len=self.max_len,
            kv_bytes_per_slot=bps["total_bytes"],
            prefix_pane_tokens=(self._prefix_pane_len
                                if self.prefix_store is not None
                                else None),
            **kv_fields, **spec_fields, **sp_fields)
        logger.info(
            "Serving warmup: %s + 1 %s program in %.2fs (kv %s, "
            "%.2f MiB/slot%s%s)",
            (f"1 chunk program (C={self.kv_policy.prefill_chunk})"
             if self.kv_policy.prefill_chunk > 0
             else f"{len(buckets)} prefill buckets {buckets}"),
            f"verify (k={self.spec_k})" if self.spec_k else "decode",
            time.monotonic() - t0, self.kv_policy.kv_quant,
            bps["total_bytes"] / 1024 ** 2,
            ", prefix cache on" if self.prefix_store is not None else "",
            f", spec {self.drafter.describe()}" if self.spec_k else "")

    def _watchers(self) -> list:
        return [w for w in (self._prefill, self._prefill_chunk,
                            self._prefix_copy, self._prefix_extract,
                            self._decode, self._verify)
                if isinstance(w, CompileWatcher)]

    @property
    def n_recompiles(self) -> int:
        return sum(w.n_recompiles for w in self._watchers())

    # -- background loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        if self.supervisor is not None:
            self.supervisor.start()
        self._spawn_loop()

    def _spawn_loop(self) -> None:
        """Start one decode-loop thread bound to the CURRENT generation.
        A stale thread (superseded by ``_restart``) exits at its next
        checkpoint without touching engine state."""
        gen = self._generation

        def loop():
            while not self._stop.is_set() and self._generation == gen:
                if self.supervisor is not None:
                    self.supervisor.notify_tick()
                if self._heartbeat is not None:
                    self._heartbeat()
                try:
                    progressed = self.step()
                except Exception as e:          # noqa: BLE001 — must not
                    # die silently: callers block on result() forever and
                    # shutdown(drain=True) spins if requests just vanish
                    if self._generation != gen:
                        return                  # superseded: not ours
                    logger.exception("decode-engine loop died")
                    # batch-wide fault: with a supervisor and restart
                    # budget left, fail only the in-flight batch and come
                    # back up; otherwise the engine dies loudly
                    if self.supervisor is None or not self._restart(
                            reason="loop_error",
                            detail=f"engine loop error: {e!r}"):
                        self._fail_all(f"engine loop error: {e!r}")
                    return
                if not progressed:
                    with self._work:
                        self._work.wait(timeout=0.05)

        self._thread = threading.Thread(target=loop, name="decode-engine",
                                        daemon=True)
        self._thread.start()

    #: external per-tick heartbeat (``--stall_timeout`` flight recorder in
    #: serve mode rides this without the full supervisor)
    _heartbeat = None

    def set_heartbeat(self, fn) -> None:
        self._heartbeat = fn

    def _restart(self, reason: str, detail: str = "") -> bool:
        """Supervisor recovery: abandon the (possibly wedged) loop thread,
        fail the in-flight requests, keep the queue, rebuild the KV cache
        and sync primitives, and bring up a fresh loop thread after a
        bounded exponential backoff. The compiled prefill/decode programs
        (and their CompileWatchers) are untouched — the restarted engine
        reuses them, so recovery costs ZERO recompiles. Returns False when
        the restart budget is exhausted (caller escalates to _fail_all).
        """
        with self._restart_lock:
            if self._dead is not None or self._stop.is_set():
                return False
            if self.n_restarts >= self.max_restarts:
                return False
            self.n_restarts += 1
            n_restart = self.n_restarts
            # bump FIRST: the wedged thread checks the generation at every
            # commit point, and must see the bump before we touch state
            self._generation += 1
            # fresh primitives — the abandoned thread may hold the old
            # lock forever; new threads must not queue behind it
            self._lock = threading.RLock()
            self._work = threading.Condition()
            failed = 0
            failed_ids = []
            with self._lock:
                for slot, req in self.scheduler.active():
                    self._fail_request(
                        slot, req,
                        f"engine restarted ({reason}): {detail}",
                        reason="engine_restart")
                    failed += 1
                    failed_ids.append(req.id)
                self._lengths[:] = 0
                self._last_tokens[:] = 0
                self._n_gen[:] = 0
                self._temps[:] = 0.0
                self._topks[:] = 0
                self._adapter_ids[:] = -1
                self._hist_len[:] = 0
                self._prefill_state.clear()
                # the old cache may be donation-poisoned or numerically
                # corrupt; a fresh one has identical shapes/dtypes, so the
                # frozen compiled programs accept it without recompiling.
                # Contiguous: the prefix store survives — its panes are
                # independent device arrays a wedged tick can't have
                # corrupted. Paged: stored entries REFERENCE the pool
                # being thrown away, so the store is cleared and the pool
                # rebuilt from scratch alongside the cache (the ledger's
                # providers read self.page_pool and follow the swap).
                self.cache = self._place_cache(init_slot_cache(
                    self.cfg, self.n_slots, self._cache_len,
                    policy=self.kv_policy))
                if self._paged:
                    if self.prefix_store is not None:
                        self.prefix_store.clear()
                    self.page_pool = PagePool(
                        self.kv_policy.total_pool_pages(self.n_slots,
                                                        self._cache_len),
                        self.kv_policy.page_bytes(self.cfg))
                    if self.prefix_store is not None:
                        self.prefix_store.page_pool = self.page_pool
                    self._page_table[:] = 0
                    self._slot_cols[:] = 0
                    self._pages_reserved[:] = 0
                    self._pool_exhausted_logged = False
            backoff = self.restart_backoff_s * (2.0 ** (n_restart - 1))
            self._ev(
                "engine_restart", reason=reason, detail=detail,
                n_restart=n_restart, max_restarts=self.max_restarts,
                backoff_s=round(backoff, 3), n_inflight_failed=failed,
                failed_request_ids=failed_ids,
                queue_depth=len(self.queue))
            logger.error(
                "Engine restart %d/%d (%s): failed %d in-flight "
                "request(s), kept %d queued; backoff %.2fs.",
                n_restart, self.max_restarts, reason, failed,
                len(self.queue), backoff)
            time.sleep(backoff)
            if self._thread is not None:
                self._spawn_loop()
        return True

    def _fail_all(self, msg: str) -> None:
        """Fail every in-flight and queued request (engine loop death):
        set ``req.error`` so ``result()`` raises instead of hanging.
        Marks the engine dead — later ``submit()`` calls raise.

        Timed lock acquire for the same reason as ``drain()``: the
        supervisor's escalation path runs this WHILE the tick is wedged
        holding the lock — a plain acquire would deadlock the recovery."""
        lock = self._lock
        locked = lock.acquire(timeout=5.0)
        try:
            if not locked:
                # edge is infeasible: this branch runs only when the
                # _lock acquire FAILED (wedged tick), and _restart
                # acquires the REPLACEMENT lock, not the abandoned one
                with self._restart_lock:  # graft-ok: GL032 wedge path
                    self._generation += 1   # wedged loop may never commit
                    self._lock = threading.RLock()   # see drain(): later
                    self._work = threading.Condition()  # paths must not
                    # queue behind the lock the wedged thread holds
            self._dead = msg
            failed = 0
            failed_ids = []

            def _kill(req, slot=None):
                # engine death is still a per-request terminal outcome:
                # each request gets its own request_failed event + closed
                # span so trace joins never drop the casualties
                req.error = msg
                req.finish_reason = FINISH_ERROR
                req.state = FINISHED
                req.t_finish = time.monotonic()
                self.requests_failed += 1
                self._ev("request_failed", request_id=req.id,
                                    reason="engine_dead", error=msg,
                                    slot=slot,
                                    n_tokens=len(req.output_ids))
                self._emit_span(req)
                req._mark_done()
                failed_ids.append(req.id)

            for slot, req in self.scheduler.active():
                self.scheduler.retire(slot)
                _kill(req, slot)
                failed += 1
            while True:
                req = self.queue.get_nowait()
                if req is None:
                    break
                _kill(req)
                failed += 1
            self._ev("serve_error", error=msg, n_failed=failed,
                                failed_request_ids=failed_ids)
        finally:
            if locked:
                lock.release()
        with self._work:
            self._work.notify_all()

    # -- graceful drain ----------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def cancel(self, req: Request) -> bool:
        """Client gave up on ``req`` (HTTP timeout, disconnect): stop
        spending decode on it. Queued requests are failed immediately;
        running ones are marked and retired at the next tick boundary
        (their slot frees instead of decoding to ``max_new_tokens`` for
        nobody). Returns False when the request is already done."""
        if req.done:
            return False
        req._cancelled = True
        if req.state == QUEUED and self.queue.remove(req):
            # under the engine lock: _fail_request mutates the shared
            # failure counters and must not interleave with a tick
            # retiring the same request (pre-fix this ran lock-free from
            # client threads — a real GL031 finding). TIMED acquire: a
            # wedged tick holds the lock forever and restart ABANDONS
            # (never releases) it, so an unbounded acquire would leak
            # this client thread — on timeout fall back to the old
            # lock-free retire: we already own the request (remove()
            # returned True) and the wedged tick can never commit it
            # (generation-checked), so the race window is gone with it
            lock = self._lock
            locked = lock.acquire(timeout=2.0)
            try:
                self._fail_request(None, req, "cancelled while queued",
                                   reason="cancelled",
                                   finish=FINISH_CANCELLED)
            finally:
                if locked:
                    lock.release()
        with self._work:
            self._work.notify()
        return True

    def drain(self, timeout: float = 30.0) -> dict:
        """Graceful drain: close admission (``submit()`` raises
        ``EngineDrainingError`` -> HTTP 503), let in-flight AND queued
        work finish within ``timeout`` seconds, then fail whatever is
        left with reason ``preempted``. Idempotent; safe from any thread
        (the SIGTERM path calls it off the signal watcher). Returns a
        small summary dict (also emitted as the ``drain`` event)."""
        t0 = time.monotonic()
        already = self._draining
        # deliberately lock-free write: drain's whole reason to exist is
        # the wedged-tick case where self._lock may NEVER be released —
        # a bool store is atomic and readers re-check under real barriers
        self._draining = True                  # graft-ok: GL031 wedge-safe
        if not already:
            self._ev(
                "drain", phase="start", timeout_s=timeout,
                n_active=self.scheduler.n_active,
                queue_depth=len(self.queue))
            logger.warning(
                "Draining: admission closed; finishing %d in-flight + %d "
                "queued request(s) within %.1fs.",
                self.scheduler.n_active, len(self.queue), timeout)
        deadline = t0 + timeout
        if self._thread is not None:
            while (time.monotonic() < deadline
                   and (self.scheduler.n_active or len(self.queue))
                   and self._thread.is_alive()
                   and self._dead is None):
                time.sleep(0.01)
        else:
            # manual mode (no loop thread): we do the ticking ourselves
            while time.monotonic() < deadline and self.step():
                pass
        preempted = 0
        # a WEDGED tick holds self._lock for the whole hung device call —
        # a plain `with self._lock:` here would deadlock the drain (and
        # the SIGTERM exit path behind it) forever, exactly the hang this
        # PR exists to bound. Timed acquire: on timeout, retire the
        # wedged loop via a generation bump (it can never commit state
        # again — every commit point is generation-checked) and sweep the
        # requests without the lock so clients and serve_jsonl unblock.
        lock = self._lock
        lock_wait = min(5.0, max(0.1, timeout))
        locked = lock.acquire(timeout=lock_wait)
        try:
            if not locked:
                logger.error(
                    "Drain: decode tick wedged (lock held > %.1fs); "
                    "abandoning it and force-failing in-flight requests.",
                    lock_wait)
                # edge is infeasible: this branch runs only when the
                # _lock acquire FAILED (wedged tick), and _restart
                # acquires the REPLACEMENT lock, not the abandoned one
                with self._restart_lock:  # graft-ok: GL032 wedge path
                    self._generation += 1
                    # the wedged thread holds the OLD lock forever — give
                    # every later path (shutdown's stats(), submit's
                    # counters) a fresh one or they deadlock behind it
                    self._lock = threading.RLock()
                    self._work = threading.Condition()
            for slot, req in self.scheduler.active():
                self._fail_request(
                    slot, req,
                    f"preempted: drain timeout {timeout}s elapsed",
                    reason="preempted", finish=FINISH_PREEMPTED)
                preempted += 1
            while True:
                req = self.queue.get_nowait()
                if req is None:
                    break
                self._fail_request(
                    None, req,
                    f"preempted: drain timeout {timeout}s elapsed",
                    reason="preempted", finish=FINISH_PREEMPTED)
                preempted += 1
        finally:
            if locked:
                lock.release()
        summary = {"phase": "end", "n_preempted": preempted,
                   "seconds": round(time.monotonic() - t0, 3),
                   "requests_finished": self.requests_finished}
        self._ev("drain", **summary)
        logger.warning("Drain complete in %.2fs (%d preempted).",
                       summary["seconds"], preempted)
        return summary

    def shutdown(self, drain: bool = True) -> None:
        """Stop the engine loop; with ``drain`` (default) finish everything
        queued first. Emits the ``serve_summary`` event with the latency
        histograms' percentiles."""
        if self._thread is not None:
            if drain:
                while ((self.scheduler.n_active or len(self.queue))
                       and self._thread.is_alive()):
                    time.sleep(0.01)
            self._stop.set()
            with self._work:
                self._work.notify_all()
            self._thread.join(timeout=10)
            self._thread = None
        elif drain:
            self.run_until_idle()
        if self.supervisor is not None:
            self.supervisor.stop()
        self._ev("serve_summary", **self.stats())

    def stats(self) -> dict:
        with self._lock:                       # vs a mid-tick _finish()
            out = {
                "requests_finished": self.requests_finished,
                "requests_rejected": self.requests_rejected,
                "requests_failed": self.requests_failed,
                "requests_shed": self.requests_shed,
                "requests_expired": self.requests_expired,
                "tokens_generated": self.tokens_generated,
                "n_ticks": self.n_ticks,
                "n_recompiles": self.n_recompiles,
                "n_restarts": self.n_restarts,
                "draining": self._draining,
            }
            if self.spec_k:
                out["spec_k"] = self.spec_k
                out["spec_tokens_drafted"] = self.spec_tokens_drafted
                out["spec_tokens_accepted"] = self.spec_tokens_accepted
                if self.spec_tokens_drafted:
                    out["spec_acceptance_ratio"] = round(
                        self.spec_tokens_accepted
                        / self.spec_tokens_drafted, 6)
            if self._adapter_counts:
                out["per_adapter"] = {
                    nm: dict(c)
                    for nm, c in sorted(self._adapter_counts.items())}
            if self.adapters is not None:
                out["adapters_loaded"] = self.adapters.n_loaded
            out["kv_policy"] = self.kv_policy.describe()
            out["memory"] = self.memory_ledger.describe()
            if self._paged:
                out["page_pool"] = self.page_pool.stats()
                out["pane_copies"] = self.pane_copies
            if self.prefix_store is not None:
                out["prefix_store"] = self.prefix_store.stats()
            slo = self.slo_window.ratio()
            if slo is not None:
                out["slo_miss_ratio"] = round(slo, 6)
            hists = [("ttft_s", self.ttft_hist),
                     ("tpot_s", self.tpot_hist),
                     ("queue_wait_s", self.queue_wait_hist),
                     ("e2e_s", self.e2e_hist)]
        for name, hist in hists:
            # percentiles are now bucket-interpolated estimates (the
            # histograms are cumulative and never forget a request)
            pct = hist.percentiles((50, 95, 99))
            if pct:
                out[name] = pct
        return out

    def uptime_s(self) -> float:
        return time.monotonic() - self._t_start_mono

    def queue_capacity(self) -> int:
        """Bounded-queue capacity (the 429 payload field) — a method so
        the HTTP frontend reads one surface for engine AND router."""
        return self.queue.max_size

    def metrics_snapshot(self) -> tuple:
        """(counters, gauges, histograms) for the ``/metrics`` exporter
        and the structured ``/healthz`` body. TIMED lock acquire: a
        wedged tick holding the engine lock must not hang the scrape —
        monitoring an incident is precisely when ``/metrics`` has to
        answer (the fields are simple attrs, so a lock-less read during
        a wedge is stale-but-safe)."""
        lock = self._lock
        locked = lock.acquire(timeout=0.5)
        try:
            counters = {
                "requests_finished": self.requests_finished,
                "requests_failed": self.requests_failed,
                "requests_rejected": self.requests_rejected,
                "requests_shed": self.requests_shed,
                "requests_expired": self.requests_expired,
                "tokens_generated": self.tokens_generated,
                "engine_restarts": self.n_restarts,
                "engine_ticks": self.n_ticks,
                "recompiles": self.n_recompiles,
                "tick_busy_seconds": round(self.tick_seconds_total, 6),
            }
            for ph in TICK_PHASES:
                counters[f"tick_{ph}_seconds"] = round(
                    self.tick_phase_totals[ph], 6)
            if self.prefix_store is not None:
                counters["prefix_hits"] = self.prefix_store.n_hits
                counters["prefix_misses"] = self.prefix_store.n_misses
                counters["prefix_evictions"] = \
                    self.prefix_store.n_evictions
                counters["prefix_inserts"] = self.prefix_store.n_inserts
            if self.spec_k:
                counters["spec_tokens_drafted"] = self.spec_tokens_drafted
                counters["spec_tokens_accepted"] = \
                    self.spec_tokens_accepted
            # per-adapter labeled series (multi-tenant accounting): one
            # requests/tokens counter triple per adapter name seen, plus
            # a live per-adapter slot-occupancy gauge
            adapter_active: dict = {}
            for _slot, _req in self.scheduler.active():
                nm = _req.params.adapter or BASE_ADAPTER
                adapter_active[nm] = adapter_active.get(nm, 0) + 1
            for nm, c in sorted(self._adapter_counts.items()):
                lbl = f'{{adapter="{nm}"}}'
                counters[f"adapter_requests_finished{lbl}"] = c["finished"]
                counters[f"adapter_requests_failed{lbl}"] = c["failed"]
                counters[f"adapter_tokens_generated{lbl}"] = c["tokens"]
            gauges = {
                "slot_occupancy": self.scheduler.occupancy(),
                "slots_active": self.scheduler.n_active,
                "slots_total": self.n_slots,
                "queue_depth": len(self.queue),
                "queue_capacity": self.queue.max_size,
                "draining": 1.0 if self._draining else 0.0,
                "engine_up": 0.0 if self._dead is not None else 1.0,
                "uptime_seconds": round(self.uptime_s(), 3),
            }
            for nm, n_act in sorted(adapter_active.items()):
                gauges[f'adapter_slots_active{{adapter="{nm}"}}'] = n_act
            if self.adapters is not None:
                gauges["adapters_loaded"] = self.adapters.n_loaded
                gauges["adapter_capacity"] = self.adapters.capacity
            # KV memory-engine gauges: bytes/slot is the HBM number that
            # sizes n_slots (the int8 policy's whole point); the
            # hit-ratio is the prefix cache's scoreboard
            gauges["kv_bytes_per_slot"] = self.kv_policy.bytes_per_slot(
                self.cfg, self._cache_len)["total_bytes"]
            if self._paged:
                ps = self.page_pool.stats()
                gauges["kv_pages_total"] = ps["n_pages"]
                gauges["kv_pages_used"] = ps["used"]
                gauges["kv_pages_free"] = ps["free"]
                gauges["kv_pages_reserved"] = ps["reserved"]
                gauges["kv_pages_peak_used"] = ps["peak_used"]
                gauges["kv_page_bytes"] = ps["page_bytes"]
            if self.spec_k:
                # acceptance ratio is THE drafter-quality dial: low ratio
                # means the verify widths are wasted compute — shrink k
                # or disable spec for the workload (README guidance)
                gauges["spec_k"] = self.spec_k
                gauges["spec_acceptance_ratio"] = round(
                    self.spec_tokens_accepted
                    / max(self.spec_tokens_drafted, 1), 6)
            if self.prefix_store is not None:
                ratio = self.prefix_store.hit_ratio()
                gauges["prefix_hit_ratio"] = (round(ratio, 6)
                                              if ratio is not None else 0.0)
                gauges["prefix_entries"] = self.prefix_store.n_entries
                gauges["prefix_bytes"] = self.prefix_store.bytes_total
            # memory observatory: refresh the ledger from the live
            # arrays (metadata math — safe under the timed lock) and
            # export the component/watermark/attribution series; the
            # fleet scrape path relabels these per worker automatically
            self.memory_ledger.snapshot()
            gauges.update(self.memory_ledger.gauges())
            # always exported: a scrape gap (series absent until the
            # first deadline-carrying request) reads as "no data" on a
            # dashboard when the truth is "no misses"
            slo = self.slo_window.ratio()
            gauges["slo_miss_ratio"] = round(slo, 6) if slo is not None \
                else 0.0
            hists = {
                "ttft_seconds": self.ttft_hist,
                "tpot_seconds": self.tpot_hist,
                "queue_wait_seconds": self.queue_wait_hist,
                "e2e_seconds": self.e2e_hist,
                "tick_prefill_seconds": self.tick_prefill_hist,
            }
        finally:
            if locked:
                lock.release()
        return counters, gauges, hists

    def prometheus_text(self) -> str:
        """The ``GET /metrics`` body (Prometheus text exposition 0.0.4)."""
        counters, gauges, hists = self.metrics_snapshot()
        return render_prometheus(counters, gauges, hists,
                                 prefix="bllm_serve_")

    def healthz_payload(self) -> dict:
        """The ``GET /healthz`` body — one method so the single-engine
        frontend and the router's per-replica fleet view can't drift."""
        if self._dead is not None:
            status = "dead"
        elif self.draining:
            status = "draining"
        else:
            status = "serving"
        counters, gauges, _ = self.metrics_snapshot()
        return {
            # original fields (kept for compatibility)
            "status": status,
            "slots": self.n_slots,
            "active": self.scheduler.n_active,
            "queue_depth": len(self.queue),
            "queue_capacity": self.queue.max_size,
            "warmed_up": self.warmed_up,
            "draining": self.draining,
            "restarts": self.n_restarts,
            # structured snapshot (one probe answers "how is it
            # doing", not just "is it up")
            "uptime_s": round(self.uptime_s(), 3),
            "n_ticks": counters["engine_ticks"],
            "occupancy": self.scheduler.occupancy(),
            "slo_miss_ratio": gauges.get("slo_miss_ratio"),
            "counters": counters,
        }


def service_estimate(queue_depth: int, n_active: int, n_slots: int,
                     tpot_ewma: Optional[float],
                     tokens_ewma: Optional[float],
                     max_new_tokens: int) -> Optional[float]:
    """THE SLO completion estimate (pure): predicted submit->finish
    seconds given a backlog and the live service EWMAs. Shared by
    ``DecodeEngine.estimate_completion_s`` (per-engine shed) and the
    fleet router's dispatch scoring — one formula, so fleet admission
    and per-engine shed can never disagree on what a predicted miss is.
    None without service history (admission stays optimistic)."""
    if tpot_ewma is None or tokens_ewma is None:
        return None
    per_request = tokens_ewma * tpot_ewma
    backlog = queue_depth + 0.5 * n_active
    wait = (backlog / max(n_slots, 1)) * per_request
    return wait + max_new_tokens * tpot_ewma


def queue_clear_estimate(queue_depth: int, n_active: int, n_slots: int,
                         tpot_ewma: Optional[float],
                         tokens_ewma: Optional[float]
                         ) -> Optional[float]:
    """Rough seconds until a backlog drains (Retry-After material) —
    the pure sibling of ``service_estimate``, shared with the router."""
    if tpot_ewma is None or tokens_ewma is None:
        return None
    per_request = tokens_ewma * tpot_ewma
    backlog = queue_depth + n_active
    return round((backlog / max(n_slots, 1)) * per_request, 3)


def _prng_key(seed: int):
    import jax

    return jax.random.PRNGKey(seed)
