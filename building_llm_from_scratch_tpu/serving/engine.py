"""Continuous-batching decode engine (Orca-style, slot-scheduled).

One fixed ``(n_slots, Tmax)`` KV cache; requests are admitted into free
slots at step boundaries and retired the moment they finish, so XLA
compiles exactly ONE decode program (and one prefill per prompt-length
bucket) no matter how traffic arrives. The host loop per tick:

    retire finished -> admit queued into free slots (prefill, bucketed)
    -> one fused decode step for ALL slots (per-slot masks) -> stream

Slot independence is total: every row carries its own length, sampling
params and PRNG stream (``generate.token_rng`` fold-in on the request
seed), so a request's tokens are identical whether it runs alone, in any
slot, or next to arbitrary co-batched traffic — and identical to the
one-shot ``generate()`` path (test-pinned).

Telemetry (obs/metrics.py sink): per-request ``request_done`` events with
queue-wait/TTFT/TPOT, slot-occupancy + queue-depth gauges, periodic
``metrics`` rows with the decode token rate, and compile/recompile events
from the ``CompileWatcher``-wrapped prefill/decode programs — after
warmup, a prompt outside the warmed bucket set surfaces as a ``recompile``
event with the leaf diff instead of a silent latency cliff.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence, Union

import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import (
    _bucket,
    sample_tokens_dynamic,
    token_rng,
)
from building_llm_from_scratch_tpu.models.transformer import (
    decode_slots,
    init_slot_cache,
    prefill_into_slot,
    unstack_blocks,
)
from building_llm_from_scratch_tpu.obs.compile import CompileWatcher
from building_llm_from_scratch_tpu.obs.metrics import get_metrics
from building_llm_from_scratch_tpu.serving.queue import (
    QueueFullError,
    RequestQueue,
)
from building_llm_from_scratch_tpu.serving.request import (
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISHED,
    REJECTED,
    RUNNING,
    Request,
    SamplingParams,
    next_request_id,
    resolve_eos,
)
from building_llm_from_scratch_tpu.serving.scheduler import Scheduler
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


def _percentiles(values: Sequence[float], ps=(50, 95, 99)) -> dict:
    if not values:
        return {}
    arr = np.asarray(values, np.float64)
    return {f"p{p}": round(float(np.percentile(arr, p)), 6) for p in ps}


class DecodeEngine:
    """The serving runtime: slot-batched KV cache + request lifecycle.

    Drive it either manually (``step()`` / ``run_until_idle()`` — what the
    deterministic tests do) or with the background thread
    (``start()`` / ``shutdown()`` — what the frontends do). ``submit()``
    is thread-safe either way.
    """

    def __init__(self, cfg: ModelConfig, params, tokenizer=None, *,
                 n_slots: int = 4, max_len: Optional[int] = None,
                 max_queue: int = 64, max_top_k: int = 64,
                 default_max_new_tokens: int = 128,
                 warmup_prompt_cap: int = 256, metrics_every: int = 32,
                 watch_compiles: bool = True):
        import jax

        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.n_slots = int(n_slots)
        self.max_len = min(int(max_len or cfg.context_length),
                           cfg.context_length)
        self.max_top_k = min(int(max_top_k), cfg.vocab_size)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.warmup_prompt_cap = min(int(warmup_prompt_cap), self.max_len)
        self.metrics_every = int(metrics_every)

        self.queue = RequestQueue(max_queue)
        self.scheduler = Scheduler(self.n_slots)
        self.cache = init_slot_cache(cfg, self.n_slots, self.max_len)
        self._blocks = unstack_blocks(params, cfg)

        S = self.n_slots
        # host-owned per-slot state; the device owns only the big k/v.
        # PRNG key width depends on the configured impl (threefry (2,),
        # rbg (4,)) — probe it instead of assuming
        probe_key = np.asarray(_prng_key(0))
        self._lengths = np.zeros((S,), np.int32)
        self._last_tokens = np.zeros((S,), np.int32)
        self._n_gen = np.zeros((S,), np.int32)
        self._base_keys = np.zeros((S,) + probe_key.shape, probe_key.dtype)
        self._temps = np.zeros((S,), np.float32)
        self._topks = np.zeros((S,), np.int32)

        # donate the cache panes: the caller always rebinds self.cache to
        # the outputs, so XLA may alias input->output and the pallas
        # in-place append really is in place (no per-tick full-cache copy)
        prefill_jit = jax.jit(self._prefill_impl, donate_argnums=(0, 1))
        decode_jit = jax.jit(self._decode_impl, donate_argnums=(0, 1))
        if watch_compiles:
            self._prefill = CompileWatcher(prefill_jit,
                                           label="serve_prefill",
                                           multi_program=True)
            self._decode = CompileWatcher(decode_jit, label="serve_decode",
                                          multi_program=True)
        else:
            self._prefill = prefill_jit
            self._decode = decode_jit

        self._lock = threading.RLock()
        self._work = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._dead: Optional[str] = None        # set by _fail_all
        self.warmed_up = False

        # rolling serve accounting (histogram material for request_done /
        # serve_summary events and the frontends' reports); bounded so a
        # long-running deployment holds the most recent window, not every
        # request ever served
        self.n_ticks = 0
        self.tokens_generated = 0
        self.requests_finished = 0
        self.requests_rejected = 0
        self.ttft_hist = collections.deque(maxlen=self._HIST_MAX)
        self.tpot_hist = collections.deque(maxlen=self._HIST_MAX)
        self.queue_wait_hist = collections.deque(maxlen=self._HIST_MAX)
        self.e2e_hist = collections.deque(maxlen=self._HIST_MAX)
        self._window_tokens = 0
        self._window_t0 = time.monotonic()

    # -- jitted programs (close over params/cfg/blocks so per-tick call
    # signatures carry only the small mutable state + caches) -------------

    def _prefill_impl(self, cache_k, cache_v, tokens, prompt_len, slot,
                      base_key, temp, topk):
        import jax.numpy as jnp

        logits, cache = prefill_into_slot(
            self.params, self.cfg, tokens, prompt_len, slot,
            {"k": cache_k, "v": cache_v}, self._blocks)
        key0 = token_rng(base_key, 0)
        tok = sample_tokens_dynamic(
            logits[None], key0[None], jnp.reshape(temp, (1,)),
            jnp.reshape(topk, (1,)), self.max_top_k)[0]
        return tok, cache["k"], cache["v"]

    def _decode_impl(self, cache_k, cache_v, tokens, lengths, base_keys,
                     n_gen, temps, topks):
        import jax

        logits, cache = decode_slots(
            self.params, self.cfg, tokens[:, None], lengths,
            {"k": cache_k, "v": cache_v}, self._blocks)
        keys = jax.vmap(token_rng)(base_keys, n_gen)
        nxt = sample_tokens_dynamic(logits, keys, temps, topks,
                                    self.max_top_k)
        return nxt, cache["k"], cache["v"]

    # -- admission --------------------------------------------------------

    def _bucket_len(self, n: int) -> int:
        return min(_bucket(n), self.max_len)

    def prompt_buckets(self) -> List[int]:
        """The prompt-length buckets warmup compiles (one prefill program
        each): every bucket value up to ``warmup_prompt_cap``. Prompts
        longer than the cap still work — their first arrival pays a
        compile, which the frozen watcher reports as a ``recompile``
        (bucket miss)."""
        vals = {self._bucket_len(1)}
        b = 64
        while b <= self.warmup_prompt_cap:
            vals.add(self._bucket_len(b))
            b += 64
        # the clamped terminal bucket: when max_len is not a multiple of
        # 64 the loop above never reaches it, yet in-capacity prompts
        # bucket there (e.g. max_len=48 -> bucket 48)
        vals.add(self._bucket_len(self.warmup_prompt_cap))
        return sorted(vals)

    def encode_prompt(self, prompt: Union[str, Sequence[int], np.ndarray]
                      ) -> np.ndarray:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("text prompt needs a tokenizer")
            ids = self.tokenizer.encode(prompt)
        else:
            ids = prompt
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        return ids

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, timeout: Optional[float] = None,
               on_token=None) -> Request:
        """Enqueue one request (thread-safe). ``block=False`` rejects with
        ``QueueFullError`` when the bounded queue is at capacity;
        ``block=True`` waits for space (backpressure)."""
        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        params = params or SamplingParams()
        ids = self.encode_prompt(prompt)
        if params.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if params.top_k is not None and not (
                1 <= params.top_k <= self.max_top_k):
            raise ValueError(
                f"top_k={params.top_k} outside this engine's compiled "
                f"capacity 1..{self.max_top_k} (raise max_top_k)")
        total = int(ids.size) + params.max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens "
                f"({params.max_new_tokens}) = {total} exceeds the "
                f"engine's slot capacity {self.max_len}")
        req = Request(next_request_id(), ids, params, on_token=on_token)
        try:
            self.queue.put(req, block=block, timeout=timeout)
        except QueueFullError:
            req.state = REJECTED
            with self._lock:                   # submit() is thread-safe
                self.requests_rejected += 1
            get_metrics().event("request_rejected", request_id=req.id,
                                queue_depth=len(self.queue))
            req._mark_done()
            raise
        if self._dead is not None:
            # raced _fail_all: a blocked put() can be woken by the death
            # drain and append into the dead engine — nothing will ever
            # process it, so fail it here instead of hanging result()
            req.error = self._dead
            req.finish_reason = FINISH_ERROR
            req.state = FINISHED
            req._mark_done()
            raise RuntimeError(f"engine is dead: {self._dead}")
        with self._work:
            self._work.notify()
        return req

    def _admit(self, slot: int, req: Request) -> None:
        Tp = int(req.prompt_ids.size)
        Tpb = self._bucket_len(Tp)
        padded = np.zeros((1, Tpb), np.int32)
        padded[0, :Tp] = req.prompt_ids
        base_key = np.asarray(_prng_key(req.params.seed))
        temp = np.float32(req.params.temperature)
        topk = np.int32(req.params.top_k or 0)
        tok, k, v = self._prefill(self.cache["k"], self.cache["v"], padded,
                                  np.int32(Tp), np.int32(slot), base_key,
                                  temp, topk)
        self.cache = {"k": k, "v": v}
        req.state = RUNNING
        req.slot = slot
        req.t_admit = time.monotonic()
        self._lengths[slot] = Tp
        self._n_gen[slot] = 0
        self._base_keys[slot] = base_key
        self._temps[slot] = temp
        self._topks[slot] = topk
        self._accept_token(slot, req, int(tok))

    # -- the tick ---------------------------------------------------------

    def step(self) -> bool:
        """One engine tick: admit into free slots, then one fused decode
        step over the slot batch. Returns False when fully idle (no active
        slots and nothing queued)."""
        with self._lock:
            # re-run admission until no progress: a request can finish
            # DURING admission (eos on its first sampled token, or
            # max_new_tokens=1), freeing its slot after admit_from already
            # returned — without the retry those queued behind it would
            # strand (step() would report idle with a non-empty queue)
            while True:
                admitted = self.scheduler.admit_from(self.queue)
                for slot, req in admitted:
                    self._admit(slot, req)
                if not admitted:
                    break
            active = self.scheduler.active()
            if not active:
                # all slots free => admission drained the queue too
                return False
            nxt, k, v = self._decode(
                self.cache["k"], self.cache["v"], self._last_tokens,
                self._lengths, self._base_keys, self._n_gen, self._temps,
                self._topks)
            self.cache = {"k": k, "v": v}
            nxt = np.asarray(nxt)
            for slot, req in active:
                # this tick wrote the slot's previous token at _lengths
                self._lengths[slot] += 1
                self._accept_token(slot, req, int(nxt[slot]))
            self.n_ticks += 1
            self._maybe_log_metrics()
            return True

    def run_until_idle(self) -> None:
        while self.step():
            pass

    def _accept_token(self, slot: int, req: Request, tok: int) -> None:
        eos = resolve_eos(req.params, self.cfg.eos_id)
        if eos is not None and tok == eos:
            # the triggering eos is dropped (generate()'s per-row
            # semantics) and the slot frees this boundary
            self._finish(slot, req, FINISH_EOS)
            return
        now = time.monotonic()
        if req.t_first_token is None:
            req.t_first_token = now
        req.output_ids.append(tok)
        self._last_tokens[slot] = tok
        self._n_gen[slot] = len(req.output_ids)
        self.tokens_generated += 1
        self._window_tokens += 1
        piece = self._detok_piece(req)
        if req.on_token is not None:
            req.on_token(req, tok, piece)
        if piece:
            req._push_piece(piece)
        if len(req.output_ids) >= req.params.max_new_tokens:
            self._finish(slot, req, FINISH_LENGTH)

    #: per-histogram cap: serve_summary percentiles cover the most recent
    #: window of finished requests at O(1) memory
    _HIST_MAX = 8192

    #: max tokens a partial multi-byte char may hold back detokenization
    #: before committing anyway (bounds the re-decoded tail per token)
    _DETOK_HOLD_MAX = 16

    def _detok_piece(self, req: Request, final: bool = False) -> str:
        """Incremental detokenization: decode only the uncommitted tail
        (O(tail) per token, not O(total)). A tail ending in a replacement
        char is a partial multi-byte sequence the next token may complete
        — hold it (return "") rather than commit a mangled boundary,
        up to ``_DETOK_HOLD_MAX`` tokens; ``final`` flushes regardless."""
        if self.tokenizer is None:
            return ""
        tail_ids = req.output_ids[req._detok_start:]
        if not tail_ids:
            return ""
        try:
            tail = self.tokenizer.decode([int(t) for t in tail_ids])
        except Exception:                      # partial byte sequences etc.
            return ""
        if (not final and tail.endswith("�")
                and len(tail_ids) < self._DETOK_HOLD_MAX):
            return ""
        req.text += tail
        req._detok_start = len(req.output_ids)
        return tail

    def _finish(self, slot: int, req: Request, reason: str) -> None:
        tail = self._detok_piece(req, final=True)  # flush any held bytes
        if tail:
            req._push_piece(tail)
        req.state = FINISHED
        req.finish_reason = reason
        req.t_finish = time.monotonic()
        self.scheduler.retire(slot)
        self._lengths[slot] = 0
        self._last_tokens[slot] = 0
        self._n_gen[slot] = 0
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        self.requests_finished += 1
        for hist, val in ((self.ttft_hist, req.ttft_s()),
                          (self.tpot_hist, req.tpot_s()),
                          (self.queue_wait_hist, req.queue_wait_s()),
                          (self.e2e_hist, req.e2e_s())):
            if val is not None:
                hist.append(val)
        sink = get_metrics()
        sink.event("request_done", **req.summary())
        sink.gauge("slot_occupancy", self.scheduler.occupancy())
        sink.gauge("queue_depth", len(self.queue))
        req._mark_done()
        with self._work:
            self._work.notify_all()

    def _maybe_log_metrics(self) -> None:
        if self.metrics_every <= 0 or self.n_ticks % self.metrics_every:
            return
        now = time.monotonic()
        dt = max(now - self._window_t0, 1e-9)
        sink = get_metrics()
        sink.gauge("slot_occupancy", self.scheduler.occupancy())
        sink.gauge("queue_depth", len(self.queue))
        sink.log_metrics(self.n_ticks,
                         serve_tok_s=round(self._window_tokens / dt, 2),
                         requests_finished=self.requests_finished,
                         tokens_generated=self.tokens_generated)
        self._window_tokens = 0
        self._window_t0 = now

    # -- warmup / compile discipline --------------------------------------

    def warmup(self) -> None:
        """Compile the legitimate program set up front — one prefill per
        prompt bucket + THE decode step — then freeze the watchers so any
        later signature is reported as a bucket-miss ``recompile``. The
        warmup traffic runs through slot 0 with throwaway state; host
        state is reset after."""
        t0 = time.monotonic()
        buckets = self.prompt_buckets()
        zero_key = np.zeros_like(self._base_keys[0])
        for Tpb in buckets:
            dummy = np.zeros((1, Tpb), np.int32)
            tok, k, v = self._prefill(
                self.cache["k"], self.cache["v"], dummy, np.int32(1),
                np.int32(0), zero_key, np.float32(0.0), np.int32(0))
            self.cache = {"k": k, "v": v}
        nxt, k, v = self._decode(
            self.cache["k"], self.cache["v"], self._last_tokens,
            self._lengths, self._base_keys, self._n_gen, self._temps,
            self._topks)
        self.cache = {"k": k, "v": v}
        np.asarray(nxt)                       # block until compiled + ran
        if isinstance(self._prefill, CompileWatcher):
            self._prefill.freeze()
            self._decode.freeze()
        self._lengths[:] = 0
        self._last_tokens[:] = 0
        self._n_gen[:] = 0
        self.warmed_up = True
        get_metrics().event(
            "serve_warmup", n_prefill_buckets=len(buckets),
            buckets=buckets, seconds=round(time.monotonic() - t0, 3),
            n_slots=self.n_slots, max_len=self.max_len)
        logger.info("Serving warmup: %d prefill buckets %s + 1 decode "
                    "program in %.2fs", len(buckets), buckets,
                    time.monotonic() - t0)

    @property
    def n_recompiles(self) -> int:
        if isinstance(self._decode, CompileWatcher):
            return self._decode.n_recompiles + self._prefill.n_recompiles
        return 0

    # -- background loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    progressed = self.step()
                except Exception as e:          # noqa: BLE001 — must not
                    # die silently: callers block on result() forever and
                    # shutdown(drain=True) spins if requests just vanish
                    logger.exception("decode-engine loop died")
                    self._fail_all(f"engine loop error: {e!r}")
                    return
                if not progressed:
                    with self._work:
                        self._work.wait(timeout=0.05)

        self._thread = threading.Thread(target=loop, name="decode-engine",
                                        daemon=True)
        self._thread.start()

    def _fail_all(self, msg: str) -> None:
        """Fail every in-flight and queued request (engine loop death):
        set ``req.error`` so ``result()`` raises instead of hanging.
        Marks the engine dead — later ``submit()`` calls raise."""
        with self._lock:
            self._dead = msg
            failed = 0
            for slot, req in self.scheduler.active():
                req.error = msg
                req.finish_reason = FINISH_ERROR
                req.state = FINISHED
                self.scheduler.retire(slot)
                req._mark_done()
                failed += 1
            while True:
                req = self.queue.get_nowait()
                if req is None:
                    break
                req.error = msg
                req.finish_reason = FINISH_ERROR
                req.state = FINISHED
                req._mark_done()
                failed += 1
            get_metrics().event("serve_error", error=msg, n_failed=failed)
        with self._work:
            self._work.notify_all()

    def shutdown(self, drain: bool = True) -> None:
        """Stop the engine loop; with ``drain`` (default) finish everything
        queued first. Emits the ``serve_summary`` event with the latency
        histograms' percentiles."""
        if self._thread is not None:
            if drain:
                while ((self.scheduler.n_active or len(self.queue))
                       and self._thread.is_alive()):
                    time.sleep(0.01)
            self._stop.set()
            with self._work:
                self._work.notify_all()
            self._thread.join(timeout=10)
            self._thread = None
        elif drain:
            self.run_until_idle()
        get_metrics().event("serve_summary", **self.stats())

    def stats(self) -> dict:
        with self._lock:                       # vs a mid-tick _finish()
            out = {
                "requests_finished": self.requests_finished,
                "requests_rejected": self.requests_rejected,
                "tokens_generated": self.tokens_generated,
                "n_ticks": self.n_ticks,
                "n_recompiles": self.n_recompiles,
            }
            hists = [("ttft_s", list(self.ttft_hist)),
                     ("tpot_s", list(self.tpot_hist)),
                     ("queue_wait_s", list(self.queue_wait_hist)),
                     ("e2e_s", list(self.e2e_hist))]
        for name, hist in hists:
            pct = _percentiles(hist)
            if pct:
                out[name] = pct
        return out


def _prng_key(seed: int):
    import jax

    return jax.random.PRNGKey(seed)
