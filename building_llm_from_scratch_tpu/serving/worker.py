"""The fleet's worker: one replica engine behind a process boundary.

Three layers, shared by production serving (``serving/fleet.py``), the
bench harness (``scripts/bench_fleet_worker.py``), and the tests:

  - ``EngineSpec`` + ``apply_host_env`` + ``build_engine`` — a
    JSON-serializable recipe for rebuilding the SAME engine in another
    process. Params are reconstructed, not shipped: ``init_params(cfg,
    PRNGKey(seed))`` is deterministic, and ``init_params_from`` loads an
    exported checkpoint — either way every worker holds identical
    weights, which is what makes prefix-pane keys (config-fingerprinted)
    portable across the fleet.
  - ``FakeEngine`` — a jax-free engine stand-in with the same
    worker-facing surface (bounded queue, slot concurrency, typed
    admission errors, drain semantics, optionally a REAL ``PrefixStore``
    over deterministic numpy panes). Fault-injection tests exercise the
    whole transport/supervisor/kill-9/handoff machinery in milliseconds
    instead of compile-seconds.
  - ``WorkerServer`` + ``main`` — the subprocess entrypoint: an
    ``RpcServer`` on a unix socket (submit/adopt/cancel/steal_queue/
    drain/healthz/export_panes/import_panes/...), an event-push channel
    (heartbeats + per-request admitted/piece/done/failed), its own
    metrics JSONL, and a clean SIGTERM drain. Stdout carries exactly one
    ready line and then stays open: the supervisor reads EOF on it as a
    death signal no heartbeat timeout can beat.

Import-light on purpose: jax is imported only inside ``build_engine``,
so the supervisor (and fake-mode workers) never pay for — or depend
on — an accelerator runtime.
"""

from __future__ import annotations

import argparse
import base64
import dataclasses
import json
import os
import queue as _stdqueue
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from building_llm_from_scratch_tpu.obs.metrics import (
    configure_metrics,
    get_metrics,
)
from building_llm_from_scratch_tpu.serving.kvcache import (
    KVCachePolicy,
    PrefixStore,
    cache_nbytes,
)
from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    RequestQueue,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import (
    FINISH_CANCELLED,
    FINISH_EXPIRED,
    FINISH_LENGTH,
    FINISH_PREEMPTED,
    FINISHED,
    RUNNING,
    Request,
    SamplingParams,
    next_request_id,
    seed_request_ids,
)
from building_llm_from_scratch_tpu.serving.transport import (
    DETACH,
    RpcServer,
    RpcStats,
    TransportError,
    send_frame,
)
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


# ---------------------------------------------------------------------------
# the engine recipe
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EngineSpec:
    """Everything a worker process needs to rebuild its replica engine.

    ``engine`` holds ``DecodeEngine`` keyword arguments (n_slots,
    max_len, max_queue, ...); ``kv_policy`` holds ``KVCachePolicy``
    fields; ``fake`` non-None selects the jax-free ``FakeEngine`` (its
    constructor kwargs). The whole spec round-trips through JSON — it IS
    the worker's command line.
    """

    model: str = "GPT2"
    size: str = "124M"
    dtype: str = "auto"              # "auto" = bf16 on tpu else fp32
    debug: bool = False
    seed: int = 0
    init_params_from: Optional[str] = None
    tokenizer: str = "none"          # "byte" | "none"
    devices: int = 1                 # forced-host CPU device count
    tp: int = 1
    engine: Dict[str, Any] = dataclasses.field(default_factory=dict)
    kv_policy: Optional[Dict[str, Any]] = None
    adapters: Optional[Dict[str, str]] = None     # name -> npz path
    spec_k: int = 0
    fake: Optional[Dict[str, Any]] = None

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "EngineSpec":
        return cls(**json.loads(s))


def apply_host_env(devices: int, platform: str = "cpu") -> None:
    """Force-host device count + platform env, BEFORE jax imports.

    Each worker process pins its own device count (the bench's
    subprocess trick, now the fleet's default): the parent's jax — if
    any — is untouched.
    """
    if devices > 1:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={devices}").strip()
    os.environ.setdefault("JAX_PLATFORMS", platform)


def build_engine(spec: EngineSpec, replica: Optional[int] = None):
    """Rebuild the replica engine a spec describes (jax imported here)."""
    if spec.fake is not None:
        return FakeEngine(**spec.fake)

    import jax

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving.engine import DecodeEngine

    dtype = spec.dtype
    if dtype == "auto":
        dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config(spec.model, spec.size, dtype=dtype, debug=spec.debug)
    params = init_params(cfg, jax.random.PRNGKey(spec.seed))
    if spec.init_params_from:
        from building_llm_from_scratch_tpu.training.checkpoint import (
            load_exported_params,
        )

        params = load_exported_params(spec.init_params_from, params)

    tokenizer = None
    if spec.tokenizer == "byte":
        from building_llm_from_scratch_tpu.data.tokenizers import (
            ByteTokenizer,
        )

        tokenizer = ByteTokenizer()

    mesh_plan = None
    if spec.tp > 1:
        from building_llm_from_scratch_tpu.parallel import build_mesh_plan

        mesh_plan = build_mesh_plan("tp", tp=spec.tp)

    adapters = None
    if spec.adapters:
        from building_llm_from_scratch_tpu.serving.adapters import (
            AdapterRegistry,
        )

        adapters = AdapterRegistry(cfg, params)
        for name, path in spec.adapters.items():
            adapters.load(name, path)

    kv_policy = (KVCachePolicy(**spec.kv_policy)
                 if spec.kv_policy else None)
    return DecodeEngine(cfg, params, tokenizer,
                        adapters=adapters, kv_policy=kv_policy,
                        spec_k=spec.spec_k, mesh_plan=mesh_plan,
                        replica=replica, **spec.engine)


# ---------------------------------------------------------------------------
# pane serialization (prefix handoff)
# ---------------------------------------------------------------------------

def encode_panes(panes: Any) -> Any:
    """Pane pytree -> JSON-able tree (arrays as base64 + dtype + shape).

    ``np.asarray`` pulls device arrays to host; byte-exactness is the
    contract the handoff test asserts."""
    if isinstance(panes, dict):
        return {k: encode_panes(v) for k, v in panes.items()}
    arr = np.asarray(panes)
    return {"__nd__": base64.b64encode(
                np.ascontiguousarray(arr).tobytes()).decode("ascii"),
            "dtype": arr.dtype.str, "shape": list(arr.shape)}


def decode_panes(tree: Any) -> Any:
    if isinstance(tree, dict) and "__nd__" in tree:
        arr = np.frombuffer(
            base64.b64decode(tree["__nd__"]),
            dtype=np.dtype(tree["dtype"])).reshape(tree["shape"])
        return arr.copy()                      # writable, owns its bytes
    return {k: decode_panes(v) for k, v in tree.items()}


# ---------------------------------------------------------------------------
# the jax-free engine stand-in
# ---------------------------------------------------------------------------

class FakeEngine:
    """A decode engine with the physics removed.

    Same worker-facing surface and admission semantics as
    ``DecodeEngine`` (bounded queue -> ``QueueFullError``, drain ->
    ``EngineDrainingError``, slot-limited concurrency, per-token
    ``on_token`` callbacks, terminal finish reasons) but tokens are a
    deterministic function of the prompt and each costs ``tpot_s`` of
    wall time. With ``prefix_chunk > 0`` it runs a REAL ``PrefixStore``
    whose panes are a pure function of the prefix tokens — so pane
    handoff is byte-checkable without a model.
    """

    def __init__(self, *, n_slots: int = 2, max_queue: int = 16,
                 tpot_s: float = 0.01, default_max_new_tokens: int = 16,
                 prefix_chunk: int = 0,
                 prefix_budget_bytes: int = 8 * 1024 * 1024,
                 vocab_size: int = 96):
        self.n_slots = int(n_slots)
        self.queue = RequestQueue(max_queue)
        self.tpot_s = float(tpot_s)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.vocab_size = int(vocab_size)
        self.warmed_up = True
        self.n_recompiles = 0
        self.n_restarts = 0
        self._draining = False
        self._dead: Optional[str] = None
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._active: List[Request] = []               # guarded-by: _lock
        self._finished = 0                             # guarded-by: _lock
        self._failed = 0                               # guarded-by: _lock
        self._ticks = 0                                # guarded-by: _lock
        self.prefix_store = (PrefixStore(
            "fake-engine", chunk_tokens=prefix_chunk,
            budget_bytes=prefix_budget_bytes,
            pane_tokens=4 * prefix_chunk)
            if prefix_chunk > 0 else None)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def warmup(self) -> None:
        pass

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="fake-decode", daemon=True)
            self._thread.start()

    def shutdown(self, drain: bool = True) -> None:
        if drain and not self._draining:
            self.drain(timeout=5.0)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def drain(self, timeout: float = 30.0) -> dict:
        self._draining = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                idle = not self._active and len(self.queue) == 0
            if idle:
                break
            time.sleep(0.002)
        preempted = 0
        while True:                       # whatever is left gets failed
            req = self.queue.get_nowait()
            if req is None:
                break
            self._finish(req, FINISH_PREEMPTED, error="drain timeout")
            preempted += 1
        with self._lock:
            leftovers = list(self._active)
        for req in leftovers:
            self._finish(req, FINISH_PREEMPTED, error="drain timeout")
            preempted += 1
        return {"preempted": preempted}

    def run_until_idle(self) -> None:
        while True:
            with self._lock:
                if not self._active and len(self.queue) == 0:
                    return
            time.sleep(0.002)

    # -- admission ---------------------------------------------------------

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, timeout: Optional[float] = None,
               on_token=None, route=None) -> Request:
        if self._draining:
            raise EngineDrainingError("engine is draining",
                                      retry_after_s=1.0)
        params = params or SamplingParams(
            max_new_tokens=self.default_max_new_tokens)
        if params.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if params.deadline_s is not None:
            # deadline-aware admission, FakeEngine style: the decode cost
            # is exactly max_new_tokens ticks of tpot_s, so a deadline
            # below that is a predicted miss — shed now (mirrors
            # DecodeEngine's TPOT-EWMA estimate, deterministic here)
            est = params.max_new_tokens * self.tpot_s
            if params.deadline_s < est:
                raise SLOShedError(
                    f"deadline {params.deadline_s:.3f}s < estimated "
                    f"decode {est:.3f}s", retry_after_s=est)
        prompt_ids = np.asarray(prompt, np.int32).reshape(-1)
        req = Request(next_request_id(), prompt_ids, params, on_token)
        req.route = route
        self.queue.put(req, block=block, timeout=timeout)
        return req

    def adopt(self, req: Request, timeout: float = 5.0) -> None:
        if self._dead is not None:
            raise RuntimeError(f"engine is dead: {self._dead}")
        if self._draining:
            raise EngineDrainingError("engine is draining: "
                                      "admission closed")
        self.queue.put(req, block=True, timeout=timeout)

    def cancel(self, req: Request) -> bool:
        if self.queue.remove(req):
            self._finish(req, FINISH_CANCELLED, error="cancelled")
            return True
        with self._lock:
            if req in self._active:
                req._cancelled = True
                return True
        return False

    # -- the "decode" loop -------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            expired: List[Request] = []
            with self._lock:
                while len(self._active) < self.n_slots:
                    req = self.queue.get_nowait()
                    if req is None:
                        break
                    if req.expired():
                        # queue-TTL shed at the admission boundary —
                        # finishing outside the lock (_finish re-takes it)
                        expired.append(req)
                        continue
                    self._admit_locked(req)
                active = list(self._active)
            for req in expired:
                self._finish(req, FINISH_EXPIRED,
                             error="deadline expired in queue")
            if not active:
                time.sleep(0.002)
                continue
            time.sleep(self.tpot_s)
            with self._lock:
                self._ticks += 1
            for req in active:
                self._step(req)

    # holds: _lock
    def _admit_locked(self, req: Request) -> None:
        req.t_admit = time.monotonic()
        req.state = RUNNING
        req.slot = len(self._active)
        self._active.append(req)
        if self.prefix_store is not None:
            self._prefix_probe(req)

    def _prefix_probe(self, req: Request) -> None:
        """Real PrefixStore traffic over deterministic panes: a hit
        reuses the stored pane (and counts), a miss computes + inserts —
        the handoff test's donor/adoptee behavior without a model."""
        store = self.prefix_store
        span = store.storable_span(len(req.prompt_ids))
        if span <= 0:
            return
        tag = req.params.adapter or ""
        hit_span, entry = store.match(req.prompt_ids, tag)
        if entry is not None:
            get_metrics().event("prefix_hit", request_id=req.id,
                                span_tokens=hit_span,
                                prompt_tokens=int(len(req.prompt_ids)))
            store.release(entry)
            return
        get_metrics().event("prefix_miss", request_id=req.id,
                            prompt_tokens=int(len(req.prompt_ids)))
        store.insert(req.prompt_ids[:span], tag,
                     self._panes_for(req.prompt_ids[:span]))

    @staticmethod
    def _panes_for(token_ids) -> Dict[str, np.ndarray]:
        """Byte-deterministic pane tree: a pure function of the tokens,
        so donor-computed and locally-computed panes are identical."""
        ids = np.asarray(token_ids, np.float32)
        return {"k": (ids * 0.5 + 1.0).reshape(1, 1, -1, 1),
                "v": (ids * 0.25 - 2.0).reshape(1, 1, -1, 1)}

    def _step(self, req: Request) -> None:
        if req.done:
            return
        if req._cancelled:
            self._finish(req, FINISH_CANCELLED, error="cancelled")
            return
        tok = int((int(req.prompt_ids[-1]) + len(req.output_ids))
                  % self.vocab_size)
        if req.t_first_token is None:
            req.t_first_token = time.monotonic()
        req.output_ids.append(tok)
        piece = chr(0x20 + tok % 94)
        req.text += piece
        if req.on_token is not None:
            req.on_token(req, tok, piece)
        if len(req.output_ids) >= req.params.max_new_tokens:
            self._finish(req, FINISH_LENGTH)

    def _finish(self, req: Request, reason: str,
                error: Optional[str] = None) -> None:
        with self._lock:
            if req.state == FINISHED:
                return
            req.state = FINISHED
            req.finish_reason = reason
            req.error = error
            req.t_finish = time.monotonic()
            if req in self._active:
                self._active.remove(req)
            if error is None:
                self._finished += 1
            else:
                self._failed += 1
        req._mark_done()

    # -- introspection (the engine-shaped surface) -------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_capacity(self) -> int:
        return self.queue.max_size

    def estimate_queue_clear_s(self) -> Optional[float]:
        return None

    def service_snapshot(self) -> dict:
        with self._lock:
            n_active = len(self._active)
        return {"queue_depth": len(self.queue),
                "queue_capacity": self.queue.max_size,
                "n_active": n_active, "n_slots": self.n_slots,
                "tpot_ewma": self.tpot_s, "tokens_ewma": None,
                "draining": self._draining, "dead": self._dead is not None}

    def stats(self) -> dict:
        with self._lock:
            out = {"requests_finished": self._finished,
                   "requests_failed": self._failed,
                   "n_ticks": self._ticks,
                   "n_recompiles": self.n_recompiles,
                   "n_restarts": self.n_restarts,
                   "draining": self._draining}
        if self.prefix_store is not None:
            out["prefix_store"] = self.prefix_store.stats()
        return out

    def healthz_payload(self) -> dict:
        snap = self.service_snapshot()
        with self._lock:
            ticks, finished, failed = (self._ticks, self._finished,
                                       self._failed)
        status = "serving"
        if self._dead is not None:
            status = "dead"
        elif self._draining:
            status = "draining"
        return {"status": status, "slots": self.n_slots,
                "active": snap["n_active"],
                "queue_depth": snap["queue_depth"],
                "queue_capacity": snap["queue_capacity"],
                "warmed_up": True, "draining": self._draining,
                "restarts": 0,
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "n_ticks": ticks,
                "occupancy": round(snap["n_active"]
                                   / max(self.n_slots, 1), 3),
                "counters": {"requests_finished": finished,
                             "requests_failed": failed}}

    def metrics_snapshot(self):
        with self._lock:
            counters = {"serve_requests_finished_total": self._finished,
                        "serve_requests_failed_total": self._failed}
            gauges = {"serve_active_slots": float(len(self._active)),
                      "serve_queue_depth": float(len(self.queue))}
        return counters, gauges, {}


# ---------------------------------------------------------------------------
# the worker RPC server
# ---------------------------------------------------------------------------

class _WEntry:
    __slots__ = ("client_id", "req", "stolen")

    def __init__(self, client_id: int, req: Request):
        self.client_id = client_id
        self.req = req
        self.stolen = False


class WorkerServer:
    """RPC facade over one replica engine inside the worker process.

    Control methods run on transport connection threads; request
    progress (admitted/piece/done/failed) and heartbeats push over the
    subscribed event channel. ``client_id`` — the SUPERVISOR's request
    id — is the cross-process request identity: piece callbacks close
    over it, so no map lookup can race the engine admitting a request
    before ``submit`` returns.
    """

    def __init__(self, engine, socket_path: str, *,
                 replica: int = 0, heartbeat_s: float = 0.5,
                 max_frame_bytes: Optional[int] = None,
                 incarnation: int = 0):
        self.engine = engine
        self.replica = replica
        self.incarnation = incarnation
        self.heartbeat_s = heartbeat_s
        self.rpc_stats = RpcStats()
        kw = {}
        if max_frame_bytes:
            kw["max_frame_bytes"] = max_frame_bytes
        self.server = RpcServer(socket_path, self._handle,
                                stats=self.rpc_stats,
                                span_hook=self._rpc_span, **kw)
        self._lock = threading.Lock()
        self._entries: Dict[int, _WEntry] = {}         # guarded-by: _lock
        self._events: "_stdqueue.Queue[Optional[dict]]" = _stdqueue.Queue()
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.server.start()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           name="worker-heartbeat",
                                           daemon=True)
        self._hb_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._events.put(None)
        self.server.stop()

    # -- event channel -----------------------------------------------------

    def _push(self, ev: dict) -> None:
        self._events.put(ev)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            # paired (wall, mono) stamps: the supervisor's between-RPC
            # clock-offset signal, and the honest base for heartbeat-age
            # math (receipt time includes pipe latency; this doesn't)
            self._push({"ev": "heartbeat", "pid": os.getpid(),
                        "wall": time.time(), "mono": time.monotonic(),
                        "incarnation": self.incarnation,
                        "snapshot": self.engine.service_snapshot()})

    # -- observability -----------------------------------------------------

    def _rpc_span(self, method: str, trace: dict, t0_wall: float,
                  dur_s: float, ok: bool) -> None:
        """RpcServer span hook: one ``rpc`` span row per traced frame —
        the server-handle half of the hop (the client logs its
        send→reply wait as an ``rpc:<method>`` child on the request
        tree; the gap between the two IS the transport)."""
        get_metrics().log_span(
            "rpc", t0_wall, dur_s, cat="rpc", method=method,
            request_id=trace.get("request_id"), replica=self.replica,
            pid=os.getpid(), incarnation=self.incarnation, ok=ok)

    def _event_sender(self, sock) -> None:
        """Drains the event queue onto the subscribed connection. Peer
        gone = the supervisor died; the worker keeps serving (SIGTERM or
        a new supervisor will claim it)."""
        while not self._stop.is_set():
            ev = self._events.get()
            if ev is None:
                return
            try:
                send_frame(sock, ev)
            except TransportError:
                logger.warning("Event peer gone; event channel closed.")
                return

    # -- request watchers --------------------------------------------------

    def _watch(self, entry: _WEntry) -> None:
        """Per-request lifecycle reporter: polls admission (cheap attr
        read), then blocks on the done event and pushes the terminal
        frame — authoritative token ids + text, so streamed pieces are
        pure latency optimization."""
        req = entry.req
        admitted_sent = False
        while not req._done.wait(0.01):
            if not admitted_sent and req.t_admit is not None:
                self._push({"ev": "admitted", "client_id": entry.client_id})
                admitted_sent = True
        with self._lock:
            self._entries.pop(entry.client_id, None)
            if entry.stolen:
                return              # handle now lives on another worker
        self._emit_worker_span(entry)
        if req.error is None and req.finish_reason is not None \
                and req.finish_reason not in ("error",):
            self._push({"ev": "done", "client_id": entry.client_id,
                        "token_ids": [int(t) for t in req.output_ids],
                        "text": req.text,
                        "finish_reason": req.finish_reason,
                        "n_prompt_tokens": int(len(req.prompt_ids)),
                        "queue_wait_s": req.queue_wait_s(),
                        "ttft_s": req.ttft_s(), "tpot_s": req.tpot_s()})
        else:
            self._push({"ev": "failed", "client_id": entry.client_id,
                        "reason": req.finish_reason or "error",
                        "error": req.error or "engine failure"})

    def _on_piece(self, client_id: int, req: Request, tok: int,
                  piece: str) -> None:
        self._push({"ev": "piece", "client_id": client_id,
                    "token": int(tok), "piece": piece})

    def _emit_worker_span(self, entry: _WEntry) -> None:
        """The worker-process half of the request's span tree: the same
        queued/prefill/decode shape as the engine's ``request`` root,
        renamed ``worker_request``, keyed by the SUPERVISOR's request id
        (the cross-process identity) and stamped with pid/incarnation —
        the merged timeline joins it to the fleet's ``request`` root on
        ``request_id``. Telemetry only: failures are swallowed."""
        try:
            row = entry.req.trace_row()
            row["name"] = "worker_request"
            row["local_request_id"] = row.get("request_id")
            row["request_id"] = entry.client_id
            row["replica"] = self.replica
            row["pid"] = os.getpid()
            row["incarnation"] = self.incarnation
            get_metrics().log_span(**row)
        except Exception:
            logger.exception("worker_request span emit failed (ignored)")

    # -- control methods ---------------------------------------------------

    def _handle(self, method: str, args: dict, sock):
        if method == "subscribe":
            t = threading.Thread(target=self._event_sender, args=(sock,),
                                 name="worker-events", daemon=True)
            t.start()
            return (DETACH, {"ok": True, "pid": os.getpid()})
        if method == "ping":
            return {"ok": True, "pid": os.getpid()}
        if method in ("submit", "adopt"):
            return self._rpc_submit(args, adopt=(method == "adopt"))
        if method == "cancel":
            return self._rpc_cancel(args)
        if method == "steal_queue":
            return self._rpc_steal_queue()
        if method == "drain":
            return self.engine.drain(
                timeout=float(args.get("timeout", 30.0)))
        if method == "healthz":
            out = dict(self.engine.healthz_payload())
            out["pid"] = os.getpid()
            return out
        if method == "snapshot":
            return self.engine.service_snapshot()
        if method == "stats":
            return _jsonable(self.engine.stats())
        if method == "metrics":
            counters, gauges, hists = self.engine.metrics_snapshot()
            out = {"counters": dict(counters), "gauges": dict(gauges),
                   "hists": {k: h.snapshot() for k, h in hists.items()}}
            # server-side transport telemetry rides the same scrape: the
            # fleet re-labels every series with worker/incarnation
            for m, e in self.rpc_stats.snapshot().items():
                lab = f'{{method="{m}"}}'
                out["counters"][f"rpc_server_calls{lab}"] = e["calls"]
                out["counters"][f"rpc_server_errors{lab}"] = e["errors"]
                out["counters"][
                    f"rpc_server_frame_bytes_received{lab}"] = \
                    e["bytes_received"]
                out["counters"][f"rpc_server_frame_bytes_sent{lab}"] = \
                    e["bytes_sent"]
                out["hists"][f"rpc_server_handle_seconds{lab}"] = \
                    e["latency"]
            return out
        if method == "export_panes":
            return self._rpc_export_panes()
        if method == "import_panes":
            return self._rpc_import_panes(args)
        raise ValueError(f"unknown method '{method}'")

    def _rpc_submit(self, args: dict, adopt: bool) -> dict:
        client_id = int(args["client_id"])
        prompt_ids = np.asarray(args["prompt_ids"], np.int32)
        params = SamplingParams(**(args.get("params") or {}))
        on_token = (lambda req, tok, piece, cid=client_id:
                    self._on_piece(cid, req, tok, piece))
        if adopt:
            # re-dispatched work was admitted fleet-wide already: skip
            # submit-time shedding, mirror EngineRouter._redispatch
            req = Request(next_request_id(), prompt_ids, params, on_token)
            req.route = args.get("route")
            self.engine.adopt(req, timeout=float(args.get("timeout", 5.0)))
        else:
            req = self.engine.submit(prompt_ids, params, block=False,
                                     on_token=on_token,
                                     route=args.get("route"))
        entry = _WEntry(client_id, req)
        with self._lock:
            self._entries[client_id] = entry
        threading.Thread(target=self._watch, args=(entry,),
                         name=f"watch-{client_id}", daemon=True).start()
        return {"request_id": req.id}

    def _rpc_cancel(self, args: dict) -> dict:
        with self._lock:
            entry = self._entries.get(int(args["client_id"]))
        if entry is None:
            return {"cancelled": False}
        return {"cancelled": bool(self.engine.cancel(entry.req))}

    def _rpc_steal_queue(self) -> dict:
        """Pop every still-QUEUED request (the supervisor re-dispatches
        them under the same client ids — ``drain_replica`` semantics
        across the process boundary)."""
        stolen: List[int] = []
        while True:
            req = self.engine.queue.get_nowait()
            if req is None:
                break
            with self._lock:
                entry = next((e for e in self._entries.values()
                              if e.req is req), None)
                if entry is not None:
                    entry.stolen = True
                    stolen.append(entry.client_id)
            # unblock the watcher; `stolen` suppresses its terminal frame
            req._mark_done()
        return {"client_ids": stolen}

    def _rpc_export_panes(self) -> dict:
        store = getattr(self.engine, "prefix_store", None)
        if store is None:
            return {"entries": []}
        entries = [{"key": k, "span": span, "panes": encode_panes(panes),
                    "nbytes": cache_nbytes(panes)}
                   for k, span, panes in store.export_entries()]
        return {"entries": entries}

    def _rpc_import_panes(self, args: dict) -> dict:
        store = getattr(self.engine, "prefix_store", None)
        if store is None:
            return {"imported": 0, "bytes": 0}
        imported = total = 0
        for ent in args.get("entries", []):
            n = store.import_entry(ent["key"],
                                   decode_panes(ent["panes"]),
                                   int(ent["span"]))
            if n > 0:
                imported += 1
                total += n
        return {"imported": imported, "bytes": total}


def _jsonable(obj: Any) -> Any:
    """Best-effort JSON coercion for stats payloads (numpy scalars)."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


# ---------------------------------------------------------------------------
# subprocess entrypoint
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="fleet worker: one replica engine behind a unix-socket "
                    "RPC boundary")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--spec", required=True,
                    help="EngineSpec JSON (inline or @/path/to/file)")
    ap.add_argument("--replica", type=int, default=0)
    ap.add_argument("--incarnation", type=int, default=0,
                    help="restart generation of this worker process "
                         "(the supervisor's restart count); stamps "
                         "telemetry + seeds a disjoint request-id range")
    ap.add_argument("--metrics_jsonl", default=None)
    ap.add_argument("--heartbeat_s", type=float, default=0.5)
    ap.add_argument("--drain_timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    spec_json = args.spec
    if spec_json.startswith("@"):
        with open(spec_json[1:]) as f:
            spec_json = f.read()
    spec = EngineSpec.from_json(spec_json)

    if spec.fake is None:
        apply_host_env(spec.devices)
    if args.metrics_jsonl:
        # append mode: a restarted incarnation stacks its rows (own
        # header first) onto the same per-replica file, so the victim's
        # last rows and its successor's live in one artifact
        configure_metrics(args.metrics_jsonl,
                          run_metadata={"role": "fleet_worker",
                                        "replica": args.replica,
                                        "incarnation": args.incarnation,
                                        "pid": os.getpid()},
                          append=True)
    # worker-LOCAL request ids must never collide with the supervisor's
    # fleet-wide ids (or another worker's) in merged telemetry: seed a
    # disjoint per-(replica, incarnation) range
    seed_request_ids((args.replica * 1000 + args.incarnation + 1)
                     * 1_000_000)

    engine = build_engine(spec, replica=args.replica)
    engine.warmup()
    engine.start()

    server = WorkerServer(engine, args.socket, replica=args.replica,
                          heartbeat_s=args.heartbeat_s,
                          incarnation=args.incarnation)
    server.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    # exactly ONE stdout line, then the pipe stays open: the supervisor
    # parses this for readiness and reads EOF on it as process death
    print(json.dumps({"ready": True, "pid": os.getpid(),
                      "replica": args.replica, "socket": args.socket}),
          flush=True)
    logger.info("Worker %d serving on %s (pid %d).",
                args.replica, args.socket, os.getpid())

    stop.wait()
    logger.info("Worker %d: SIGTERM — draining (%.1fs budget).",
                args.replica, args.drain_timeout)
    try:
        engine.drain(timeout=args.drain_timeout)
    finally:
        engine.shutdown(drain=False)
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
