"""The shared transformer core.

ONE parameterized implementation covers every model family the reference
builds with three separate module stacks:
  - GPTModel          (reference Models/GPT2/GPT2.py:91-124)
  - Llama2Model       (reference Models/Llama/Llama2.py:156-190)
  - Llama3Model       (reference Models/Llama/Llama3.py:185-204)

The architecture knobs live in ``ModelConfig`` (configs.py); the parameters
are a plain pytree; the forward pass is a pure function usable under ``jit``
/ ``pjit`` / ``grad`` / ``shard_map``.

TPU-first design choices (vs. the reference's nn.Module stacks):
  - all L transformer blocks are STACKED along a leading layer axis and
    executed with ``jax.lax.scan`` — one compiled block body instead of L
    unrolled copies (compile time O(1) in depth, XLA-friendly);
  - ``--use_actv_ckpt`` maps to ``jax.checkpoint`` (remat) of the scanned
    block body (reference: torch checkpoint_sequential, GPT2.py:115-116);
  - no (ctx, ctx) causal-mask buffer; masking is positional iota inside the
    attention kernel;
  - KV-cache decode path with static shapes for jitted autoregressive
    generation (the reference re-runs the full forward per token,
    generate.py:36-45);
  - dropout uses explicit PRNG keys, folded per layer.

Parameter tree layout (linear weights stored (in, out), applied as x @ w):

  params = {
    "tok_emb":   {"weight": (V, D)},
    "pos_emb":   {"weight": (T, D)}          # learned positions (GPT-2) only
    "blocks": {
      "norm1":   {"scale": (L, D)[, "bias": (L, D)]},
      "attn":    {"wq": (L, D, Hq*hd), "wk": (L, D, Hkv*hd),
                  "wv": (L, D, Hkv*hd), "wo": (L, Hq*hd, D)
                  [, "bq", "bk", "bv" , "bo"]},
      "norm2":   {"scale": (L, D)[, "bias"]},
      "mlp":     {"up": (L, D, F), "down": (L, F, D)
                  [, "gate": (L, D, F)]      # SwiGLU (LLaMA)
                  [, "b_up": (L, F), "b_down": (L, D)]},
    },
    "final_norm": {"scale": (D,)[, "bias": (D,)]},
    "head":      {"weight": (D, V)},
  }
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models.lora import apply_lora, lora_delta
from building_llm_from_scratch_tpu.ops.attention import (
    causal_attention,
    decode_attention,
)
from building_llm_from_scratch_tpu.ops.activations import gelu, silu
from building_llm_from_scratch_tpu.ops.norms import layernorm, rmsnorm
from building_llm_from_scratch_tpu.ops.rope import (
    apply_rope,
    precompute_rope_params,
)

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# LoRA adapter application (merge-free; models/lora.apply_lora is the
# shared projection helper)
#
# Two shapes of "adapter" flow through the forward passes:
#   - a single unmerged adapter tree (``lora=`` on forward/forward_with_
#     cache): every batch row shares one {"A","B"} node per projection —
#     the trainer's eval-sampling path;
#   - a per-row adapter POOL (``adapter=`` on the slot-batched serving
#     functions): stacked ``(n_adapters_max, ...)`` A/B leaves plus a
#     per-row ``ids`` vector — Punica/S-LoRA-style BGMV, where adapter
#     identity is DATA, so hot-loading adapters never recompiles and one
#     decode program serves arbitrary adapter mixes (id −1 = base model,
#     exact zero delta).
# ---------------------------------------------------------------------------

def _block_adp(lb: Params, s) -> Params:
    """Per-layer adapter argument for ``_block``/the slot loops: the lora
    blocks node (attn/mlp, each projection a {"A","B"}) + the scale."""
    return {"attn": dict(lb["attn"], s=s), "mlp": dict(lb["mlp"], s=s)}


def _aligned_block_adp(lb: Params, s, rows_per_job: int) -> Params:
    """Per-layer adapter argument for the SLOT-ALIGNED pool path: each
    projection node routes through ``models/lora.aligned_lora_delta``
    (one application per job block) instead of the per-row gather. ``lb``
    leaves are the layer's stacked (J, in, r)/(J, r, out) pool panes."""
    out = {}
    for group in ("attn", "mlp"):
        out[group] = {name: {"aligned": (n["A"], n["B"], s, rows_per_job)}
                      for name, n in lb[group].items()}
        out[group]["s"] = None
    return out


def _adapter_rows(pool: Params, scaling: jnp.ndarray, ids: jnp.ndarray):
    """BGMV gather: per-row adapter matrices from the stacked pool.

    ``pool`` mirrors the lora tree with a leading ``(n_adapters_max,)``
    axis on every leaf; ``ids`` (B,) int32 selects one pool row per batch
    row (−1 = base model: the index clamps into range but the gathered
    scale is forced to 0, so the delta is exactly zero regardless of what
    the clamped row holds)."""
    idx = jnp.clip(ids.astype(jnp.int32), 0, scaling.shape[0] - 1)
    s = jnp.where(ids >= 0, jnp.take(scaling, idx, axis=0), 0.0)
    rows = jax.tree_util.tree_map(lambda a: jnp.take(a, idx, axis=0), pool)
    return rows, s


def unstack_lora_blocks(lora: Params, cfg: ModelConfig) -> list:
    """Per-layer views of a stacked lora tree's ``blocks`` node — the
    adapter twin of ``unstack_blocks`` (hoisted out of sampling loops for
    the same re-layout reason)."""
    return [
        jax.tree_util.tree_map(lambda a, l=l: a[l], lora["blocks"])
        for l in range(cfg.n_layers)
    ]


def _head_logits(x: jnp.ndarray, w: jnp.ndarray,
                 node: Optional[Params] = None,
                 scaling=None) -> jnp.ndarray:
    """LM-head projection (+ optional unmerged LoRA delta). The base
    einsum is byte-for-byte the historical head path; the delta rides on
    top in fp32 like ``apply_lora``."""
    logits = jnp.einsum("btd,dv->btv", x, w,
                        preferred_element_type=jnp.float32)
    if node is None:
        return logits
    return logits + lora_delta(x, node, scaling).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------

def _linear_init(key, in_dim: int, out_dim: int, dtype, n_layers=None):
    """Truncated-normal fan-in init (GPT-2-style 0.02-capped)."""
    std = min(0.02, in_dim ** -0.5)
    shape = (in_dim, out_dim) if n_layers is None else (n_layers, in_dim, out_dim)
    return (jax.random.truncated_normal(key, -3.0, 3.0, shape, jnp.float32)
            * std).astype(dtype)


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Build the full parameter pytree for ``cfg``."""
    L, D, V, T = cfg.n_layers, cfg.emb_dim, cfg.vocab_size, cfg.context_length
    hd, Hq, Hkv, F = cfg.head_dim, cfg.n_heads, cfg.n_kv_groups, cfg.hidden_dim
    dt = cfg.jax_dtype

    keys = jax.random.split(key, 16)
    zeros = lambda *shape: jnp.zeros(shape, dt)
    ones = lambda *shape: jnp.ones(shape, dt)

    attn: Params = {
        "wq": _linear_init(keys[0], D, Hq * hd, dt, L),
        "wk": _linear_init(keys[1], D, Hkv * hd, dt, L),
        "wv": _linear_init(keys[2], D, Hkv * hd, dt, L),
        "wo": _linear_init(keys[3], Hq * hd, D, dt, L),
    }
    if cfg.qkv_bias:
        attn.update(bq=zeros(L, Hq * hd), bk=zeros(L, Hkv * hd),
                    bv=zeros(L, Hkv * hd))
    if cfg.attn_out_bias:
        attn["bo"] = zeros(L, D)

    mlp: Params = {
        "up": _linear_init(keys[4], D, F, dt, L),
        "down": _linear_init(keys[5], F, D, dt, L),
    }
    if cfg.activation == "swiglu":
        mlp["gate"] = _linear_init(keys[6], D, F, dt, L)
    if cfg.mlp_bias:
        mlp.update(b_up=zeros(L, F), b_down=zeros(L, D))

    def norm(n_layers=None):
        n: Params = {"scale": ones(n_layers, D) if n_layers else ones(D)}
        if cfg.norm_bias:
            n["bias"] = zeros(n_layers, D) if n_layers else zeros(D)
        return n

    params: Params = {
        "tok_emb": {"weight": (jax.random.normal(keys[7], (V, D), jnp.float32)
                               * 0.02).astype(dt)},
        "blocks": {"norm1": norm(L), "attn": attn, "norm2": norm(L), "mlp": mlp},
        "final_norm": norm(),
        "head": {"weight": _linear_init(keys[8], D, V, dt)},
    }
    if cfg.positional == "learned":
        params["pos_emb"] = {"weight": (jax.random.normal(keys[9], (T, D),
                                                          jnp.float32)
                                        * 0.02).astype(dt)}
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def _norm(cfg: ModelConfig, p: Params, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.norm == "rmsnorm":
        return rmsnorm(x, p["scale"], eps=cfg.rmsnorm_eps)
    return layernorm(x, p["scale"], p.get("bias"), eps=cfg.layernorm_eps)


def _mlp(cfg: ModelConfig, p: Params, x: jnp.ndarray,
         tp_axis: Optional[str] = None,
         adp: Optional[Params] = None) -> jnp.ndarray:
    """MLP. ``tp_axis``: Megatron column-parallel up/gate (+ their biases,
    which are feature-sharded like the weights) and row-parallel down with
    an explicit psum; the replicated down bias is added once after.
    ``adp``: optional unmerged LoRA nodes per projection (+ ``"s"`` scale;
    does not compose with tp — adapters see the FULL weight)."""
    s = adp["s"] if adp is not None else None
    n = (lambda name: adp.get(name)) if adp is not None else (lambda _: None)
    if cfg.activation == "swiglu":
        # silu(gate(x)) * up(x) -> down   (reference common_components.py:95-124)
        g = checkpoint_name(apply_lora(x, p["gate"], n("gate"), s),
                            "gate_out")
        u = checkpoint_name(apply_lora(x, p["up"], n("up"), s), "up_out")
        h = apply_lora(silu(g) * u, p["down"], n("down"), s)
        if tp_axis is not None:
            h = jax.lax.psum(h, tp_axis)
        return h
    h = apply_lora(x, p["up"], n("up"), s)
    if "b_up" in p:
        h = h + p["b_up"]
    h = checkpoint_name(h, "up_out")
    h = gelu(h)
    h = apply_lora(h, p["down"], n("down"), s)
    if tp_axis is not None:
        h = jax.lax.psum(h, tp_axis)
    if "b_down" in p:
        h = h + p["b_down"]
    return h


def _use_fused_dropout(shape) -> bool:
    if jax.default_backend() != "tpu":
        return False
    from building_llm_from_scratch_tpu.ops.fused_dropout import supports_shape

    return supports_shape(shape)


def _dropout(x: jnp.ndarray, rate: float, rng: Optional[jax.Array],
             deterministic: bool) -> jnp.ndarray:
    if rate <= 0.0 or deterministic:
        return x
    if _use_fused_dropout(x.shape):
        from building_llm_from_scratch_tpu.ops.fused_dropout import (
            fused_dropout,
        )

        return fused_dropout(x, rate, rng)
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def _residual_dropout(x: jnp.ndarray, h: jnp.ndarray, rate: float,
                      rng: Optional[jax.Array],
                      deterministic: bool) -> jnp.ndarray:
    """x + dropout(h): the pre-norm residual update (reference
    GPT2.py:79-87). On TPU the mask is drawn in-kernel (fused_dropout.py)
    so it is never generated twice or stored for the backward."""
    if rate <= 0.0 or deterministic:
        return x + h
    if _use_fused_dropout(h.shape):
        from building_llm_from_scratch_tpu.ops.fused_dropout import (
            fused_dropout_add,
        )

        return fused_dropout_add(x, h, rate, rng)
    return x + _dropout(h, rate, rng, deterministic)


def _qkv_proj(cfg: ModelConfig, p: Params, x: jnp.ndarray,
              rope, positions, adp: Optional[Params] = None):
    """Shared q/k/v projection (+biases, head reshape, RoPE) — the single
    source of truth for the attention parameterization, used by BOTH the
    training path (_attention) and the KV-cache decode body
    (forward_with_cache); divergence here would silently break decode.
    ``adp``: optional unmerged LoRA nodes (wq/wk/wv + ``"s"``), applied
    BEFORE the head reshape and RoPE — exactly where a merged weight's
    delta would land."""
    B, Tq, _ = x.shape
    hd = cfg.head_dim
    s = adp["s"] if adp is not None else None
    n = (lambda name: adp.get(name)) if adp is not None else (lambda _: None)
    q = apply_lora(x, p["wq"], n("wq"), s)
    k = apply_lora(x, p["wk"], n("wk"), s)
    v = apply_lora(x, p["wv"], n("wv"), s)
    if "bq" in p:
        q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
    # head counts come from the PROJECTED widths, not the config: under
    # tensor parallelism inside a shard_map each device holds Hq/ntp (and
    # Hkv/ntp) head slices of wq/wk/wv and attends over them locally
    q = q.reshape(B, Tq, -1, hd)
    k = k.reshape(B, Tq, -1, hd)
    v = v.reshape(B, Tq, -1, hd)
    if rope is not None:
        cos, sin = rope
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
    # names for the selective-save remat policy (forward_hidden): post-RoPE
    # q/k/v are saved so the backward neither re-projects nor re-rotates
    q = checkpoint_name(q, "q")
    k = checkpoint_name(k, "k")
    v = checkpoint_name(v, "v")
    return q, k, v


def _attn_out_proj(p: Params, out: jnp.ndarray, B: int, Tq: int,
                   tp_axis: Optional[str] = None,
                   adp: Optional[Params] = None) -> jnp.ndarray:
    """Output projection; with ``tp_axis`` (Megatron row-parallel wo inside
    a shard_map) the partial products psum over the model axis and the
    bias — replicated, not sharded — is added exactly once AFTER."""
    out = apply_lora(out.reshape(B, Tq, -1), p["wo"],
                     adp.get("wo") if adp is not None else None,
                     adp["s"] if adp is not None else None)
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if "bo" in p:
        out = out + p["bo"]
    return out


def _attention(cfg: ModelConfig, p: Params, x: jnp.ndarray,
               rope: Optional[Tuple[jnp.ndarray, jnp.ndarray]],
               positions: Optional[jnp.ndarray],
               cache_kv: Optional[Tuple[jnp.ndarray, jnp.ndarray]],
               cache_len: Optional[jnp.ndarray],
               rng: Optional[jax.Array], deterministic: bool,
               sp_mesh=None, sp_inside=None, tp_axis=None, adp=None):
    """Per-block attention; returns (out, new_cache_kv)."""
    B, Tq, D = x.shape
    hd = cfg.head_dim

    q, k, v = _qkv_proj(cfg, p, x, rope, positions, adp=adp)

    new_cache = None
    if cache_kv is not None:
        # write current k/v into the cache at offset cache_len, attend to the
        # full valid prefix
        ck, cv = cache_kv                        # (B, Tmax, Hkv, hd)
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                          (0, cache_len, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                          (0, cache_len, 0, 0))
        new_cache = (ck, cv)
        k, v = ck, cv
        kv_length = cache_len + Tq
        q_positions = positions
    else:
        kv_length = None
        q_positions = None

    if sp_inside is not None and cache_kv is None:
        # already INSIDE a shard_map that mapped the seq axis (the explicit
        # bf16_hybrid step): run the local ring body directly
        from building_llm_from_scratch_tpu.ops.ring_attention import (
            _ring_attention_local,
        )
        from building_llm_from_scratch_tpu.parallel.mesh import DATA_AXIS

        axis_name, axis_size = sp_inside
        dropout_on = cfg.drop_rate > 0.0 and not deterministic
        out = _ring_attention_local(
            q, k, v, axis_name=axis_name, axis_size=axis_size,
            scale=1.0 / float(hd) ** 0.5,
            dropout_rate=cfg.drop_rate if dropout_on else 0.0,
            dropout_rng=rng if dropout_on else None,
            shard_fold_axes=(DATA_AXIS,))
    elif sp_mesh is not None and cache_kv is None:
        # sequence parallelism: the ring schedule owns the communication;
        # attention dropout folds shard indices into the mask PRNG (the
        # round-3 restriction is lifted — ring_attention.py)
        from building_llm_from_scratch_tpu.ops.ring_attention import (
            ring_causal_attention,
        )

        dropout_on = cfg.drop_rate > 0.0 and not deterministic
        out = ring_causal_attention(
            q, k, v, sp_mesh,
            dropout_rate=cfg.drop_rate if dropout_on else 0.0,
            dropout_rng=rng if dropout_on else None)
    else:
        out = causal_attention(
            q, k, v,
            q_positions=q_positions,
            kv_length=kv_length,
            dropout_rate=cfg.drop_rate,
            dropout_rng=rng,
            deterministic=deterministic,
            impl=cfg.attn_impl,
        )
    out = checkpoint_name(out, "attn_out")
    out = _attn_out_proj(p, out, B, Tq, tp_axis=tp_axis, adp=adp)
    return out, new_cache


def _block(cfg: ModelConfig, p: Params, x: jnp.ndarray,
           rope, positions, cache_kv, cache_len, rng, deterministic,
           sp_mesh=None, sp_inside=None, tp_axis=None, adp=None):
    """Pre-norm transformer block (reference GPT2.py:68-88, Llama3.py:159-181).

    ``tp_axis``: Megatron tensor parallelism INSIDE a shard_map — the
    caller feeds head-/feature-sharded wq/wk/wv/up(/gate) and input-sharded
    wo/down slices; this block attends over its local heads and psums the
    two row-parallel projections over the named axis (used by the pipeline
    schedule for pp x tp; the GSPMD tp path shards the same rule table
    outside shard_map instead)."""
    if rng is not None:
        r_attn, r_res1, r_res2 = jax.random.split(rng, 3)
        if tp_axis is not None and not deterministic:
            # attention-weight masks cover LOCAL head slices — fold the
            # model-shard index so global heads get iid masks. Residual
            # dropout keys stay UNfolded: they apply to the replicated
            # post-psum activations, which must mask identically on every
            # model shard or the replicas diverge.
            r_attn = jax.random.fold_in(r_attn,
                                        jax.lax.axis_index(tp_axis))
    else:
        r_attn = r_res1 = r_res2 = None
    h, new_cache = _attention(cfg, p["attn"], _norm(cfg, p["norm1"], x),
                              rope, positions, cache_kv, cache_len,
                              r_attn, deterministic, sp_mesh=sp_mesh,
                              sp_inside=sp_inside, tp_axis=tp_axis,
                              adp=adp["attn"] if adp is not None else None)
    x = _residual_dropout(x, h, cfg.drop_rate, r_res1, deterministic)
    x = checkpoint_name(x, "resid_mid")
    h = _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x), tp_axis=tp_axis,
             adp=adp["mlp"] if adp is not None else None)
    x = _residual_dropout(x, h, cfg.drop_rate, r_res2, deterministic)
    return x, new_cache


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------

def _train_scan_unroll(cfg: ModelConfig) -> int:
    """Unroll factor for the training layer scan.

    Full unroll on TPU for models up to 24 layers: the rolled scan forces
    XLA to serialize each layer's weight fetches and residual-save DUS
    against the loop step, and the backward copies whole stacked (L,.,.)
    gradient accumulators every iteration (r5 profile: ~8ms/step of pure
    copies on GPT2-124M bs8). Unrolled, weights prefetch across layers and
    grad accumulation becomes static-offset updates: measured 82.9k ->
    97.5k tok/s/chip (+18%) on the bs8 headline, +2.4% on the rematted
    LLaMA3.2-1B LoRA config. Deeper models keep the O(1)-compile scan
    (compile time for 36+ unrolled big-layer graphs grows superlinearly);
    CPU (test) backend always scans. Override: BLLM_TRAIN_UNROLL=<n>."""
    import os

    env = os.environ.get("BLLM_TRAIN_UNROLL")
    if env:
        return int(env)
    if jax.default_backend() == "tpu" and cfg.n_layers <= 24:
        return cfg.n_layers
    return 1


def _rope_tables(cfg: ModelConfig):
    if not cfg.uses_rope:
        return None
    return precompute_rope_params(
        cfg.head_dim,
        theta_base=cfg.rope_base,
        context_length=cfg.context_length,
        rope_scaling=cfg.rope_scaling,
    )


def _embed(cfg: ModelConfig, params: Params, tokens: jnp.ndarray,
           positions: Optional[jnp.ndarray], rng, deterministic) -> jnp.ndarray:
    x = jnp.take(params["tok_emb"]["weight"], tokens, axis=0)
    if cfg.positional == "learned":
        T = tokens.shape[1]
        pos = positions if positions is not None else jnp.arange(T)
        x = x + jnp.take(params["pos_emb"]["weight"], pos, axis=0)
    return _dropout(x, cfg.drop_rate, rng, deterministic)


def forward_hidden(params: Params, cfg: ModelConfig, tokens: jnp.ndarray, *,
                   rng: Optional[jax.Array] = None,
                   deterministic: bool = True,
                   sp_mesh=None, sp_inside=None,
                   lora: Optional[Params] = None,
                   lora_scaling=1.0,
                   adapter: Optional[Params] = None) -> jnp.ndarray:
    """Forward up to (and including) the final norm — the (B, T, D) hidden
    states BEFORE the output head. The training loss path consumes this
    directly via ops/softmax_xent.py so (B, T, V) fp32 logits never
    materialize; ``forward`` below adds the head for logits consumers
    (generation, tests, golden-logit parity).

    ``lora``: optional unmerged adapter tree (models/lora.py layout),
    applied at every adapted projection via ``apply_lora`` — the
    merge-free path serving shares. Not composable with tp/sp sharding
    (adapters multiply against the full weights).

    ``adapter``: optional per-ROW adapter pool ``{"pool": stacked
    (n, ...) lora tree, "scaling": (n,), "ids": (B,)}`` — the serving
    slot paths' BGMV gather applied to the full-sequence TRAINING
    forward: each batch row multiplies against its own gathered A/B
    (id −1 = zeroed scale = exact base path), so k finetune jobs'
    rows share ONE base forward/backward (training/lora_fusion.py).
    Job identity is data: changing ids never recompiles. Mutually
    exclusive with ``lora``; same tp/sp caveat."""
    if lora is not None and adapter is not None:
        raise ValueError("forward_hidden: pass lora= (one shared adapter) "
                         "or adapter= (per-row pool), not both")
    L = cfg.n_layers
    rope = _rope_tables(cfg)
    if rng is None:
        emb_rng = None
        layer_rngs = jnp.zeros((L, 2), jnp.uint32)
        deterministic = True
    else:
        emb_rng, blocks_rng = jax.random.split(rng)
        layer_rngs = jax.random.split(blocks_rng, L)

    if sp_inside is not None:
        # inside a seq-mapped shard_map, ``tokens`` is this shard's T/S
        # block: RoPE / learned positions must use the GLOBAL offsets
        # my*Tl..(my+1)*Tl-1, not 0..Tl-1
        axis_name, _ = sp_inside
        Tl = tokens.shape[1]
        positions = jax.lax.axis_index(axis_name) * Tl + jnp.arange(Tl)
    else:
        positions = None

    x = _embed(cfg, params, tokens, positions, emb_rng, deterministic)

    aligned_R = (adapter.get("rows_per_job")
                 if adapter is not None else None)
    if adapter is not None and aligned_R is not None:
        # SLOT-ALIGNED pool application (training/lora_fusion.py): the
        # batch's rows are job-contiguous (row block [j*R, (j+1)*R) is
        # job j — the stack_fleet_batch layout), so there is nothing to
        # gather: re-lead the stacked pool itself with the layer axis
        # and apply each job's adapter ONCE per block via
        # models/lora.aligned_lora_delta. Replaces the per-row gather's
        # rows_per_job-fold A/B duplication (and its scatter-add
        # backward) for this layout; ids are not needed — an inactive
        # slot's zero scaling zeroes its block's delta exactly.
        if tokens.shape[0] % int(aligned_R):
            raise ValueError(
                f"aligned adapter: batch rows {tokens.shape[0]} not a "
                f"multiple of rows_per_job={aligned_R}")
        row_blocks = jax.tree_util.tree_map(
            lambda a: jnp.moveaxis(a, 1, 0), adapter["pool"]["blocks"])
        row_s = adapter["scaling"]
    elif adapter is not None:
        # BGMV gather ONCE for the whole batch (the serving-path math,
        # _adapter_rows) — blocks subtree only; the head gathers
        # separately in forward() (gathering the whole pool here would
        # eagerly materialize discarded (B, r, V) head rows on
        # non-jitted calls). Gathered leaves are (B, L, in, r) —
        # re-lead with the layer axis so the scan slices each layer's
        # (B, in, r) per-row matrices
        rows, row_s = _adapter_rows(adapter["pool"]["blocks"],
                                    adapter["scaling"], adapter["ids"])
        row_blocks = jax.tree_util.tree_map(
            lambda a: jnp.moveaxis(a, 1, 0), rows)
    else:
        row_blocks = row_s = None

    def body(carry, layer):
        if lora is not None:
            p, lrng, lb = layer
            adp = _block_adp(lb, lora_scaling)
        elif adapter is not None:
            p, lrng, lb = layer
            adp = (_aligned_block_adp(lb, row_s, int(aligned_R))
                   if aligned_R is not None else _block_adp(lb, row_s))
        else:
            p, lrng = layer
            adp = None
        r = None if deterministic else lrng
        y, _ = _block(cfg, p, carry, rope, positions, None, None, r,
                      deterministic, sp_mesh=sp_mesh, sp_inside=sp_inside,
                      adp=adp)
        return y, None

    if cfg.use_actv_ckpt:
        body = jax.checkpoint(body, prevent_cse=False)
    else:
        # Selective-save remat (round-5 profile-driven): under plain
        # autodiff XLA saved ~460MB/layer of residuals across the scan
        # (six f32[B,T,D] norm intermediates, four bf16[B,T,4D] MLP
        # temps, q/k/v...) — ~5.5GB written fwd + re-read bwd per
        # GPT2-124M bs8 step. Save ONLY the named tensors (post-RoPE
        # q/k/v, the attention kernel's out+lse, the mid-block residual,
        # the MLP up/gate outputs) and recompute the cheap elementwise
        # chains (norms, GELU/SiLU, residual adds) in the backward: no
        # matmul and no attention-kernel recompute, ~4x less scan-carried
        # HBM traffic.
        # Only the fused kernel names its out+lse residuals
        # (fused_attention._fused_fwd_rule) — under the non-fused impls
        # (xla/flash; CPU tests, explicit --attn_impl) the backward
        # recomputes the attention scores/softmax from the saved q/k/v,
        # flash-style: more VPU work than r4's save-everything, far less
        # memory. The TPU default ('auto' -> fused) is unaffected.
        body = jax.checkpoint(
            body, prevent_cse=False,
            policy=jax.checkpoint_policies.save_only_these_names(
                "q", "k", "v", "attn_raw_out", "attn_lse", "attn_out",
                "resid_mid", "up_out", "gate_out"))

    if lora is not None:
        xs = (params["blocks"], layer_rngs, lora["blocks"])
    elif adapter is not None:
        xs = (params["blocks"], layer_rngs, row_blocks)
    else:
        xs = (params["blocks"], layer_rngs)
    x, _ = jax.lax.scan(body, x, xs, unroll=_train_scan_unroll(cfg))
    return _norm(cfg, params["final_norm"], x)


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray, *,
            rng: Optional[jax.Array] = None,
            deterministic: bool = True,
            sp_mesh=None, sp_inside=None,
            lora: Optional[Params] = None, lora_scaling=1.0,
            adapter: Optional[Params] = None) -> jnp.ndarray:
    """Training/eval forward over full sequences.

    tokens: (B, T) int32.  Returns fp32 logits (B, T, V).

    ``sp_mesh``: a Mesh whose ``seq`` axis is > 1 switches attention to the
    ring schedule (ops/ring_attention.py) — sequence parallelism for
    long-context training. Everything else (embeddings, norms, MLPs, loss)
    is token-local, so GSPMD shards it over the seq axis from the batch
    sharding alone; only attention needs the explicit ring.

    ``adapter``: per-row adapter pool (see ``forward_hidden``) — the head
    delta rides per-row gathered head matrices, exactly like
    ``decode_slots``.
    """
    x = forward_hidden(params, cfg, tokens, rng=rng,
                       deterministic=deterministic, sp_mesh=sp_mesh,
                       sp_inside=sp_inside, lora=lora,
                       lora_scaling=lora_scaling, adapter=adapter)
    if adapter is not None and adapter.get("rows_per_job") is not None:
        # slot-aligned head delta: one application per job block (see
        # forward_hidden); rides in fp32 like every head delta
        from building_llm_from_scratch_tpu.models.lora import (
            aligned_lora_delta,
        )

        head = adapter["pool"]["head"]["weight"]
        return _head_logits(x, params["head"]["weight"]) + \
            aligned_lora_delta(
                x, head["A"], head["B"], adapter["scaling"],
                int(adapter["rows_per_job"])).astype(jnp.float32)
    if adapter is not None:
        head_rows, head_s = _adapter_rows(
            {"head": adapter["pool"]["head"]}, adapter["scaling"],
            adapter["ids"])
        return _head_logits(x, params["head"]["weight"],
                            head_rows["head"]["weight"], head_s)
    return _head_logits(x, params["head"]["weight"],
                        lora["head"]["weight"] if lora is not None else None,
                        lora_scaling)


# ---------------------------------------------------------------------------
# KV-cache decode path
# ---------------------------------------------------------------------------

def init_cache(cfg: ModelConfig, batch_size: int, max_length: int) -> Params:
    """Allocate a static-shape KV cache: a LIST of per-layer (B, Tmax,
    Hkv, hd) buffers per k/v.

    Per-layer buffers instead of one stacked (L, ...) array (round 5): with
    the stacked cache as a while-loop carry, XLA failed to alias the
    dynamic-update-slice writes and copied the ENTIRE cache twice per
    decoded token (r5 profile: 206us of a 1010us step on GPT2-124M bs8
    Tmax=320 — copy-start/copy-done pairs over the full 47MB). With one
    buffer per layer, each layer's update aliases its own small buffer and
    the other L-1 pass through the carry untouched.

    Layout (B, Hkv, Tmax, hd) — attention-native: ``decode_attention``
    batches its einsums over (B, H), so the cache streams without the
    full-buffer re-layout copies the (B, T, H, D) model layout forced
    through ``causal_attention`` (the r5 profile's other 24
    copies/step).

    Allocation itself lives on ``serving.kvcache.KVCachePolicy.alloc``
    — ONE rule shared with the serving slot cache, so the two can never
    drift (layout, per-layer split, dtype policy). The train/one-shot
    path always uses the default policy (model dtype, no sidecars).
    """
    from building_llm_from_scratch_tpu.serving.kvcache import (
        DEFAULT_POLICY,
    )

    cache = DEFAULT_POLICY.alloc(cfg, batch_size, max_length)
    cache["length"] = jnp.zeros((), jnp.int32)
    return cache


def unstack_blocks(params: Params, cfg: ModelConfig) -> list:
    """Split the stacked (L, ...) block params into a list of per-layer
    trees. The decode loop wants this done ONCE outside the sampling
    while-loop: slicing stacked weights inside the loop made XLA re-layout
    wq/wk/wv copies every decoded token (r5 profile: 123us/step of
    loop-invariant weight transposes)."""
    return [
        jax.tree_util.tree_map(lambda a, l=l: a[l], params["blocks"])
        for l in range(cfg.n_layers)
    ]


def forward_with_cache(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                       cache: Params,
                       blocks_list: Optional[list] = None,
                       lora: Optional[Params] = None,
                       lora_scaling=1.0,
                       lora_blocks_list: Optional[list] = None
                       ) -> Tuple[jnp.ndarray, Params]:
    """Decode forward: process ``tokens`` (B, Tq) given ``cache`` holding
    ``cache['length']`` valid positions; returns (fp32 logits (B, Tq, V),
    updated cache). Static shapes throughout — jit-friendly.

    The layer loop is a plain Python loop (decode bodies are small; the
    r4 scan-unroll measured +14% over the rolled loop, and the explicit
    loop additionally lets per-layer cache buffers alias — see
    ``init_cache``). Pass ``blocks_list`` (from ``unstack_blocks``) when
    calling inside a sampling loop so the per-layer weight slices are
    hoisted out of it.

    Contract: the caller must ensure ``cache['length'] + Tq <= max_length``
    (the cache allocation). Under jit an overflow cannot raise —
    ``dynamic_update_slice`` would clamp the write offset and silently
    overwrite the newest entries. The generation loop sizes its cache to
    cover the full decode so this never triggers.
    """
    rope = _rope_tables(cfg)
    length = cache["length"]
    B, Tq = tokens.shape
    positions = length + jnp.arange(Tq)

    x = _embed(cfg, params, tokens, positions, None, True)

    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)
    if lora is not None and lora_blocks_list is None:
        lora_blocks_list = unstack_lora_blocks(lora, cfg)

    import os as _os

    # BLLM_FUSED_DECODE=1 opts into the pallas fused append+attend kernel
    # (ops/decode_step.py). It provably removes the per-token whole-cache
    # copies XLA inserts on the while-loop carry, but measured 3% SLOWER
    # end-to-end on GPT2-124M bs8 (690 vs 715 tok/s/seq, r5 A/B x3): its
    # per-batch-row grid serializes attention panes the XLA path overlaps
    # with the surrounding weight streams. On GQA (LLaMA3.2-1B bs8) the
    # A/B is dead-even (224.1 vs 224.4 tok/s/seq — weight streaming
    # dominates at 1B). Kept for future tuning; default off.
    use_fused_step = False
    if (jax.default_backend() == "tpu"
            and _os.environ.get("BLLM_FUSED_DECODE", "0") == "1"):
        from building_llm_from_scratch_tpu.ops.decode_step import (
            supports_shape as _fds_supports,
        )

        Tmax = cache["k"][0].shape[2]
        use_fused_step = _fds_supports(Tq, Tmax, cfg.head_dim)

    new_k, new_v = [], []
    for l, (p, K, V) in enumerate(zip(blocks_list, cache["k"], cache["v"])):
        adp = (_block_adp(lora_blocks_list[l], lora_scaling)
               if lora_blocks_list is not None else None)
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        if use_fused_step:
            # fused in-place append + attention (ops/decode_step.py): the
            # pallas input_output_aliases declaration is what finally stops
            # XLA from copying the whole cache every token (r5 profiles)
            from building_llm_from_scratch_tpu.ops.decode_step import (
                fused_decode_step,
            )

            out, K, V = fused_decode_step(q, k.astype(K.dtype),
                                          v.astype(V.dtype), K, V, length)
        else:
            # (B, Tq, Hkv, hd) -> cache-native (B, Hkv, Tq, hd) — tiny
            K = jax.lax.dynamic_update_slice(
                K, k.transpose(0, 2, 1, 3).astype(K.dtype),
                (0, 0, length, 0))
            V = jax.lax.dynamic_update_slice(
                V, v.transpose(0, 2, 1, 3).astype(V.dtype),
                (0, 0, length, 0))
            out = decode_attention(q, K, V, q_positions=positions,
                                   kv_length=length + Tq)
        new_k.append(K)
        new_v.append(V)
        x = x + _attn_out_proj(p["attn"], out, B, Tq,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    logits = _head_logits(x, params["head"]["weight"],
                          lora["head"]["weight"] if lora is not None
                          else None, lora_scaling)
    new_cache = {"k": new_k, "v": new_v, "length": length + Tq}
    return logits, new_cache


# ---------------------------------------------------------------------------
# Slot-batched decode path (serving/engine.py)
#
# The one-shot decode above shares ONE scalar ``length`` across the whole
# batch — every row is the same request family. The continuous-batching
# engine instead keeps a fixed (n_slots, Tmax) cache where every row is an
# INDEPENDENT request at its own sequence length: prefill writes one
# request's prompt k/v into one slot, and a decode tick advances all active
# slots by one token with per-row positions/lengths. Both are static-shape
# programs: XLA compiles one prefill per prompt-length bucket and exactly
# one decode step.
# ---------------------------------------------------------------------------

def init_slot_cache(cfg: ModelConfig, n_slots: int, max_length: int,
                    policy=None) -> Params:
    """Per-layer (n_slots, Hkv, Tmax, hd) k/v buffers; lengths are host
    state (serving/engine.py), not part of the device cache.

    ``policy`` (serving.kvcache.KVCachePolicy) owns layout and dtype:
    the default reproduces the historical model-dtype cache; the int8
    policy allocates int8 k/v plus fp32 per-position scale sidecars
    (``k_scale``/``v_scale`` lists) that the slot paths below fill on
    append and ``decode_attention`` folds back in."""
    from building_llm_from_scratch_tpu.serving.kvcache import (
        DEFAULT_POLICY,
    )

    return (policy or DEFAULT_POLICY).alloc(cfg, n_slots, max_length)


def _slot_adapter_layers(adapter, cfg: ModelConfig):
    """Gather the batch's per-row adapter matrices from the stacked pool
    and return (per-layer adp dicts, head node, scales) for the slot
    loops. ``adapter`` = {"pool": stacked lora tree, "scaling": (N,),
    "ids": (B,)}; ``None`` -> all-None (exact base path)."""
    if adapter is None:
        return None, None, None
    rows, s = _adapter_rows(adapter["pool"], adapter["scaling"],
                            adapter["ids"])
    # rows["blocks"] leaves are (B, L, in, r): slice each layer's view
    # once, trace-time (the gather itself happened once, above)
    layers = [
        _block_adp(jax.tree_util.tree_map(lambda a, l=l: a[:, l],
                                          rows["blocks"]), s)
        for l in range(cfg.n_layers)
    ]
    return layers, rows["head"]["weight"], s


def _cache_quantized(cache: Params) -> bool:
    return "k_scale" in cache


def _slot_write(cache: Params, name: str, pane: jnp.ndarray, offsets: tuple,
                new: Params) -> None:
    """Append one layer's cache write into the ``new`` accumulator:
    plain dynamic-update-slice for float caches; quantize-then-write
    (int8 codes + the fp32 scale sidecar) for int8 caches. ``pane`` is
    cache-native (1, Hkv, T, hd); ``offsets`` the 4-d DUS origin."""
    buf = cache[name][len(new[name])]
    if _cache_quantized(cache):
        from building_llm_from_scratch_tpu.ops.decode_step import quantize_kv

        codes, scale = quantize_kv(pane)
        sbuf = cache[name + "_scale"][len(new[name + "_scale"])]
        new[name + "_scale"].append(
            jax.lax.dynamic_update_slice(sbuf, scale, offsets))
        pane = codes
    new[name].append(
        jax.lax.dynamic_update_slice(buf, pane.astype(buf.dtype), offsets))


def _new_cache_acc(cache: Params) -> Params:
    return {name: [] for name in cache}


def _slot_append_kv(cache: Params, new: Params, l: int,
                    K: jnp.ndarray, V: jnp.ndarray,
                    k: jnp.ndarray, v: jnp.ndarray,
                    lengths: jnp.ndarray):
    """Per-row append of one layer's fresh k/v (model layout (S, Tq,
    Hkv, hd)) into the slot cache at each row's offset, quantizing on
    write under the int8 policy (codes + fp32 scale sidecars). THE one
    inner write rule shared by ``decode_slots`` (Tq=1) and
    ``verify_slots`` (Tq=k+1): the speculative path's bit-parity with
    plain decode depends on these two appends never drifting. Returns
    the appended (K, V) buffers (also pushed onto ``new``)."""
    from building_llm_from_scratch_tpu.ops.decode_step import (
        quantize_kv,
        slot_cache_append,
    )

    kt, vt = k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)
    if _cache_quantized(cache):
        kt, ks = quantize_kv(kt)
        vt, vs = quantize_kv(vt)
        new["k_scale"].append(slot_cache_append(
            cache["k_scale"][l], ks, lengths))
        new["v_scale"].append(slot_cache_append(
            cache["v_scale"][l], vs, lengths))
    K = slot_cache_append(K, kt, lengths)
    V = slot_cache_append(V, vt, lengths)
    new["k"].append(K)
    new["v"].append(V)
    return K, V


def _layer_scales(cache: Params, l: int, slot: Optional[jnp.ndarray] = None
                  ) -> dict:
    """``decode_attention`` kwargs for layer ``l``'s scale sidecars
    (empty when unquantized). ``slot`` slices one row out for the
    single-slot chunk-prefill path."""
    if not _cache_quantized(cache):
        return {}
    ks, vs = cache["k_scale"][l], cache["v_scale"][l]
    if slot is not None:
        ks = jax.lax.dynamic_slice(ks, (slot, 0, 0, 0), (1,) + ks.shape[1:])
        vs = jax.lax.dynamic_slice(vs, (slot, 0, 0, 0), (1,) + vs.shape[1:])
    return {"k_scale": ks, "v_scale": vs}


def prefill_into_slot(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                      prompt_len: jnp.ndarray, slot: jnp.ndarray,
                      cache: Params, blocks_list: Optional[list] = None,
                      adapter: Optional[Params] = None
                      ) -> Tuple[jnp.ndarray, Params]:
    """Run one request's prompt (``tokens`` (1, Tpb), right-padded to its
    length bucket) and write its k/v panes into row ``slot`` of the slot
    cache; returns (last-real-position logits (V,), updated cache).

    Attention here is plain causal self-attention over the prompt itself
    (nothing earlier lives in the slot), with ``kv_length=prompt_len``
    masking the pad keys. Pad-position k/v are ZEROED before the write —
    they used to land as garbage masked only by the engine's host-side
    lengths, which was fine while slot contents stayed request-private;
    prefix panes (serving/kvcache.py) make them shareable state, so
    every cache write must be a deterministic function of the prompt.

    ``adapter``: {"pool", "scaling", "ids" (1,)} — the request's LoRA
    adapter applied unmerged at every adapted projection (id −1 = base).
    The prompt's k/v land in the slot ALREADY adapter-transformed, so
    decode ticks attend to a prefix consistent with the same adapter.
    """
    _, Tpb = tokens.shape
    rope = _rope_tables(cfg)
    positions = jnp.arange(Tpb)
    x = _embed(cfg, params, tokens, positions, None, True)
    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)
    adp_layers, head_node, head_s = _slot_adapter_layers(adapter, cfg)
    # pad-position zero mask, model layout (1, Tpb, 1, 1)
    valid = (positions < prompt_len)[None, :, None, None]
    new = _new_cache_acc(cache)
    for l, p in enumerate(blocks_list):
        adp = adp_layers[l] if adp_layers is not None else None
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        out = causal_attention(q, k, v, q_positions=positions,
                               kv_length=prompt_len)
        # (1, Tpb, Hkv, hd) -> cache-native (1, Hkv, Tpb, hd) pane at
        # (slot, 0, 0, 0); Tpb <= Tmax by the engine's admission check
        k = jnp.where(valid, k, jnp.zeros((), k.dtype))
        v = jnp.where(valid, v, jnp.zeros((), v.dtype))
        _slot_write(cache, "k", k.transpose(0, 2, 1, 3), (slot, 0, 0, 0),
                    new)
        _slot_write(cache, "v", v.transpose(0, 2, 1, 3), (slot, 0, 0, 0),
                    new)
        x = x + _attn_out_proj(p["attn"], out, 1, Tpb,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    last = jax.lax.dynamic_slice(x, (0, prompt_len - 1, 0),
                                 (1, 1, x.shape[-1]))
    logits = _head_logits(last, params["head"]["weight"], head_node, head_s)
    return logits[0, 0], new


def prefill_chunk_into_slot(params: Params, cfg: ModelConfig,
                            tokens: jnp.ndarray, chunk_start: jnp.ndarray,
                            prompt_len: jnp.ndarray, slot: jnp.ndarray,
                            cache: Params,
                            blocks_list: Optional[list] = None,
                            adapter: Optional[Params] = None
                            ) -> Tuple[jnp.ndarray, Params]:
    """Chunked prefill: process ``tokens`` (1, C) — the prompt span
    [chunk_start, chunk_start + C), right-padded past ``prompt_len`` —
    against row ``slot`` whose positions [0, chunk_start) already hold
    valid KV (earlier chunks, or a copied prefix pane,
    serving/kvcache.py). Returns (logits at the clamped position
    ``prompt_len - 1 - chunk_start`` (V,), updated cache).

    The chunk width C is STATIC: every prompt of every length prefills
    through this ONE compiled program (chunk_start/prompt_len/slot are
    data) — both the one-compiled-program invariant and the per-tick
    prefill bound. A 2k-token prompt becomes 2k/C short calls the
    engine interleaves with decode ticks instead of one tick-stalling
    program.

    Masking: the chunk's own k/v zero at pad positions (>= prompt_len)
    BEFORE the cache write, and attention clamps ``kv_length`` to
    ``prompt_len`` so the zeros are never attended either. Pad QUERY
    rows compute garbage that stays in their own (position-wise) lanes;
    the logits read is clamped to a valid row.
    """
    _, C = tokens.shape
    rope = _rope_tables(cfg)
    positions = chunk_start + jnp.arange(C)
    x = _embed(cfg, params, tokens, positions, None, True)
    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)
    adp_layers, head_node, head_s = _slot_adapter_layers(adapter, cfg)
    valid = (positions < prompt_len)[None, :, None, None]
    kv_len = jnp.reshape(jnp.minimum(chunk_start + C, prompt_len), (1,))
    q_pos = positions[None, :]                       # (1, C) per-row form
    new = _new_cache_acc(cache)
    for l, p in enumerate(blocks_list):
        adp = adp_layers[l] if adp_layers is not None else None
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        k = jnp.where(valid, k, jnp.zeros((), k.dtype))
        v = jnp.where(valid, v, jnp.zeros((), v.dtype))
        _slot_write(cache, "k", k.transpose(0, 2, 1, 3),
                    (slot, 0, chunk_start, 0), new)
        _slot_write(cache, "v", v.transpose(0, 2, 1, 3),
                    (slot, 0, chunk_start, 0), new)
        # attend over THIS slot's full row, freshly including the chunk:
        # earlier chunks / the copied prefix pane are the context
        K_row = jax.lax.dynamic_slice(
            new["k"][l], (slot, 0, 0, 0), (1,) + new["k"][l].shape[1:])
        V_row = jax.lax.dynamic_slice(
            new["v"][l], (slot, 0, 0, 0), (1,) + new["v"][l].shape[1:])
        out = decode_attention(q, K_row, V_row, q_positions=q_pos,
                               kv_length=kv_len,
                               **_layer_scales(new, l, slot))
        x = x + _attn_out_proj(p["attn"], out, 1, C,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    idx = jnp.clip(prompt_len - 1 - chunk_start, 0, C - 1)
    last = jax.lax.dynamic_slice(x, (0, idx, 0), (1, 1, x.shape[-1]))
    logits = _head_logits(last, params["head"]["weight"], head_node, head_s)
    return logits[0, 0], new


def _use_bgmv(adapter, cfg: ModelConfig) -> bool:
    """Route per-row adapter deltas through the fused pallas BGMV kernel
    (ops/decode_step.lora_bgmv). Opt-in via BLLM_BGMV=1 on TPU — like
    BLLM_FUSED_DECODE, kept off by default until a hardware A/B proves it
    — and only when EVERY adapted projection's (in, rank, out) is
    kernel-eligible; the XLA gather+einsum path is the reference."""
    import os as _os

    if adapter is None or jax.default_backend() != "tpu":
        return False
    if _os.environ.get("BLLM_BGMV", "0") != "1":
        return False
    from building_llm_from_scratch_tpu.ops.decode_step import (
        supports_lora_shape,
    )

    r = adapter["pool"]["blocks"]["attn"]["wq"]["A"].shape[-1]
    D, F = cfg.emb_dim, cfg.hidden_dim
    wq, wkv = cfg.n_heads * cfg.head_dim, cfg.n_kv_groups * cfg.head_dim
    dims = [(D, wq), (D, wkv), (wq, D), (D, F), (F, D)]
    return all(supports_lora_shape(i, r, o) for i, o in dims)


def _bgmv_block_adp(pool_blocks_l, ids, scaling) -> Params:
    """Per-layer adp dict whose nodes route through the fused kernel:
    each projection carries its (N, in, r)/(N, r, out) pool panes — the
    kernel gathers per-row inside, driven by ``ids``."""
    def node(n):
        return {"bgmv": (n["A"], n["B"], ids, scaling)}

    out = {}
    for group in ("attn", "mlp"):
        out[group] = {name: node(n)
                      for name, n in pool_blocks_l[group].items()}
        out[group]["s"] = None
    return out


def decode_slots(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                 lengths: jnp.ndarray, cache: Params,
                 blocks_list: Optional[list] = None,
                 adapter: Optional[Params] = None
                 ) -> Tuple[jnp.ndarray, Params]:
    """One decode tick for the whole slot batch: ``tokens`` (S, 1) are each
    slot's last accepted token, ``lengths`` (S,) its valid cache prefix.
    Appends each row's k/v at ITS offset (ops/decode_step.slot_cache_append
    — pallas in-place on TPU) and attends with per-row masks; returns
    (fp32 logits (S, V), updated cache). Free/finished slots compute
    garbage rows the engine ignores — the shapes never change, so XLA
    compiles exactly one decode program.

    ``adapter``: {"pool", "scaling", "ids" (S,)} — per-SLOT LoRA adapters
    applied as a batched gather + einsum (BGMV) fused into the existing
    projections. Adapter identity is a data dimension: any mix of ids
    (−1 = base model) runs through this same one compiled program, so
    hot-loading/evicting adapters never recompiles.
    """
    rope = _rope_tables(cfg)
    S = tokens.shape[0]
    lengths = lengths.astype(jnp.int32)
    positions = lengths[:, None]                       # (S, 1)
    x = _embed(cfg, params, tokens, positions, None, True)
    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)

    from building_llm_from_scratch_tpu.ops.decode_step import (
        supports_shape as _fds_supports,
    )

    Tmax = cache["k"][0].shape[2]
    # int8 caches keep the XLA path: decode_attention folds the scale
    # sidecars into its einsums; the pallas kernel has no dequant pass
    # yet (see ops/decode_step.supports_shape)
    use_fused_step = (jax.default_backend() == "tpu"
                      and not _cache_quantized(cache)
                      and _fds_supports(1, Tmax, cfg.head_dim))

    if _use_bgmv(adapter, cfg):
        ids = adapter["ids"].astype(jnp.int32)
        pool_blocks = adapter["pool"]["blocks"]
        adp_layers = [
            _bgmv_block_adp(
                jax.tree_util.tree_map(lambda a, l=l: a[:, l], pool_blocks),
                ids, adapter["scaling"])
            for l in range(cfg.n_layers)
        ]
        # head delta stays on the gathered path (vocab width is not
        # kernel-eligible); the gather is tiny at (S, D, r)/(S, r, V)
        head_rows, head_s = _adapter_rows(
            {"head": adapter["pool"]["head"]}, adapter["scaling"], ids)
        head_node = head_rows["head"]["weight"]
    else:
        adp_layers, head_node, head_s = _slot_adapter_layers(adapter, cfg)

    new = _new_cache_acc(cache)
    for l, (p, K, V) in enumerate(zip(blocks_list, cache["k"], cache["v"])):
        adp = adp_layers[l] if adp_layers is not None else None
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        if use_fused_step:
            from building_llm_from_scratch_tpu.ops.decode_step import (
                fused_decode_step,
            )

            out, K, V = fused_decode_step(q, k.astype(K.dtype),
                                          v.astype(V.dtype), K, V, lengths)
            new["k"].append(K)
            new["v"].append(V)
        else:
            K, V = _slot_append_kv(cache, new, l, K, V, k, v, lengths)
            out = decode_attention(q, K, V, q_positions=positions,
                                   kv_length=lengths + 1,
                                   **_layer_scales(new, l))
        x = x + _attn_out_proj(p["attn"], out, S, 1,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    logits = _head_logits(x, params["head"]["weight"], head_node, head_s)
    return logits[:, 0], new


def verify_slots(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                 lengths: jnp.ndarray, cache: Params,
                 blocks_list: Optional[list] = None,
                 adapter: Optional[Params] = None
                 ) -> Tuple[jnp.ndarray, Params]:
    """Speculative verify: the Tq = k+1 sibling of ``decode_slots``.

    ``tokens`` (S, Tq) is each slot's last accepted token followed by its
    k drafted candidates; ``lengths`` (S,) the valid cache prefix per row.
    ONE forward scores all Tq positions: position j's logits condition on
    [cache, tokens[:, :j+1]], so they are the model's true next-token
    distribution exactly when the drafts before j were all accepted — the
    accept rule (generate.accept_draft_tokens) commits only such
    prefixes. Appends all Tq candidate k/v panes at per-row offsets (the
    same ``slot_cache_append`` batched DUS decode uses, quantize-on-write
    under the int8 policy); the engine advances ``lengths`` by the
    ACCEPTED count only, so a rejected tail's entries sit past the valid
    prefix — masked by ``kv_length`` everywhere and overwritten by the
    next tick's append. No rollback copy exists because none is needed.

    Per-query causality rides the existing ``decode_attention`` per-row
    masks: query j at absolute position lengths+j attends keys at
    positions <= lengths+j, i.e. the real prefix plus the drafts before
    it — never the drafts after it. k is STATIC: every acceptance count
    0..k+1 flows through this one compiled program, preserving the
    engine's one-compiled-program invariant.

    Free/mid-prefill slots ride as ignored rows exactly as in
    ``decode_slots``: their appends land at the row's next write
    position and are overwritten before anything reads them.

    Returns (fp32 logits (S, Tq, V), updated cache).
    """
    rope = _rope_tables(cfg)
    S, Tq = tokens.shape
    lengths = lengths.astype(jnp.int32)
    # position CLAMP: a row near capacity has draft positions past
    # context_length-1; unclamped they would index past the positional
    # tables (jnp.take's out-of-bounds fill is NaN) and the NaN v-pane
    # poisons every query through the value einsum's 0*NaN. Clamped
    # positions only ever affect TAIL candidates that can never be
    # committed (prompt + budget <= max_len by admission), so every
    # committable position keeps its exact positional encoding.
    positions = jnp.minimum(
        lengths[:, None] + jnp.arange(Tq)[None, :],
        cfg.context_length - 1)                                # (S, Tq)
    x = _embed(cfg, params, tokens, positions, None, True)
    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)

    # adapter application mirrors decode_slots' gathered path (the pallas
    # BGMV kernel is single-token-only; a Tq-wide variant is a TPU
    # follow-up — the XLA gather+einsum is the reference either way)
    adp_layers, head_node, head_s = _slot_adapter_layers(adapter, cfg)

    new = _new_cache_acc(cache)
    for l, (p, K, V) in enumerate(zip(blocks_list, cache["k"], cache["v"])):
        adp = adp_layers[l] if adp_layers is not None else None
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        K, V = _slot_append_kv(cache, new, l, K, V, k, v, lengths)
        out = decode_attention(q, K, V, q_positions=positions,
                               kv_length=lengths + Tq,
                               **_layer_scales(new, l))
        x = x + _attn_out_proj(p["attn"], out, S, Tq,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    logits = _head_logits(x, params["head"]["weight"], head_node, head_s)
    return logits, new


# ---------------------------------------------------------------------------
# Paged slot paths (KVCachePolicy.paged; serving/engine.py)
#
# Same programs as the contiguous slot paths above with ONE layout change:
# a row no longer owns a contiguous (Tmax,) lane — a per-slot int32 page
# table maps each row's logical positions onto fixed-size pages of a
# shared pool (cache leaves are (n_pages, Hkv, page_tokens, hd)). The
# table rides every call as traced DATA against static shapes (the
# adapter-pool trick), so page churn — prefix hits, frees, eviction,
# oversubscription — never recompiles anything.
#
# Bit-parity with the contiguous layout is by construction: appends write
# identical values at identical logical positions (the int8 quantization
# grouping — per written position per head — is unchanged), the gather
# view reassembles each row into the exact (S, Hkv, cache_len, ...)
# buffer ``decode_attention`` saw before, and every position where the
# two layouts could disagree (stale pool bytes vs. a row's leftover lane
# garbage) is masked by ``kv_length`` in both — masked weights are
# exactly zero and pool contents are always finite, so masked values
# never reach the output.
#
# Table entry 0 is the TRASH PAGE: unallocated logical positions (a free
# row's garbage-lane append, a final chunk's pad tail past the prompt)
# scatter there and are only ever read masked. Duplicate scatter indices
# therefore only ever collide on the trash page or on pad zeros — the
# nondeterminism XLA allows for them can never reach an unmasked read.
# ---------------------------------------------------------------------------

def _paged_scatter(cache: Params, name: str, vals: jnp.ndarray,
                   phys: jnp.ndarray, off: jnp.ndarray, new: Params) -> None:
    """Scatter ``vals`` (R, Hkv, hd) — R written logical positions — into
    the pool leaf at rows ``phys`` (R,) page ids / ``off`` (R,) in-page
    offsets, quantizing on write under the int8 policy exactly like
    ``_slot_write`` (same per-position per-head scale grouping, so codes
    and sidecars are bitwise identical to the contiguous layout's)."""
    buf = cache[name][len(new[name])]
    if _cache_quantized(cache):
        from building_llm_from_scratch_tpu.ops.decode_step import quantize_kv

        codes, scale = quantize_kv(vals)
        sbuf = cache[name + "_scale"][len(new[name + "_scale"])]
        new[name + "_scale"].append(sbuf.at[phys, :, off].set(scale))
        vals = codes
    new[name].append(buf.at[phys, :, off].set(vals.astype(buf.dtype)))


def _paged_append_kv(cache: Params, new: Params, l: int,
                     k: jnp.ndarray, v: jnp.ndarray,
                     lengths: jnp.ndarray, page_table: jnp.ndarray,
                     cache_len: int) -> None:
    """Paged sibling of ``_slot_append_kv``: append one layer's fresh
    k/v (model layout (S, Tq, Hkv, hd)) at each row's logical offsets,
    routed through the page table. Positions clamp to ``cache_len - 1``
    (only ever binding for garbage lanes that are masked everywhere,
    mirroring ``verify_slots``' position clamp)."""
    S, Tq = k.shape[:2]
    P = cache["k"][l].shape[2]
    pos = jnp.minimum(lengths[:, None] + jnp.arange(Tq)[None, :],
                      cache_len - 1)                        # (S, Tq)
    phys = jnp.take_along_axis(page_table, pos // P, axis=1).reshape(-1)
    off = (pos % P).reshape(-1)
    _paged_scatter(cache, "k", k.reshape(S * Tq, *k.shape[2:]), phys, off,
                   new)
    _paged_scatter(cache, "v", v.reshape(S * Tq, *v.shape[2:]), phys, off,
                   new)


def _paged_view(leaf: jnp.ndarray, page_table: jnp.ndarray,
                cache_len: int) -> jnp.ndarray:
    """Gather a (rows, Hkv, cache_len, ...) row-major view out of the
    pool leaf (n_pages, Hkv, P, ...) through the page table (rows, M):
    the XLA reference for page-table attention — downstream
    ``decode_attention`` is completely unchanged, which is what pins
    bit-parity. The TPU pallas kernel (ops/decode_step.paged_gather_kv)
    computes the same gather without materializing it per layer."""
    g = leaf[page_table]                    # (rows, M, Hkv, P, ...)
    g = jnp.moveaxis(g, 2, 1)               # (rows, Hkv, M, P, ...)
    shape = g.shape
    g = g.reshape(shape[0], shape[1], shape[2] * shape[3], *shape[4:])
    return g[:, :, :cache_len]


def _paged_layer_kv(new: Params, l: int, page_table: jnp.ndarray,
                    cache_len: int):
    """(K, V, scale kwargs) row views for layer ``l`` AFTER its paged
    append — the paged sibling of slicing ``new['k'][l]`` directly plus
    ``_layer_scales``."""
    K = _paged_view(new["k"][l], page_table, cache_len)
    V = _paged_view(new["v"][l], page_table, cache_len)
    scales = {}
    if "k_scale" in new:
        scales = {
            "k_scale": _paged_view(new["k_scale"][l], page_table, cache_len),
            "v_scale": _paged_view(new["v_scale"][l], page_table, cache_len),
        }
    return K, V, scales


def _use_paged_attn(cache: Params, cfg: ModelConfig) -> bool:
    """Route decode attention through the pallas page-gather kernel
    (ops/decode_step.paged_decode_attention). Opt-in via BLLM_PAGED_ATTN=1
    on TPU — the same off-until-hardware-A/B discipline as
    BLLM_FUSED_DECODE/BLLM_BGMV — and only for unquantized pools of
    kernel-eligible shape; the XLA gather view is the reference."""
    import os as _os

    if jax.default_backend() != "tpu" or _cache_quantized(cache):
        return False
    if _os.environ.get("BLLM_PAGED_ATTN", "0") != "1":
        return False
    from building_llm_from_scratch_tpu.ops.decode_step import (
        supports_paged_shape,
    )

    return supports_paged_shape(1, cache["k"][0].shape[2], cfg.head_dim)


def paged_decode_slots(params: Params, cfg: ModelConfig,
                       tokens: jnp.ndarray, lengths: jnp.ndarray,
                       page_table: jnp.ndarray, cache: Params,
                       blocks_list: Optional[list] = None,
                       adapter: Optional[Params] = None, *,
                       cache_len: int) -> Tuple[jnp.ndarray, Params]:
    """Paged sibling of ``decode_slots``: one decode tick over the slot
    batch with every cache read/write routed through ``page_table``
    ((S, max_pages) int32, traced data). ``cache_len`` is the static
    logical row length (the engine's ``_cache_len``), identical to the
    contiguous buffer width — so the reassembled row views, masks, and
    therefore logits are bit-identical to the contiguous program's."""
    rope = _rope_tables(cfg)
    S = tokens.shape[0]
    lengths = lengths.astype(jnp.int32)
    page_table = page_table.astype(jnp.int32)
    positions = lengths[:, None]                       # (S, 1)
    x = _embed(cfg, params, tokens, positions, None, True)
    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)
    adp_layers, head_node, head_s = _slot_adapter_layers(adapter, cfg)
    use_paged_attn = _use_paged_attn(cache, cfg)

    new = _new_cache_acc(cache)
    for l, p in enumerate(blocks_list):
        adp = adp_layers[l] if adp_layers is not None else None
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        _paged_append_kv(cache, new, l, k, v, lengths, page_table,
                         cache_len)
        if use_paged_attn:
            from building_llm_from_scratch_tpu.ops.decode_step import (
                paged_decode_attention,
            )

            out = paged_decode_attention(q, new["k"][l], new["v"][l],
                                         page_table, lengths)
        else:
            K, V, scales = _paged_layer_kv(new, l, page_table, cache_len)
            out = decode_attention(q, K, V, q_positions=positions,
                                   kv_length=lengths + 1, **scales)
        x = x + _attn_out_proj(p["attn"], out, S, 1,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    logits = _head_logits(x, params["head"]["weight"], head_node, head_s)
    return logits[:, 0], new


def paged_verify_slots(params: Params, cfg: ModelConfig,
                       tokens: jnp.ndarray, lengths: jnp.ndarray,
                       page_table: jnp.ndarray, cache: Params,
                       blocks_list: Optional[list] = None,
                       adapter: Optional[Params] = None, *,
                       cache_len: int) -> Tuple[jnp.ndarray, Params]:
    """Paged sibling of ``verify_slots`` (Tq = k+1 speculative verify):
    candidate k/v scatter at per-row logical offsets through the table,
    rejected tails sit past ``kv_length`` exactly as before — masked
    everywhere and overwritten by the next tick's append."""
    rope = _rope_tables(cfg)
    S, Tq = tokens.shape
    lengths = lengths.astype(jnp.int32)
    page_table = page_table.astype(jnp.int32)
    positions = jnp.minimum(
        lengths[:, None] + jnp.arange(Tq)[None, :],
        cfg.context_length - 1)                                # (S, Tq)
    x = _embed(cfg, params, tokens, positions, None, True)
    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)
    adp_layers, head_node, head_s = _slot_adapter_layers(adapter, cfg)

    new = _new_cache_acc(cache)
    for l, p in enumerate(blocks_list):
        adp = adp_layers[l] if adp_layers is not None else None
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        _paged_append_kv(cache, new, l, k, v, lengths, page_table,
                         cache_len)
        K, V, scales = _paged_layer_kv(new, l, page_table, cache_len)
        out = decode_attention(q, K, V, q_positions=positions,
                               kv_length=lengths + Tq, **scales)
        x = x + _attn_out_proj(p["attn"], out, S, Tq,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    logits = _head_logits(x, params["head"]["weight"], head_node, head_s)
    return logits, new


def paged_prefill_chunk_into_slot(params: Params, cfg: ModelConfig,
                                  tokens: jnp.ndarray,
                                  chunk_start: jnp.ndarray,
                                  prompt_len: jnp.ndarray,
                                  slot: jnp.ndarray,
                                  page_table: jnp.ndarray, cache: Params,
                                  blocks_list: Optional[list] = None,
                                  adapter: Optional[Params] = None, *,
                                  cache_len: int
                                  ) -> Tuple[jnp.ndarray, Params]:
    """Paged sibling of ``prefill_chunk_into_slot``: the chunk's C
    positions scatter into row ``slot``'s pages, and attention gathers
    that one row's view through its table lane. Pad positions past the
    prompt write zeros (the same determinism rule as contiguous); any
    position past the row's allocated frontier lands on the trash page
    — never read unmasked either way."""
    _, C = tokens.shape
    rope = _rope_tables(cfg)
    positions = chunk_start + jnp.arange(C)
    x = _embed(cfg, params, tokens, positions, None, True)
    if blocks_list is None:
        blocks_list = unstack_blocks(params, cfg)
    adp_layers, head_node, head_s = _slot_adapter_layers(adapter, cfg)
    valid = (positions < prompt_len)[None, :, None, None]
    kv_len = jnp.reshape(jnp.minimum(chunk_start + C, prompt_len), (1,))
    q_pos = positions[None, :]                       # (1, C) per-row form
    page_table = page_table.astype(jnp.int32)
    P = cache["k"][0].shape[2]
    row_tab = jax.lax.dynamic_slice(
        page_table, (slot, 0), (1, page_table.shape[1]))     # (1, M)
    pos = jnp.minimum(positions, cache_len - 1)              # (C,)
    phys = row_tab[0, pos // P]
    off = pos % P
    new = _new_cache_acc(cache)
    for l, p in enumerate(blocks_list):
        adp = adp_layers[l] if adp_layers is not None else None
        h = _norm(cfg, p["norm1"], x)
        q, k, v = _qkv_proj(cfg, p["attn"], h, rope, positions,
                            adp=adp["attn"] if adp is not None else None)
        k = jnp.where(valid, k, jnp.zeros((), k.dtype))
        v = jnp.where(valid, v, jnp.zeros((), v.dtype))
        _paged_scatter(cache, "k", k[0], phys, off, new)
        _paged_scatter(cache, "v", v[0], phys, off, new)
        K_row, V_row, scales = _paged_layer_kv(new, l, row_tab, cache_len)
        out = decode_attention(q, K_row, V_row, q_positions=q_pos,
                               kv_length=kv_len, **scales)
        x = x + _attn_out_proj(p["attn"], out, 1, C,
                               adp=adp["attn"] if adp is not None else None)
        x = x + _mlp(cfg, p["mlp"], _norm(cfg, p["norm2"], x),
                     adp=adp["mlp"] if adp is not None else None)
    x = _norm(cfg, params["final_norm"], x)
    idx = jnp.clip(prompt_len - 1 - chunk_start, 0, C - 1)
    last = jax.lax.dynamic_slice(x, (0, idx, 0), (1, 1, x.shape[-1]))
    logits = _head_logits(last, params["head"]["weight"], head_node, head_s)
    return logits[0, 0], new
