"""LoRA as a pytree partition.

The reference implements LoRA by recursive nn.Module surgery — freezing all
params, then replacing every nn.Linear with LinearWithLoRA
(lora.py:29-65, build_components.py:117-135). Here adapters are a SEPARATE
pytree mirroring the model's linear weights:

  lora = {
    "blocks": {"attn": {"wq": {"A": (L, in, r), "B": (L, r, out)}, ...},
               "mlp":  {...}},
    "head":   {"weight": {"A": (in, r), "B": (r, out)}},
  }

Training uses the partition directly: the optimizer sees ONLY the lora tree
(so "freezing" is structural, not a requires_grad flag), and the forward
pass runs on ``merge_lora(params, lora, scaling)`` — W' = W + (alpha/r)*A@B,
which XLA fuses into the surrounding matmuls. Gradients flow to A/B through
the merge; base weights are never touched.

Matches the reference's placement: every Linear gets an adapter (all
attention projections, all MLP projections, and the LM head — reference
replace_linear_with_lora walks every nn.Linear, lora.py:49-65); embeddings
do not (nn.Embedding is not nn.Linear).

Init parity (reference lora.py:6-26): A ~ kaiming-uniform(a=sqrt(5)) over
(in, r), B = 0, scaling = alpha / rank.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from building_llm_from_scratch_tpu.configs import ModelConfig

Params = Dict[str, Any]

# model-tree linear weights that receive adapters: path -> (stacked?, in_axis)
_ADAPTED = {
    ("blocks", "attn", "wq"),
    ("blocks", "attn", "wk"),
    ("blocks", "attn", "wv"),
    ("blocks", "attn", "wo"),
    ("blocks", "mlp", "up"),
    ("blocks", "mlp", "down"),
    ("blocks", "mlp", "gate"),
    ("head", "weight"),
}


def _kaiming_uniform(key, shape, fan_in: int, dtype):
    # torch kaiming_uniform_(a=sqrt(5)) => U(-1/sqrt(fan_in), 1/sqrt(fan_in))
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound
                              ).astype(dtype)


def init_lora_params(cfg: ModelConfig, params: Params, key: jax.Array,
                     rank: int) -> Params:
    """Build the adapter tree for every adapted linear in ``params``."""
    dt = cfg.jax_dtype
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out: Params = {}
    keys = jax.random.split(key, len(flat))
    for (path, leaf), k in zip(flat, keys):
        names = tuple(p.key for p in path)
        if names not in _ADAPTED:
            continue
        if leaf.ndim == 3:            # stacked per-layer weight (L, in, out)
            L, fan_in, fan_out = leaf.shape
            a = _kaiming_uniform(k, (L, fan_in, rank), fan_in, dt)
            b = jnp.zeros((L, rank, fan_out), dt)
        else:                         # (in, out), e.g. the head
            fan_in, fan_out = leaf.shape
            a = _kaiming_uniform(k, (fan_in, rank), fan_in, dt)
            b = jnp.zeros((rank, fan_out), dt)
        node = out
        for name in names[:-1]:
            node = node.setdefault(name, {})
        node[names[-1]] = {"A": a, "B": b}
    return out


def merge_lora(params: Params, lora: Params, alpha: float, rank: int) -> Params:
    """Return params with W' = W + (alpha/rank) * A @ B on adapted weights.

    Pure and differentiable — grads w.r.t. ``lora`` flow through the merge
    while ``params`` stays a constant of the step.
    """
    scaling = alpha / rank

    def walk(p_node, l_node):
        merged = {}
        for name, child in p_node.items():
            l_child = l_node.get(name) if isinstance(l_node, dict) else None
            if isinstance(child, dict):
                merged[name] = walk(child, l_child or {})
            elif (isinstance(l_child, dict) and "A" in l_child):
                a, b = l_child["A"], l_child["B"]
                delta = jnp.einsum("...ir,...ro->...io", a, b)
                merged[name] = child + scaling * delta.astype(child.dtype)
            else:
                merged[name] = child
        return merged

    return walk(params, lora)


def count_lora_params(lora: Params) -> int:
    """Trainable-parameter count (reference build_components.py:131-135)."""
    import numpy as np

    return int(sum(np.prod(l.shape)
                   for l in jax.tree_util.tree_leaves(lora)))
