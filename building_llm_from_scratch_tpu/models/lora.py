"""LoRA as a pytree partition.

The reference implements LoRA by recursive nn.Module surgery — freezing all
params, then replacing every nn.Linear with LinearWithLoRA
(lora.py:29-65, build_components.py:117-135). Here adapters are a SEPARATE
pytree mirroring the model's linear weights:

  lora = {
    "blocks": {"attn": {"wq": {"A": (L, in, r), "B": (L, r, out)}, ...},
               "mlp":  {...}},
    "head":   {"weight": {"A": (in, r), "B": (r, out)}},
  }

Training uses the partition directly: the optimizer sees ONLY the lora tree
(so "freezing" is structural, not a requires_grad flag), and the forward
pass runs on ``merge_lora(params, lora, scaling)`` — W' = W + (alpha/r)*A@B,
which XLA fuses into the surrounding matmuls. Gradients flow to A/B through
the merge; base weights are never touched.

Matches the reference's placement: every Linear gets an adapter (all
attention projections, all MLP projections, and the LM head — reference
replace_linear_with_lora walks every nn.Linear, lora.py:49-65); embeddings
do not (nn.Embedding is not nn.Linear).

Init parity (reference lora.py:6-26): A ~ kaiming-uniform(a=sqrt(5)) over
(in, r), B = 0, scaling = alpha / rank.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig

Params = Dict[str, Any]

#: adapter artifact (.npz) format version — bump on layout changes
ADAPTER_FORMAT_VERSION = 1

# model-tree linear weights that receive adapters: path -> (stacked?, in_axis)
_ADAPTED = {
    ("blocks", "attn", "wq"),
    ("blocks", "attn", "wk"),
    ("blocks", "attn", "wv"),
    ("blocks", "attn", "wo"),
    ("blocks", "mlp", "up"),
    ("blocks", "mlp", "down"),
    ("blocks", "mlp", "gate"),
    ("head", "weight"),
}


def _kaiming_uniform(key, shape, fan_in: int, dtype):
    # torch kaiming_uniform_(a=sqrt(5)) => U(-1/sqrt(fan_in), 1/sqrt(fan_in))
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound
                              ).astype(dtype)


def init_lora_params(cfg: ModelConfig, params: Params, key: jax.Array,
                     rank: int) -> Params:
    """Build the adapter tree for every adapted linear in ``params``."""
    dt = cfg.jax_dtype
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out: Params = {}
    keys = jax.random.split(key, len(flat))
    for (path, leaf), k in zip(flat, keys):
        names = tuple(p.key for p in path)
        if names not in _ADAPTED:
            continue
        if leaf.ndim == 3:            # stacked per-layer weight (L, in, out)
            L, fan_in, fan_out = leaf.shape
            a = _kaiming_uniform(k, (L, fan_in, rank), fan_in, dt)
            b = jnp.zeros((L, rank, fan_out), dt)
        else:                         # (in, out), e.g. the head
            fan_in, fan_out = leaf.shape
            a = _kaiming_uniform(k, (fan_in, rank), fan_in, dt)
            b = jnp.zeros((rank, fan_out), dt)
        node = out
        for name in names[:-1]:
            node = node.setdefault(name, {})
        node[names[-1]] = {"A": a, "B": b}
    return out


def merge_lora(params: Params, lora: Params, alpha: float, rank: int) -> Params:
    """Return params with W' = W + (alpha/rank) * A @ B on adapted weights.

    Pure and differentiable — grads w.r.t. ``lora`` flow through the merge
    while ``params`` stays a constant of the step.
    """
    scaling = alpha / rank

    def walk(p_node, l_node):
        merged = {}
        for name, child in p_node.items():
            l_child = l_node.get(name) if isinstance(l_node, dict) else None
            if isinstance(child, dict):
                merged[name] = walk(child, l_child or {})
            elif (isinstance(l_child, dict) and "A" in l_child):
                a, b = l_child["A"], l_child["B"]
                delta = jnp.einsum("...ir,...ro->...io", a, b)
                merged[name] = child + scaling * delta.astype(child.dtype)
            else:
                merged[name] = child
        return merged

    return walk(params, lora)


def apply_lora(x: jnp.ndarray, w: jnp.ndarray, node: Optional[Params],
               scaling=None) -> jnp.ndarray:
    """Merge-free adapted projection: ``x @ w + s * ((x @ A) @ B)``.

    The unmerged twin of ``merge_lora`` — same math, applied at the
    activation instead of the weight, so ONE base ``w`` serves many
    adapters at once (the multi-tenant serving requirement; merging
    would need a weight copy per adapter). Shared by the trainer's
    eval sampling (``generate(..., lora=...)``) and the serving
    engine's per-slot path (models/transformer.py slot functions).

    ``node``: ``{"A", "B"}``, either unbatched (``(in, r)``/``(r, out)``
    — one adapter for the whole batch) or per-row batched
    (``(B, in, r)``/``(B, r, out)`` — the BGMV gather output, shared by
    the serving engine's slot paths AND the fused multi-LoRA TRAINING
    forward, ``forward(..., adapter=)`` / training/lora_fusion.py: the
    gather's transpose scatter-adds each row's gradient into its own
    pool row, which is what makes k jobs trainable through one base
    backward).
    ``None`` returns exactly ``x @ w`` (bit-identical base path).
    ``scaling``: alpha/rank — a scalar, or ``(B,)`` per-row scales
    (0 = zero delta, the id −1 base-model row). A node carrying a
    ``"bgmv"`` entry routes the delta through the fused TPU kernel
    (ops/decode_step.lora_bgmv) instead of the gathered einsum.
    """
    h = x @ w
    if node is None:
        return h
    if "bgmv" in node:
        from building_llm_from_scratch_tpu.ops.decode_step import lora_bgmv

        a_pool, b_pool, ids, scales = node["bgmv"]
        # x (S, 1, D) single-token decode rows -> (S, D); kernel returns
        # the already-scaled (S, O) delta
        delta = lora_bgmv(x[:, 0], a_pool, b_pool, ids, scales)
        return h + delta[:, None].astype(h.dtype)
    if "aligned" in node:
        # slot-ALIGNED pool application (fused multi-LoRA training):
        # the batch's rows are laid out job-contiguously, so each job's
        # A/B multiplies ONCE against its own (R*T)-row block instead of
        # being gather-duplicated R-fold per row
        a, b, s, rows_per_job = node["aligned"]
        return h + aligned_lora_delta(x, a, b, s,
                                      rows_per_job).astype(h.dtype)
    return h + lora_delta(x, node, scaling).astype(h.dtype)


def lora_delta(x: jnp.ndarray, node: Params, scaling) -> jnp.ndarray:
    """The scaled unmerged delta ``s * ((x @ A) @ B)`` — ONE definition
    of the application math, shared by ``apply_lora`` and the LM-head
    path (models/transformer._head_logits), so scaling/broadcast
    semantics cannot drift between projection sites. ``scaling`` is a
    scalar or per-row ``(B,)``."""
    delta = (x @ node["A"]) @ node["B"]
    s = jnp.asarray(scaling, jnp.float32)
    if s.ndim == 1:                       # (B,) per-row scales
        s = s[:, None, None]
    return s * delta


def aligned_lora_delta(x: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray,
                       scaling, rows_per_job: int) -> jnp.ndarray:
    """Slot-aligned per-JOB delta: ``x`` (B, T, in) whose rows are laid
    out job-contiguously (row block [j*R, (j+1)*R) belongs to job j —
    the ``stack_fleet_batch`` layout) against a stacked pool ``a``
    (J, in, r) / ``b`` (J, r, out) with per-job ``scaling`` (J,).

    The mathematical twin of the per-row gather (``_adapter_rows`` +
    ``lora_delta``) for that layout, WITHOUT materializing each job's
    A/B once per row: reshape to (J, R*T, in) and batch-matmul each
    job's block against its adapter exactly once — the backward
    correspondingly writes each job's gradient block straight into its
    pool row instead of scatter-adding R duplicates (ROADMAP PR 12
    follow-up; parity vs the gather path is test-pinned)."""
    B, T, _ = x.shape
    J = a.shape[0]
    xj = x.reshape(J, rows_per_job * T, -1)
    d = jnp.einsum("jti,jir->jtr", xj, a)
    d = jnp.einsum("jtr,jro->jto", d, b)
    s = jnp.asarray(scaling, jnp.float32)[:, None, None]
    return (s * d).reshape(B, T, -1)


def count_lora_params(lora: Params) -> int:
    """Trainable-parameter count (reference build_components.py:131-135)."""
    return int(sum(np.prod(l.shape)
                   for l in jax.tree_util.tree_leaves(lora)))


# ---------------------------------------------------------------------------
# Adapter artifacts (.npz): the finetune -> serve hand-off
# ---------------------------------------------------------------------------

#: ModelConfig fields that define the ARCHITECTURE an adapter was trained
#: against. dtype / attn_impl / remat are runtime choices — an adapter is
#: portable across them — but any mismatch here means the A/B matrices
#: multiply against different-shaped (or differently-wired) weights.
_FINGERPRINT_FIELDS = (
    "name", "vocab_size", "context_length", "emb_dim", "n_heads",
    "n_layers", "hidden_dim", "n_kv_groups", "norm", "positional",
    "activation", "qkv_bias", "attn_out_bias", "mlp_bias", "norm_bias",
)


def adapter_fingerprint(cfg: ModelConfig) -> str:
    """Short stable hash of the base architecture an adapter binds to."""
    ident = {f: getattr(cfg, f) for f in _FINGERPRINT_FIELDS}
    blob = json.dumps(ident, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _flatten_adapter(lora: Params) -> Dict[str, Any]:
    flat = jax.tree_util.tree_flatten_with_path(lora)[0]
    return {".".join(p.key for p in path): leaf for path, leaf in flat}


def save_adapter(path: str, lora: Params, *, rank: int, alpha: float,
                 cfg: ModelConfig) -> str:
    """Write one LoRA adapter as a standalone npz artifact: the A/B tree
    (dotted-path keys), per-array dtypes (np.savez stores ml_dtypes
    arrays as raw void bytes — same trick as ``checkpoint.export_params``)
    and a JSON metadata record carrying (rank, alpha, base-config
    fingerprint). The serving ``AdapterRegistry`` refuses artifacts whose
    fingerprint does not match its loaded base model."""
    arrays: Dict[str, Any] = {}
    for key, leaf in _flatten_adapter(lora).items():
        arr = np.asarray(jax.device_get(leaf))
        arrays[key] = arr
        arrays[f"__dtype__.{key}"] = np.asarray(str(arr.dtype))
    meta = {
        "format": ADAPTER_FORMAT_VERSION,
        "rank": int(rank),
        "alpha": float(alpha),
        "fingerprint": adapter_fingerprint(cfg),
        "model": cfg.name,
    }
    arrays["__adapter_meta__"] = np.asarray(json.dumps(meta))
    if jax.process_index() == 0:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        np.savez(tmp, **arrays)
        # np.savez appends .npz to paths without it
        os.replace(tmp if os.path.exists(tmp) else tmp + ".npz", path)
    return path


def load_adapter(path: str) -> Tuple[Params, Dict[str, Any]]:
    """Load a ``save_adapter`` artifact -> (lora tree of np arrays, meta).

    Raises ``ValueError`` for files without adapter metadata (a model
    export or token cache passed by mistake) or from a newer format."""
    data = np.load(path, allow_pickle=False)
    if "__adapter_meta__" not in data:
        raise ValueError(
            f"{path} is not an adapter artifact (no __adapter_meta__; "
            "write one with --save_adapter / models.lora.save_adapter)")
    meta = json.loads(str(data["__adapter_meta__"]))
    if meta.get("format", 0) > ADAPTER_FORMAT_VERSION:
        raise ValueError(
            f"{path}: adapter format {meta.get('format')} is newer than "
            f"this build supports ({ADAPTER_FORMAT_VERSION})")
    lora: Params = {}
    for key in data.files:
        if key.startswith("__"):
            continue
        arr = data[key]
        dt_key = f"__dtype__.{key}"
        if dt_key in data:
            # np.load returns ml_dtypes arrays (bf16) as raw void bytes; a
            # view restores them losslessly (checkpoint._restore_dtype)
            target = np.dtype(str(data[dt_key]))
            if arr.dtype != target:
                arr = (arr.view(target)
                       if (arr.dtype.kind == "V"
                           and arr.dtype.itemsize == target.itemsize)
                       else arr.astype(target))
        node = lora
        parts = key.split(".")
        for name in parts[:-1]:
            node = node.setdefault(name, {})
        node[parts[-1]] = arr
    return lora, meta
