"""Model construction (reference: build_components.py:189-205).

Every architecture is the shared transformer core plus a ``ModelConfig``;
``build_model`` returns (config, params).
"""

from typing import Optional, Tuple

import jax

from building_llm_from_scratch_tpu.configs import ModelConfig, get_config
from building_llm_from_scratch_tpu.models.transformer import (
    decode_slots,
    forward,
    forward_with_cache,
    init_cache,
    init_params,
    init_slot_cache,
    prefill_into_slot,
)

__all__ = [
    "build_model",
    "decode_slots",
    "forward",
    "forward_with_cache",
    "init_cache",
    "init_params",
    "init_slot_cache",
    "prefill_into_slot",
]


def build_model(model: str, num_params: str, key: Optional[jax.Array] = None,
                **cfg_overrides) -> Tuple[ModelConfig, dict]:
    """Instantiate (config, params) for a named model + size.

    Mirrors the reference factory dispatch (build_components.py:198-205) where
    each name maps to a different class; here it is one core + config lookup.
    """
    cfg = get_config(model, num_params, **cfg_overrides)
    if key is None:
        key = jax.random.PRNGKey(0)
    params = init_params(cfg, key)
    return cfg, params
