"""GL04x — telemetry-schema lint: event call sites vs obs/schema.py.

Every ``.event("kind", ...)`` / ``emit_event("kind", ...)`` call site in
the package is checked against the declared registry:

  - **GL041** — the kind is not registered (a typo'd event name would
    otherwise produce rows no consumer ever joins);
  - **GL042** — an explicit keyword names a field the kind does not
    declare (drift between emitter and the renderer/trace consumers);
  - **GL043** — a required field is missing. Only checkable when the
    call passes no ``**kwargs`` (dynamic payloads skip this check but
    still get their explicit keywords validated);
  - **GL044** — a module outside ``obs/schema.py`` re-declares one of
    the schema's table constants (``TICK_PHASES`` & co): the exact
    drift-prone-copy failure mode PR 7's review caught by hand.

Only literal-string kinds are checked; a dynamic first argument is
invisible to static analysis (none exist in the repo today — keeping it
that way is the point of the lint).
"""

from __future__ import annotations

import ast
from typing import List

from building_llm_from_scratch_tpu.analysis.base import (
    Finding,
    ParsedModule,
    call_name,
    iter_functions,
    load_schema_module,
)

# loaded by file path so the lint gate stays stdlib-only (a package
# import of obs.schema would initialize obs/__init__ and pull in jax)
_SCHEMA = load_schema_module()
EVENTS = _SCHEMA.EVENTS
ALWAYS_ALLOWED_FIELDS = _SCHEMA.ALWAYS_ALLOWED_FIELDS

#: attribute / function names whose calls emit an event row with the
#: kind as first positional argument
_EVENT_ATTRS = {"event"}
_EVENT_FUNCS = {"emit_event"}

#: schema-owned table constants: redefining one of these outside the
#: schema module is GL044
_SCHEMA_TABLES = {"TICK_PHASES", "TRAIN_SEGMENTS", "INCIDENT_EVENTS",
                  "REQUEST_EVENTS", "SERVING_LIFECYCLE_EVENTS",
                  "SPAN_NAMES", "REQUEST_SPAN_PHASES"}

_SCHEMA_MODULE = "building_llm_from_scratch_tpu/obs/schema.py"


def _is_event_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _EVENT_ATTRS:
        # exclude unrelated .event attributes: require the object to be
        # name-shaped metrics plumbing (sink / self.metrics_sink /
        # get_metrics() / logger); conservative — a miss here is a
        # false negative, not a false positive
        base = func.value
        if isinstance(base, ast.Call):
            return call_name(base.func).endswith("get_metrics")
        name = call_name(base)
        return name.split(".")[-1] in ("sink", "metrics_sink", "metrics",
                                       "logger", "_global_logger", "m")
    if isinstance(func, ast.Name):
        return func.id in _EVENT_FUNCS
    return False


def _qual_for(mod: ParsedModule, node: ast.AST) -> str:
    best = ""
    target = getattr(node, "lineno", 0)
    for qualname, _cls, fn in iter_functions(mod.tree):
        if fn.lineno <= target <= (fn.end_lineno or fn.lineno):
            if len(qualname) > len(best) or not best:
                best = qualname
    return best


def check_module(mod: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []

    def emit(rule: str, node: ast.AST, message: str) -> None:
        f = mod.finding(rule, node, message, _qual_for(mod, node))
        if f is not None:
            findings.append(f)

    # GL044: schema-table redeclaration outside the schema module
    if mod.relpath != _SCHEMA_MODULE:
        for node in mod.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id in _SCHEMA_TABLES:
                    emit("GL044", node,
                         f"private copy of schema table {tgt.id} — "
                         f"import it from obs/schema.py instead "
                         f"(drift here is invisible to consumers)")

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _is_event_call(node):
            continue
        if not node.args:
            continue
        kind_node = node.args[0]
        if not (isinstance(kind_node, ast.Constant)
                and isinstance(kind_node.value, str)):
            continue                      # dynamic kind: not checkable
        kind = kind_node.value
        spec = EVENTS.get(kind)
        if spec is None:
            emit("GL041", node,
                 f"event kind '{kind}' is not registered in "
                 f"obs/schema.py — declare an EventSpec for it")
            continue
        explicit = {kw.arg for kw in node.keywords if kw.arg is not None}
        has_dynamic = any(kw.arg is None for kw in node.keywords)
        if not spec.open_fields:
            unknown = explicit - spec.known_fields()
            for fieldname in sorted(unknown):
                emit("GL042", node,
                     f"event '{kind}' does not declare field "
                     f"'{fieldname}' — add it to the EventSpec or fix "
                     f"the call site")
        if not has_dynamic:
            missing = spec.required - explicit - ALWAYS_ALLOWED_FIELDS
            if missing:
                emit("GL043", node,
                     f"event '{kind}' missing required field(s) "
                     f"{sorted(missing)}")
    return findings
