"""graft-lint core: parsed-module model, findings, and suppressions.

The analyzers (``hostsync``/``jitpurity``/``locks``/``telemetry``) are
stdlib-``ast`` passes over ``ParsedModule`` objects. Everything comment-
shaped (suppressions, ``# guarded-by:`` / ``# holds:`` / ``# graft:
hot-path`` annotations) lives here because ``ast`` drops comments: the
annotations are recovered from the raw source lines and joined to nodes
by line number.

Inline suppression grammar (same line as the finding, or the line above
when the flagged line has no room):

    x = float(lr)            # graft-ok: GL011 cadence-time fetch
    y = np.asarray(v)        # graft-ok: GL01x host numpy, not device

A suppression names one or more rule ids (comma-separated); a family id
ending in ``x`` (``GL01x``) matches every rule in the family. Suppressed
findings are dropped before baseline comparison — the baseline is for
repo-level debt with reasons, suppressions for point decisions the
adjacent code explains.
"""

from __future__ import annotations

import ast
import hashlib
import importlib.util
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


def load_schema_module():
    """Load ``obs/schema.py`` by FILE PATH, bypassing the ``obs`` package
    ``__init__`` (which imports the jax-backed observability stack —
    ~1s and a hard jax dependency, measured). This keeps the lint gate
    and the telemetry renderer genuinely stdlib-only. When the package
    is already imported (tests, in-process use), the real module is
    reused so identity checks (``trace.TICK_PHASES is
    schema.TICK_PHASES``) keep holding."""
    mod = sys.modules.get("building_llm_from_scratch_tpu.obs.schema")
    if mod is not None:
        return mod
    name = "_graft_obs_schema"
    mod = sys.modules.get(name)
    if mod is not None:
        return mod
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "obs", "schema.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    # registered BEFORE exec: dataclass processing resolves the module's
    # (string, via __future__ annotations) field types through
    # sys.modules[cls.__module__]
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod

#: rule id -> one-line description (the catalog; README mirrors it).
RULES: Dict[str, str] = {
    "GL011": "implicit device->host scalar conversion (float/int/bool) "
             "in a registered hot path",
    "GL012": "implicit device->host array materialization (np.asarray/"
             "np.array/.tolist) in a registered hot path",
    "GL013": ".item() device fetch in a registered hot path",
    "GL021": "print() side effect inside a jit-compiled function",
    "GL022": "wall-clock (time.*) call inside a jit-compiled function",
    "GL023": "host RNG (random.*/np.random.*) inside a jit-compiled "
             "function",
    "GL024": "Python branching on a traced (non-static) argument inside "
             "a jit-compiled function",
    "GL025": "closure/state mutation (global/nonlocal/self.attr write) "
             "inside a jit-compiled function",
    "GL026": "jax.jit of a callable constructed inside a function "
             "(fresh jit cache per call: recompiles every invocation)",
    "GL031": "field annotated '# guarded-by: <lock>' touched outside "
             "the named lock",
    "GL032": "lock-acquisition ordering cycle (deadlock hazard)",
    "GL033": "guarded-by annotation names a lock the class never defines",
    "GL041": "telemetry event kind not in the obs/schema.py registry",
    "GL042": "telemetry event field not declared for its kind in "
             "obs/schema.py",
    "GL043": "telemetry event missing a required field at the call site",
    "GL044": "private redeclaration of an obs/schema.py table "
             "(schema drift hazard)",
}

_SUPPRESS_RE = re.compile(r"#\s*graft-ok:\s*([^#\n]+)")
_RULE_TOKEN_RE = re.compile(r"^GL\d+x?$")
_GUARDED_RE = re.compile(
    r"#\s*guarded-by:\s*(\w+)\s*(\[writes\])?")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([\w,\s]+)")
_HOT_RE = re.compile(r"#\s*graft:\s*hot-path")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str                 # repo-relative, forward slashes
    line: int
    message: str
    qualname: str = ""        # enclosing Class.method or function
    text: str = ""            # stripped source line (fingerprint input)

    @property
    def fingerprint(self) -> str:
        """Line-number-independent identity for baseline matching: a
        finding survives unrelated edits above it, and moves with its
        line's content + enclosing symbol."""
        h = hashlib.sha256()
        h.update("\0".join((self.rule, self.path, self.qualname,
                            self.text)).encode())
        return h.hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        q = f" [{self.qualname}]" if self.qualname else ""
        return f"{loc}: {self.rule}{q} {self.message}"


def _rule_matches(pattern: str, rule: str) -> bool:
    pattern = pattern.strip()
    if pattern.endswith("x"):
        return rule.startswith(pattern[:-1])
    return rule == pattern


class ParsedModule:
    """One source file: AST + the comment-derived annotation maps."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of suppressed rule patterns
        self.suppressions: Dict[int, Set[str]] = {}
        # line -> (lockname, writes_only) for `# guarded-by:` comments
        self.guarded: Dict[int, Tuple[str, bool]] = {}
        # line -> [locknames] for `# holds:` comments
        self.holds: Dict[int, List[str]] = {}
        # lines carrying `# graft: hot-path`
        self.hot_lines: Set[int] = set()
        for i, text in enumerate(self.lines, start=1):
            if "#" not in text:
                continue
            m = _SUPPRESS_RE.search(text)
            if m:
                # leading comma/space-separated rule ids; everything from
                # the first non-rule token on is the human reason
                rules: Set[str] = set()
                for tok in re.split(r"[\s,]+", m.group(1).strip()):
                    if _RULE_TOKEN_RE.match(tok):
                        rules.add(tok)
                    elif tok:
                        break
                if rules:
                    self.suppressions[i] = rules
            m = _GUARDED_RE.search(text)
            if m:
                self.guarded[i] = (m.group(1), bool(m.group(2)))
            m = _HOLDS_RE.search(text)
            if m:
                self.holds[i] = [p.strip() for p in
                                 m.group(1).split(",") if p.strip()]
            if _HOT_RE.search(text):
                self.hot_lines.add(i)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        """A finding is suppressed by a ``# graft-ok`` on its own line or
        the line directly above (for flagged lines with no comment room)."""
        for ln in (lineno, lineno - 1):
            for pattern in self.suppressions.get(ln, ()):
                if _rule_matches(pattern, rule):
                    return True
        return False

    def holds_for_def(self, node: ast.AST) -> List[str]:
        """``# holds: <lock>`` annotations attached to a function: on the
        ``def`` line itself or the line directly above (decorator-free
        defs put the comment above; long signatures put it on the line)."""
        lineno = getattr(node, "lineno", 0)
        out: List[str] = []
        for ln in (lineno, lineno - 1):
            out.extend(self.holds.get(ln, ()))
        return out

    def is_hot_def(self, node: ast.AST) -> bool:
        lineno = getattr(node, "lineno", 0)
        return lineno in self.hot_lines or (lineno - 1) in self.hot_lines

    def finding(self, rule: str, node: ast.AST, message: str,
                qualname: str = "") -> Optional[Finding]:
        """Build a Finding unless an inline suppression covers it."""
        lineno = getattr(node, "lineno", 0)
        if self.suppressed(rule, lineno):
            return None
        return Finding(rule=rule, path=self.relpath, line=lineno,
                       message=message, qualname=qualname,
                       text=self.line_text(lineno))


@dataclass
class QualTracker:
    """Tracks the Class.method qualname while walking nested defs."""

    stack: List[str] = field(default_factory=list)

    def push(self, name: str) -> None:
        self.stack.append(name)

    def pop(self) -> None:
        self.stack.pop()

    @property
    def qualname(self) -> str:
        return ".".join(self.stack)


def call_name(node: ast.AST) -> str:
    """Dotted name of a call target ('np.asarray', 'jax.jit',
    'self._lock.acquire') — best-effort, '' when not name-shaped."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_functions(tree: ast.Module):
    """Yield (qualname, class_name_or_None, func_node) for every function
    and method in the module, including nested ones."""

    def walk(node, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield qual, cls, child
                yield from walk(child, qual + ".", cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.",
                                child.name if cls is None else cls)

    yield from walk(tree, "", None)
