"""Runtime sanitizers: the dynamic twins of the GL01x/GL03x static rules.

**Transfer sentry** (``no_implicit_device_to_host``): proves a code
region performs ZERO implicit device->host transfers. Two layers, both
armed together:

  - ``jax.transfer_guard_device_to_host("disallow")`` — the real C++
    guard. On TPU/GPU it rejects every implicit d->h transfer while
    letting explicit ``jax.device_get`` through. On the CPU backend the
    device buffer *is* host memory, so this guard never fires there
    (measured on jax 0.4.37) — which is why the second layer exists;
  - a Python-level sentry that patches the jax array type's implicit
    conversion dunders (``__float__``/``__int__``/``__bool__``/
    ``__index__``/``item``) and wraps ``numpy.asarray``/``numpy.array``
    to reject jax arrays. These are exactly the idioms GL01x flags
    statically, intercepted portably on every backend.
    ``jax.device_get`` does not route through any of them (verified),
    so the sanctioned explicit fetch stays legal.

The sentry is test-harness machinery: patching a type's dunders is
process-global, so enter the context in exactly one test at a time
(tests are the only caller; the tier-1 gate runs them single-process).

**LockOrderSanitizer**: wraps real locks, records each thread's
acquisition stack, and flags (a) order inversions — lock B acquired
under A somewhere, A under B elsewhere: the deadlock pattern GL032
detects statically, here observed on live schedules — and (b) holds
longer than ``hold_threshold_s`` (the PR 6 wedge class). ``instrument``
swaps sanitized wrappers into an object's lock attributes so a real
engine can tick under observation.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


class ImplicitTransferError(RuntimeError):
    """An implicit device->host transfer happened inside the sentry."""


_SENTRY_DUNDERS = ("__float__", "__int__", "__bool__", "__index__",
                   "item", "tolist")


@contextmanager
def no_implicit_device_to_host(allow: Tuple[str, ...] = ()):
    """Context manager rejecting implicit d->h transfers inside it.

    ``allow`` names dunders to leave unpatched (escape hatch for
    diagnosing a failure one idiom at a time). Explicit fetches must go
    through ``jax.device_get`` — the engine tick and the trainer's
    cadence flush already do (graft-lint GL01x keeps it that way)."""
    import jax
    import jaxlib.xla_extension as xe
    import numpy as _np

    array_cls = xe.ArrayImpl
    saved: Dict[str, object] = {}

    def _make_trap(name: str, orig):
        def trap(self, *args, **kwargs):
            # tracers and committed arrays share the type's dunders only
            # for concrete arrays; anything reaching here is a real
            # host conversion of device-backed data
            raise ImplicitTransferError(
                f"implicit device->host transfer via jax.Array.{name} — "
                f"hot paths must fetch explicitly with jax.device_get "
                f"(graft-lint GL01x)")
        trap.__name__ = name
        return trap

    real_asarray, real_array = _np.asarray, _np.array

    def _guard_np(fn, label):
        def wrapped(obj, *args, **kwargs):
            if isinstance(obj, jax.Array):
                raise ImplicitTransferError(
                    f"implicit device->host transfer via np.{label}() on "
                    f"a jax.Array — use jax.device_get (graft-lint GL012)")
            return fn(obj, *args, **kwargs)
        return wrapped

    with jax.transfer_guard_device_to_host("disallow"):
        try:
            for name in _SENTRY_DUNDERS:
                if name in allow or not hasattr(array_cls, name):
                    continue
                saved[name] = getattr(array_cls, name)
                setattr(array_cls, name, _make_trap(name, saved[name]))
            _np.asarray = _guard_np(real_asarray, "asarray")
            _np.array = _guard_np(real_array, "array")
            yield
        finally:
            _np.asarray, _np.array = real_asarray, real_array
            for name, orig in saved.items():
                setattr(array_cls, name, orig)


# ---------------------------------------------------------------------------
# Lock-order sanitizer
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class LockOrderViolation:
    kind: str                 # "inversion" | "hold_time"
    lock: str
    other: Optional[str]
    thread: str
    detail: str


class _SanitizedLock:
    """Context-manager/acquire-release wrapper over a real lock. Reentrant
    acquisitions of the same wrapper (RLock semantics) are recorded once —
    re-entry cannot invert an order."""

    def __init__(self, sanitizer: "LockOrderSanitizer", name: str, inner):
        self._san = sanitizer
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = (self._inner.acquire(blocking, timeout)
               if timeout != -1 else self._inner.acquire(blocking))
        if got:
            try:
                self._san._on_acquire(self)
            except BaseException:
                # raise_on_violation mode: don't leak the inner lock when
                # the sanitizer aborts the acquisition
                self._inner.release()
                raise
        return got

    def release(self):
        self._san._on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition(lock) compatibility passthroughs
    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        return owned() if owned else False

    def __repr__(self):
        return f"<sanitized {self.name} over {self._inner!r}>"


class LockOrderSanitizer:
    """Records per-thread lock-acquisition order across wrapped locks.

    - ``wrap(lock, name)`` returns a drop-in wrapper feeding the
      sanitizer; ``instrument(obj, attrs)`` swaps wrappers into an
      object's lock attributes in place.
    - an acquisition of B while holding A registers order A->B; if B->A
      was ever registered (any thread), an **inversion** violation is
      recorded — the runtime twin of graft-lint GL032.
    - releasing a lock held longer than ``hold_threshold_s`` records a
      **hold_time** violation — wedge-class behavior (PR 6) that static
      analysis cannot see.

    Violations are collected, not raised (``raise_on_violation=True``
    flips that for tests that want the stack at the exact site).
    """

    def __init__(self, hold_threshold_s: float = 0.0,
                 raise_on_violation: bool = False):
        self.hold_threshold_s = float(hold_threshold_s)
        self.raise_on_violation = raise_on_violation
        self.violations: List[LockOrderViolation] = []
        self._mu = threading.Lock()
        self._orders: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()

    # -- wiring -----------------------------------------------------------

    def wrap(self, lock, name: str) -> _SanitizedLock:
        return _SanitizedLock(self, name, lock)

    def instrument(self, obj, attrs: Tuple[str, ...],
                   prefix: str = "") -> List[str]:
        """Replace ``obj.<attr>`` locks with sanitized wrappers; returns
        the wrapped names. Attributes that are absent are skipped."""
        wrapped = []
        label = prefix or type(obj).__name__
        for attr in attrs:
            inner = getattr(obj, attr, None)
            if inner is None:
                continue
            name = f"{label}.{attr}"
            setattr(obj, attr, self.wrap(inner, name))
            wrapped.append(name)
        return wrapped

    # -- event sinks ------------------------------------------------------

    def _stack(self) -> List[Tuple["_SanitizedLock", float, int]]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def _record(self, violation: LockOrderViolation) -> None:
        with self._mu:
            self.violations.append(violation)
        if self.raise_on_violation:
            raise RuntimeError(f"lock sanitizer: {violation}")

    def _on_acquire(self, lock: _SanitizedLock) -> None:
        stack = self._stack()
        thread = threading.current_thread().name
        for held, _t0, _n in stack:
            if held is lock:
                # reentrant re-acquire: bump the depth marker, no edge
                for i, (lk, t0, n) in enumerate(stack):
                    if lk is lock:
                        stack[i] = (lk, t0, n + 1)
                return
        for held, _t0, _n in stack:
            edge = (held.name, lock.name)
            inverse = (lock.name, held.name)
            with self._mu:
                first = self._orders.setdefault(edge, thread)
                inverted = inverse in self._orders
            if inverted:
                self._record(LockOrderViolation(
                    kind="inversion", lock=lock.name, other=held.name,
                    thread=thread,
                    detail=f"{held.name} -> {lock.name} here, but "
                           f"{lock.name} -> {held.name} was taken by "
                           f"thread '{self._orders[inverse]}'"))
            del first
        stack.append((lock, time.monotonic(), 1))

    def _on_release(self, lock: _SanitizedLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            lk, t0, n = stack[i]
            if lk is lock:
                if n > 1:
                    stack[i] = (lk, t0, n - 1)
                    return
                held_for = time.monotonic() - t0
                del stack[i]
                if (self.hold_threshold_s > 0
                        and held_for > self.hold_threshold_s):
                    self._record(LockOrderViolation(
                        kind="hold_time", lock=lock.name, other=None,
                        thread=threading.current_thread().name,
                        detail=f"held {held_for:.3f}s > threshold "
                               f"{self.hold_threshold_s:.3f}s"))
                return

    # -- reporting --------------------------------------------------------

    def inversions(self) -> List[LockOrderViolation]:
        return [v for v in self.violations if v.kind == "inversion"]

    def report(self) -> str:
        if not self.violations:
            return "lock sanitizer: no violations"
        lines = [f"lock sanitizer: {len(self.violations)} violation(s)"]
        for v in self.violations:
            lines.append(f"  [{v.kind}] {v.lock} (thread {v.thread}): "
                         f"{v.detail}")
        return "\n".join(lines)


__all__ = [
    "ImplicitTransferError",
    "LockOrderSanitizer",
    "LockOrderViolation",
    "no_implicit_device_to_host",
]
