"""GL03x — lock-discipline lint: guarded-by annotations + ordering graph.

PR 6 earned two rounds of wedged-lock fixes the hard way; this checker
turns the discipline those fixes encode into machine-checked contracts:

  - **GL031** — a field annotated ``# guarded-by: <lock>`` (on its
    ``self.field = ...`` line, normally in ``__init__``) may only be
    touched while the named lock is held. "Held" is established
    lexically: a ``with self.<lock>:`` block (or an alias assigned
    ``lock = self._lock`` earlier in the function), a
    ``lock.acquire(...)`` call (held through the rest of the function —
    the timed-acquire/finally-release pattern), or a ``# holds: <lock>``
    annotation on the ``def`` line documenting that every caller holds
    it. A ``[writes]`` qualifier (``# guarded-by: _restart_lock
    [writes]``) checks stores only — the seqlock-style fields whose
    racy reads are the design (generation stamps).
  - **GL032** — the cross-module lock-acquisition graph: while holding
    lock A, acquiring lock B adds edge A->B; a cycle means two threads
    can deadlock by acquiring in opposite orders (the engine-lock /
    queue-condvar / MetricLogger-RLock triangle is exactly PR 6's wedge
    surface). Edges are collected lexically AND through one level of
    call resolution: a call ``self.queue.put(...)`` while holding the
    engine lock contributes the locks ``put`` acquires (matched by
    method name across the scanned corpus).
  - **GL033** — a ``guarded-by`` naming a lock the class never creates
    (typo'd annotations must fail loudly, or the whole scheme rots).

``threading.Condition(self._lock)`` registers the condition name as an
ALIAS of the wrapped lock, so holding either satisfies the annotation
(the request queue's ``_not_full`` over ``_lock`` pattern).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from building_llm_from_scratch_tpu.analysis.base import (
    Finding,
    ParsedModule,
    call_name,
)

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_COND_CTORS = {"threading.Condition", "Condition"}


@dataclass
class ClassModel:
    name: str
    relpath: str
    locks: Set[str] = field(default_factory=set)
    #: condition/alias name -> canonical lock name
    aliases: Dict[str, str] = field(default_factory=dict)
    #: field -> (canonical lock, writes_only, anno line)
    guarded: Dict[str, Tuple[str, bool, int]] = field(default_factory=dict)

    def canonical(self, name: str) -> str:
        return self.aliases.get(name, name)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _collect_class(mod: ParsedModule, cls: ast.ClassDef) -> ClassModel:
    model = ClassModel(cls.name, mod.relpath)
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            if isinstance(value, ast.Call):
                ctor = call_name(value.func)
                if ctor in _LOCK_CTORS:
                    model.locks.add(attr)
                elif ctor in _COND_CTORS:
                    wrapped = (_self_attr(value.args[0])
                               if value.args else None)
                    if wrapped:
                        model.aliases[attr] = wrapped
                    else:
                        model.locks.add(attr)   # Condition() owns a lock
            # the guarded-by comment may sit on any physical line of a
            # multi-line assignment statement
            for ln in range(node.lineno,
                            (node.end_lineno or node.lineno) + 1):
                anno = mod.guarded.get(ln)
                if anno is not None:
                    lockname, writes_only = anno
                    model.guarded[attr] = (lockname, writes_only, ln)
                    break
    return model


@dataclass
class MethodFacts:
    """What one method does with locks (for the ordering graph)."""

    qualname: str
    relpath: str
    #: canonical locks this method acquires lexically (with/acquire)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    #: (held-lock, acquired-lock, line) lexical nesting edges
    edges: List[Tuple[str, str, int]] = field(default_factory=list)
    #: method names called while holding each lock: (held, callee, line)
    calls_under: List[Tuple[str, str, int]] = field(default_factory=list)


class _MethodChecker(ast.NodeVisitor):
    def __init__(self, mod: ParsedModule, model: ClassModel,
                 qualname: str, base_holds: Set[str]):
        self.mod = mod
        self.model = model
        self.qualname = qualname
        self.held: List[str] = sorted(base_holds)
        # local alias -> canonical lock ('lock = self._lock' pattern)
        self.local_aliases: Dict[str, str] = {}
        self.findings: List[Finding] = []
        self.facts = MethodFacts(f"{model.name}.{qualname.split('.')[-1]}",
                                 mod.relpath)

    # -- lock resolution --------------------------------------------------

    def _as_lock(self, node: ast.AST) -> Optional[str]:
        """Resolve an expression to a canonical lock of this class."""
        attr = _self_attr(node)
        if attr is not None:
            if attr in self.model.locks or attr in self.model.aliases:
                return self.model.canonical(attr)
            return None
        if isinstance(node, ast.Name) and node.id in self.local_aliases:
            return self.local_aliases[node.id]
        return None

    def _note_acquire(self, lock: str, lineno: int) -> None:
        self.facts.acquires.append((lock, lineno))
        for held in self.held:
            if held != lock:
                self.facts.edges.append((held, lock, lineno))

    # -- traversal ---------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        lock = self._as_lock(node.value)
        if lock is not None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.local_aliases[tgt.id] = lock
        self._check_targets(node.targets)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_targets([node.target])
        self.visit(node.value)

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            lock = self._as_lock(item.context_expr)
            if lock is not None:
                self._note_acquire(lock, node.lineno)
                entered.append(lock)
            else:
                self.visit(item.context_expr)
        self.held.extend(entered)
        for stmt in node.body:
            self.visit(stmt)
        # remove exactly the with-entered locks: a timed `.acquire()`
        # inside the body appends to `held` permanently (its release
        # lives in a finally), so a blind tail-pop would drop THAT lock
        # and leave the with-lock marked held past its block
        for lock in entered:
            for i in range(len(self.held) - 1, -1, -1):
                if self.held[i] == lock:
                    del self.held[i]
                    break

    def visit_Call(self, node: ast.Call) -> None:
        # lock.acquire(...): the timed-acquire pattern — treated as held
        # for the REST of the function (release lives in a finally)
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "acquire":
                lock = self._as_lock(node.func.value)
                if lock is not None:
                    self._note_acquire(lock, node.lineno)
                    self.held.append(lock)
            elif self.held and node.func.attr not in ("acquire", "release"):
                # method call while holding: graph fodder (resolved
                # against the corpus in the cross-module pass)
                for held in self.held:
                    self.facts.calls_under.append(
                        (held, node.func.attr, node.lineno))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr in self.model.guarded:
            lockname, writes_only, _ = self.model.guarded[attr]
            canonical = self.model.canonical(lockname)
            is_store = isinstance(node.ctx, (ast.Store, ast.Del))
            if (not writes_only or is_store) and canonical not in self.held:
                kind = "written" if is_store else "read"
                f = self.mod.finding(
                    "GL031", node,
                    f"self.{attr} is guarded-by {lockname} but {kind} "
                    f"without it (hold the lock, annotate the function "
                    f"'# holds: {lockname}', or suppress with a reason)",
                    self.qualname)
                if f is not None:
                    self.findings.append(f)
        self.generic_visit(node)

    def _check_targets(self, targets: List[ast.AST]) -> None:
        for tgt in targets:
            self.visit(tgt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs inherit the held set at their definition point —
        # the repo's nested closures (_fail_all's _kill) run synchronously
        # inside the region that defined them
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def check_module(mod: ParsedModule) -> Tuple[List[Finding],
                                             List[MethodFacts]]:
    findings: List[Finding] = []
    facts: List[MethodFacts] = []
    classes = [n for n in ast.walk(mod.tree)
               if isinstance(n, ast.ClassDef)]
    for cls in classes:
        model = _collect_class(mod, cls)
        if not model.guarded and not model.locks:
            continue
        # GL033: annotation names a lock the class never defines
        for fld, (lockname, _w, lineno) in sorted(model.guarded.items()):
            if (lockname not in model.locks
                    and lockname not in model.aliases):
                f = Finding(
                    "GL033", mod.relpath, lineno,
                    f"guarded-by names '{lockname}' but class "
                    f"{model.name} defines no such lock",
                    qualname=f"{model.name}.{fld}",
                    text=mod.line_text(lineno))
                if not mod.suppressed("GL033", lineno):
                    findings.append(f)
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue        # construction precedes sharing
            base_holds = {model.canonical(h)
                          for h in mod.holds_for_def(item)}
            checker = _MethodChecker(mod, model,
                                     f"{model.name}.{item.name}",
                                     base_holds)
            for stmt in item.body:
                checker.visit(stmt)
            findings.extend(checker.findings)
            # annotated holds count as an edge source for the graph:
            # a function documented to run under L that acquires M
            # contributes L->M even though the `with` is in its caller
            facts.append(checker.facts)
    return findings, facts


def lock_order_findings(all_facts: List[MethodFacts],
                        mods: Dict[str, ParsedModule]) -> List[Finding]:
    """Cross-module pass: assemble the acquisition graph and flag cycles.

    Nodes are ``relpath::Class.lock``; direct lexical nesting gives
    edges, and one level of call resolution adds edges for
    ``obj.method(...)`` calls made while holding a lock, where
    ``method`` matches a scanned method that acquires locks of its own
    class (method names are matched corpus-wide; an ambiguous name adds
    an edge per candidate — over-approximation is the safe direction
    for deadlock detection, and a justified false cycle can be
    suppressed at the `with` site)."""
    # method name -> [(node-prefix, [locks acquired])]
    by_name: Dict[str, List[Tuple[str, List[str]]]] = {}
    for mf in all_facts:
        cls_prefix = f"{mf.relpath}::{mf.qualname.rsplit('.', 1)[0]}"
        method = mf.qualname.rsplit(".", 1)[-1]
        if mf.acquires:
            by_name.setdefault(method, []).append(
                (cls_prefix, sorted({lk for lk, _ in mf.acquires})))

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add_edge(a: str, b: str, relpath: str, line: int) -> None:
        if a != b:
            edges.setdefault((a, b), (relpath, line))

    for mf in all_facts:
        cls_prefix = f"{mf.relpath}::{mf.qualname.rsplit('.', 1)[0]}"
        for held, acquired, line in mf.edges:
            add_edge(f"{cls_prefix}.{held}", f"{cls_prefix}.{acquired}",
                     mf.relpath, line)
        for held, callee, line in mf.calls_under:
            for target_prefix, locks in by_name.get(callee, ()):
                # same-class edges too: a call-mediated acquisition
                # (method A holds L1, calls B which takes L2) is never
                # visible lexically, and intra-class cycles are the
                # common engine shape; duplicate edges are harmless
                # (first site wins)
                for lk in locks:
                    add_edge(f"{cls_prefix}.{held}",
                             f"{target_prefix}.{lk}", mf.relpath, line)

    # cycle detection: iterative DFS over the edge set
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    findings: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}

    def dfs(start: str) -> None:
        stack: List[Tuple[str, int]] = [(start, 0)]
        path: List[str] = []
        while stack:
            node, idx = stack.pop()
            if idx == 0:
                if color.get(node, WHITE) == BLACK:
                    continue
                color[node] = GREY
                path.append(node)
            nbrs = graph.get(node, [])
            if idx < len(nbrs):
                stack.append((node, idx + 1))
                nxt = nbrs[idx]
                c = color.get(nxt, WHITE)
                if c == GREY:
                    i = path.index(nxt)
                    cycle = tuple(path[i:])
                    key = tuple(sorted(cycle))
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        # every edge of the cycle: suppressing ANY of
                        # them (a `# graft-ok: GL032 <why>` at the
                        # acquire site) dismisses the whole cycle — the
                        # reviewer asserted that edge is infeasible
                        ring = list(cycle) + [nxt]
                        sites = [edges[(a, b)]
                                 for a, b in zip(ring, ring[1:])
                                 if (a, b) in edges]
                        suppressed = any(
                            mods.get(rp) is not None
                            and mods[rp].suppressed("GL032", ln)
                            for rp, ln in sites)
                        relpath, line = (sites[-1] if sites
                                         else (nxt.split("::")[0], 0))
                        pretty = " -> ".join(
                            n.split("::")[-1] for n in ring)
                        mod = mods.get(relpath)
                        if not suppressed:
                            findings.append(Finding(
                                "GL032", relpath, line,
                                f"lock-acquisition cycle: {pretty} — "
                                "two threads taking these in opposite "
                                "orders deadlock",
                                qualname="",
                                text=(mod.line_text(line)
                                      if mod else "")))
                elif c == WHITE:
                    stack.append((nxt, 0))
            else:
                color[node] = BLACK
                path.pop()

    for node in sorted(graph):
        if color.get(node, WHITE) == WHITE:
            dfs(node)
    return findings
