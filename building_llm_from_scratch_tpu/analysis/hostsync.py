"""GL01x — implicit device->host sync lint for registered hot paths.

The repo's steady-state invariant (PR 2-4, guard-tested since): the step
loop and the decode tick NEVER block the host on the device implicitly.
Device values are fetched only at cadence boundaries, and the sanctioned
fetch points use **explicit** ``jax.device_get`` — which this lint never
flags, and which the runtime twin (``analysis/runtime.py``'s
transfer-guard sentry) lets through while rejecting everything implicit.

What gets scanned: the functions in ``HOT_PATHS`` below plus any function
whose ``def`` line carries a ``# graft: hot-path`` comment. What gets
flagged inside them:

  - GL011: ``float(x)`` / ``int(x)`` / ``bool(x)`` on a non-literal — the
    classic hidden sync (each one blocks until the dispatched program
    finishes AND pays a device round trip);
  - GL012: ``np.asarray(x)`` / ``np.array(x)`` / ``x.tolist()`` — bulk
    implicit materialization;
  - GL013: ``x.item()``.

Static analysis cannot see types, so the rules are conservative: host-only
conversions in a hot path need a ``# graft-ok: GL01x <why>`` suppression,
which doubles as documentation that a reviewer asserted host-ness. One
dataflow concession keeps the sanctioned idiom suppression-free: a name
assigned from ``jax.device_get(...)`` is host-typed for the rest of the
function, and conversions of it (or of subscripts of it) are not flagged.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from building_llm_from_scratch_tpu.analysis.base import (
    Finding,
    ParsedModule,
    call_name,
    iter_functions,
)

#: Registered hot paths: repo-relative module path -> function qualnames.
#: These are the loops where one implicit sync repeats thousands of times
#: per second; everything else syncs at worst once per cadence/request.
HOT_PATHS = {
    "building_llm_from_scratch_tpu/training/trainer.py": {
        "Trainer._epoch_steps",
    },
    "building_llm_from_scratch_tpu/serving/engine.py": {
        "DecodeEngine.step",
        "DecodeEngine._admit",
        "DecodeEngine._admit_chunked",
        "DecodeEngine._chunk_tick",
        "DecodeEngine._maybe_store_prefix",
        "DecodeEngine._accept_token",
        "DecodeEngine._verify_tick",
        "DecodeEngine._pool_args",
        "DecodeEngine._pool_args_for",
        # memory-ledger providers: run at every cadence AND under the
        # /metrics scrape — nbytes/host-numpy metadata only, a device
        # fetch here would sync the tick (and stall every scrape)
        "DecodeEngine._cache_component_bytes",
        "DecodeEngine._kv_live_by_tenant",
        "DecodeEngine._compile_temp_bytes",
        # paged-KV per-tick bookkeeping: table writes + pool refcounts
        # are host numpy/integer math — a device fetch here would sync
        # every decode tick (and every admission)
        "DecodeEngine._ensure_pages",
        "DecodeEngine._admit_pages",
        "DecodeEngine._page_need",
        "DecodeEngine._release_slot_pages",
        "DecodeEngine._apply_paged_hit",
    },
    "building_llm_from_scratch_tpu/obs/memory.py": {
        # the ledger's measurement/export surface: providers read array
        # METADATA (.nbytes) — explicit device polls live only in
        # observe()'s cadence-bounded _poll(), never here
        "MemoryLedger.snapshot",
        "MemoryLedger.gauges",
        "MemoryLedger.device_bytes",
        "MemoryLedger.host_bytes",
        "MemoryLedger.total_bytes",
    },
    "building_llm_from_scratch_tpu/serving/spec.py": {
        # the drafter runs INSIDE the tick for every spec-enabled slot:
        # pure host numpy only — one device sync here stalls the whole
        # co-resident batch every tick
        "Drafter.propose",
        "NgramDrafter.propose",
    },
    "building_llm_from_scratch_tpu/serving/adapters.py": {
        # the engine's per-tick / per-admission registry reads: must stay
        # lock-free reference snapshots with zero device syncs
        "AdapterRegistry.pool_args",
        "AdapterRegistry.lookup",
        "AdapterRegistry.load_tag",
    },
    "building_llm_from_scratch_tpu/serving/kvcache.py": {
        # per-admission prefix probe: host-side hashing only — a device
        # fetch here would sync the tick on every admission
        "PrefixStore.match",
        # page-pool bookkeeping runs inside the tick on every alloc/
        # release: pure host lists + numpy refcounts
        "PagePool.alloc",
        "PagePool.incref",
        "PagePool.decref",
        "PagePool.available",
        "PagePool.reserve",
        "PagePool.unreserve",
    },
    "building_llm_from_scratch_tpu/serving/fleet.py": {
        # router-side per-request paths for the cross-process fleet:
        # pure host dict/RPC bookkeeping — a device touch here would put
        # a sync in front of EVERY fleet request, and healthz must stay
        # answerable from cached heartbeats while a worker is down
        "ProcessFleet.submit",
        "ProcessFleet._dispatch_order",
        "ProcessFleet._apply_event",
        "ProcessFleet.healthz_payload",
        # the aggregated /metrics scrape must answer from cached series
        # even mid-outage — a device fetch would stall every scrape
        "ProcessFleet.metrics_snapshot",
    },
    "building_llm_from_scratch_tpu/serving/transport.py": {
        # every fleet RPC crosses these two; timing/trace bookkeeping
        # must stay plain host floats — a device touch would serialize
        # the whole frame stream on one sync
        "RpcClient.call",
        "RpcServer._serve_conn",
    },
    "building_llm_from_scratch_tpu/data/prefetch.py": {
        "Prefetcher._fill",
        "Prefetcher.__next__",
    },
}

_SCALAR_CASTS = {"float", "int", "bool"}
_ARRAY_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "jax.device_get"}  # device_get handled as SANCTIONED below
_DEVICE_GET = {"jax.device_get"}


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_literal(node.left) and _is_literal(node.right)
    return False


def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name of an expression like ``x``, ``x[i]``, ``x.attr``."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


class _HotFunctionChecker(ast.NodeVisitor):
    def __init__(self, mod: ParsedModule, qualname: str):
        self.mod = mod
        self.qualname = qualname
        self.findings: List[Finding] = []
        # names proven host-resident: assigned from jax.device_get(...)
        self.host_names: Set[str] = set()

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        f = self.mod.finding(rule, node, message, self.qualname)
        if f is not None:
            self.findings.append(f)

    def _arg_is_sanctioned(self, arg: ast.AST) -> bool:
        """True for args that are provably host-side: a direct
        ``jax.device_get(...)`` call, or (a subscript/attribute of) a
        name previously assigned from one."""
        if isinstance(arg, ast.Call) and call_name(arg.func) in _DEVICE_GET:
            return True
        root = _root_name(arg)
        return root is not None and root in self.host_names

    def visit_Assign(self, node: ast.Assign) -> None:
        # dataflow-lite: `x = jax.device_get(...)` marks x host-resident
        if (isinstance(node.value, ast.Call)
                and call_name(node.value.func) in _DEVICE_GET):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.host_names.add(tgt.id)
                elif isinstance(tgt, ast.Tuple):
                    for elt in tgt.elts:
                        if isinstance(elt, ast.Name):
                            self.host_names.add(elt.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node.func)
        args = node.args
        if name in _SCALAR_CASTS and args and not _is_literal(args[0]):
            if not self._arg_is_sanctioned(args[0]):
                self._emit(
                    "GL011", node,
                    f"{name}() may sync the device in a hot path — fetch "
                    f"at cadence via jax.device_get, or suppress with a "
                    f"reason if the value is host-resident")
        elif name in _ARRAY_CALLS and name not in _DEVICE_GET:
            if args and not self._arg_is_sanctioned(args[0]):
                self._emit(
                    "GL012", node,
                    f"{name}() materializes implicitly in a hot path — "
                    f"use explicit jax.device_get at the sanctioned fetch "
                    f"point, or suppress with a reason")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args):
            if not self._arg_is_sanctioned(node.func.value):
                self._emit("GL013", node,
                           ".item() is an implicit device fetch — use "
                           "jax.device_get at a cadence boundary")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "tolist" and not node.args):
            if not self._arg_is_sanctioned(node.func.value):
                self._emit("GL012", node,
                           ".tolist() materializes implicitly in a hot "
                           "path — use explicit jax.device_get")
        self.generic_visit(node)


def check_module(mod: ParsedModule) -> List[Finding]:
    registered = HOT_PATHS.get(mod.relpath, set())
    findings: List[Finding] = []
    for qualname, _cls, node in iter_functions(mod.tree):
        if qualname not in registered and not mod.is_hot_def(node):
            continue
        checker = _HotFunctionChecker(mod, qualname)
        for stmt in node.body:
            checker.visit(stmt)
        findings.extend(checker.findings)
    return findings
