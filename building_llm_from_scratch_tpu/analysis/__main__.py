"""``python -m building_llm_from_scratch_tpu.analysis`` — graft-lint."""

from building_llm_from_scratch_tpu.analysis.runner import main

raise SystemExit(main())
