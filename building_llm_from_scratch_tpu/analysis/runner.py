"""graft-lint runner: discovery, baseline, CLI.

Usage (equivalently via ``scripts/lint_graft.py`` or
``python -m building_llm_from_scratch_tpu.analysis``):

    lint_graft.py                      # repo scan vs checked-in baseline
    lint_graft.py --json out.json      # machine-readable findings
    lint_graft.py --update-baseline    # re-baseline (new entries marked)
    lint_graft.py path1.py path2.py    # scan specific files (no baseline)

Exit status: 0 when every finding is suppressed or baselined, 1 when a
NEW finding exists — the CI gate (``scripts/ci_quick.sh``) runs this
before the tier-1 suite, so invariant regressions fail fast and cheap.

The baseline (``analysis/baseline.json``) is keyed on content
fingerprints (rule + path + enclosing symbol + source line text), so
entries survive unrelated edits and line drift but die with the code
they describe. Every entry carries a ``reason``: baselining is an
explicit, reviewed decision, never a silent default — entries added by
``--update-baseline`` get a loud ``UNREVIEWED`` reason that a human must
replace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from building_llm_from_scratch_tpu.analysis import (
    hostsync,
    jitpurity,
    locks,
    telemetry,
)
from building_llm_from_scratch_tpu.analysis.base import (
    Finding,
    ParsedModule,
    RULES,
)

#: directories scanned by default (relative to the repo root)
DEFAULT_SCAN = ("building_llm_from_scratch_tpu", "scripts")
#: path fragments never scanned (fixtures hold SEEDED violations)
EXCLUDE_PARTS = ("tests/fixtures", "/fixtures/", "__pycache__")

UNREVIEWED = "UNREVIEWED — added by --update-baseline; justify or fix"


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def discover(root: str, paths: Optional[List[str]] = None) -> List[str]:
    out: List[str] = []
    if paths:
        for p in paths:
            ap = os.path.abspath(p)
            if os.path.isdir(ap):
                out.extend(discover(root, [
                    os.path.join(ap, n) for n in sorted(os.listdir(ap))]))
            elif ap.endswith(".py"):
                out.append(ap)
        return out
    for top in DEFAULT_SCAN:
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames.sort()
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                if any(part in rel for part in EXCLUDE_PARTS):
                    continue
                out.append(full)
    return out


def parse_modules(root: str, files: List[str]) -> List[ParsedModule]:
    mods: List[ParsedModule] = []
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mods.append(ParsedModule(path, rel, source))
        except (OSError, SyntaxError) as e:
            print(f"graft-lint: cannot parse {rel}: {e}", file=sys.stderr)
    return mods


def run_checkers(mods: List[ParsedModule]) -> List[Finding]:
    findings: List[Finding] = []
    all_lock_facts = []
    by_rel = {m.relpath: m for m in mods}
    for mod in mods:
        findings.extend(hostsync.check_module(mod))
        findings.extend(jitpurity.check_module(mod))
        lock_findings, facts = locks.check_module(mod)
        findings.extend(lock_findings)
        all_lock_facts.extend(facts)
        findings.extend(telemetry.check_module(mod))
    findings.extend(locks.lock_order_findings(all_lock_facts, by_rel))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("entries", [])}


def save_baseline(path: str, findings: List[Finding],
                  previous: Dict[str, dict]) -> int:
    entries = []
    for f in findings:
        prev = previous.get(f.fingerprint)
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "qualname": f.qualname,
            "text": f.text,
            "message": f.message,
            "reason": (prev or {}).get("reason", UNREVIEWED),
        })
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "graft-lint baseline: every entry is "
                              "ACCEPTED DEBT with a reason; new findings "
                              "fail the gate until fixed or justified "
                              "here.",
                   "entries": entries}, f, indent=1)
        f.write("\n")
    return len(entries)


def split_baselined(findings: List[Finding], baseline: Dict[str, dict]
                    ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, baselined, stale_fingerprints)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen = set()
    for f in findings:
        if f.fingerprint in baseline:
            old.append(f)
            seen.add(f.fingerprint)
        else:
            new.append(f)
    stale = sorted(set(baseline) - seen)
    return new, old, stale


# -- CLI --------------------------------------------------------------------

def per_rule_counts(findings: List[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return counts


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="lint_graft",
        description="graft-lint: static invariant analysis (GL01x "
                    "host-sync, GL02x jit purity, GL03x lock "
                    "discipline, GL04x telemetry schema).")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: the package + "
                        "scripts, vs the checked-in baseline)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON (default: analysis/baseline.json; "
                        "'none' disables baselining)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current findings "
                        "(keeps existing reasons; new entries are marked "
                        "UNREVIEWED)")
    p.add_argument("--json", default=None, metavar="OUT",
                   help="write machine-readable findings JSON ('-' for "
                        "stdout)")
    p.add_argument("--rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.rules:
        for rule in sorted(RULES):
            print(f"{rule}  {RULES[rule]}")
        return 0

    root = repo_root()
    explicit_paths = bool(args.paths)
    if args.update_baseline and explicit_paths and not args.baseline:
        # a partial scan would REWRITE the full repo baseline from only
        # the scanned files, silently deleting every other entry (and
        # its reviewed reason)
        print("graft-lint: refusing --update-baseline with explicit "
              "paths — a partial scan would clobber the checked-in "
              "baseline. Run a full scan, or pass an explicit "
              "--baseline file for the partial set.", file=sys.stderr)
        return 2
    files = discover(root, args.paths or None)
    mods = parse_modules(root, files)
    findings = run_checkers(mods)

    baseline_path = args.baseline or default_baseline_path()
    use_baseline = baseline_path != "none" and not explicit_paths
    baseline = load_baseline(baseline_path) if use_baseline else {}

    if args.update_baseline:
        n = save_baseline(baseline_path, findings, baseline)
        print(f"graft-lint: baseline updated: {n} entrie(s) at "
              f"{os.path.relpath(baseline_path, root)}")
        unreviewed = sum(
            1 for e in load_baseline(baseline_path).values()
            if e["reason"] == UNREVIEWED)
        if unreviewed:
            print(f"graft-lint: {unreviewed} entrie(s) are UNREVIEWED — "
                  f"edit the baseline to justify them (no silent "
                  f"suppressions)")
        return 0

    new, old, stale = split_baselined(findings, baseline)

    payload = {
        "n_findings": len(findings),
        "n_new": len(new),
        "n_baselined": len(old),
        "stale_baseline_entries": stale,
        "per_rule": per_rule_counts(findings),
        "per_rule_new": per_rule_counts(new),
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "qualname": f.qualname, "message": f.message,
             "fingerprint": f.fingerprint,
             "baselined": f.fingerprint in baseline}
            for f in findings],
    }
    json_to_stdout = args.json == "-"
    if args.json:
        text = json.dumps(payload, indent=1)
        if json_to_stdout:
            print(text)
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(text + "\n")

    # with `--json -` stdout must stay pure JSON: the human-readable
    # findings + summary move to stderr
    report = sys.stderr if json_to_stdout else sys.stdout

    def say(msg: str) -> None:
        print(msg, file=report)

    for f in new:
        say(f.render())
    # per-rule counts ALWAYS print, so two gate logs diff cleanly
    counts = per_rule_counts(findings)
    new_counts = per_rule_counts(new)
    summary = ", ".join(
        f"{rule}={counts[rule]}"
        + (f"(+{new_counts[rule]} new)" if rule in new_counts else "")
        for rule in sorted(counts)) or "clean"
    say(f"graft-lint: {len(mods)} files, {len(findings)} finding(s) "
        f"[{summary}], {len(old)} baselined, {len(new)} new")
    if stale:
        say(f"graft-lint: {len(stale)} stale baseline entrie(s) — the "
            f"debt was paid; run --update-baseline to drop them")
    if new:
        say("graft-lint: FAIL — fix the findings above, suppress "
            "inline with '# graft-ok: <rule> <why>', or baseline with "
            "a reason via --update-baseline")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
