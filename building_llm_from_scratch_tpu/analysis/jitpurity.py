"""GL02x — jit-purity and recompile-hazard lint.

A function that reaches ``jax.jit`` / ``pjit`` / ``shard_map`` runs as a
TRACE: Python executes once per (signature), and anything impure either
silently freezes (wall-clock reads, host RNG) or silently multiplies
(side effects re-run on every recompile). Worse, a *fresh callable*
handed to ``jax.jit`` inside a function body defeats the jit cache
entirely — the cache is keyed on the callable's identity, so every call
of the enclosing function pays a full XLA compile ("Run LoRA Run"'s
implementation-regression class; exactly what bit ``generate()``'s
sliding-window fallback before this lint).

Detection is two-phase per module:

  1. find the jit reach set: functions named in ``jax.jit(f)`` /
     ``pjit(f)`` / ``shard_map(f, ...)`` call sites (plain names and
     ``self._method`` references), plus functions decorated with
     ``@jax.jit`` / ``@functools.partial(jax.jit, ...)``. Static
     argument names (``static_argnames=(...)``) are collected so GL024
     exempts branching on them.
  2. walk each reached function body for the GL021-025 hazards; GL026
     fires at the call site itself when the jitted operand is a lambda
     or an inner def of the enclosing function.

Intentional trace-time effects (a debug print in a disabled code path, a
deliberate trace counter) take ``# graft-ok: GL02x <why>`` suppressions.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from building_llm_from_scratch_tpu.analysis.base import (
    Finding,
    ParsedModule,
    call_name,
    iter_functions,
)

_JIT_CALLS = {"jax.jit", "jit", "pjit", "jax.pjit", "shard_map",
              "jax.shard_map"}
_TIME_CALLS = ("time.time", "time.perf_counter", "time.monotonic",
               "time.process_time", "time.time_ns", "time.sleep")
_HOST_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")


def _static_argnames(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str):
                    names.add(elt.value)
    return names


def _jit_operand(call: ast.Call) -> Optional[ast.AST]:
    """The callable being jitted at this call site (first positional)."""
    return call.args[0] if call.args else None


def _collect_jit_reach(mod: ParsedModule) -> Tuple[
        Dict[str, Set[str]], List[Tuple[ast.AST, str]],
        List[Tuple[ast.Lambda, Set[str]]]]:
    """(reached: func-or-method name -> static argnames,
    hazards: [(node, message)] for GL026 fresh-callable sites,
    lambdas: jitted lambda nodes + their static argnames).

    Names are matched module-wide: ``jax.jit(self._decode_impl)`` marks
    method ``_decode_impl`` of any class in the module (class-accurate
    resolution would need full type inference; one module rarely reuses
    a method name across classes with only one jitted)."""
    reached: Dict[str, Set[str]] = {}
    fresh: List[Tuple[ast.AST, str]] = []
    lambdas: List[Tuple[ast.Lambda, Set[str]]] = []

    # decorators: @jax.jit / @functools.partial(jax.jit, ...)
    for qualname, _cls, fn in iter_functions(mod.tree):
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = call_name(target)
            statics: Set[str] = set()
            if name in _JIT_CALLS:
                pass
            elif name in ("functools.partial", "partial") and isinstance(
                    dec, ast.Call):
                inner = call_name(dec.args[0]) if dec.args else ""
                if inner not in _JIT_CALLS:
                    continue
                statics = _static_argnames(dec)
            else:
                continue
            if isinstance(dec, ast.Call):
                statics |= _static_argnames(dec)
            reached.setdefault(fn.name, set()).update(statics)

    # call sites: jax.jit(f) / shard_map(f, ...) anywhere in the module
    enclosing: Dict[int, str] = {}
    for qualname, _cls, fn in iter_functions(mod.tree):
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and sub is not fn:
                continue
            enclosing.setdefault(id(sub), qualname)
    inner_defs: Dict[str, Set[str]] = {}
    for qualname, _cls, fn in iter_functions(mod.tree):
        if "." in qualname:
            outer = qualname.rsplit(".", 1)[0]
            inner_defs.setdefault(outer, set()).add(fn.name)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node.func) not in _JIT_CALLS:
            continue
        operand = _jit_operand(node)
        if operand is None:
            continue
        statics = _static_argnames(node)
        qual = enclosing.get(id(node), "")
        if isinstance(operand, ast.Lambda):
            if qual:      # module-level lambda jit is built once — fine
                fresh.append((
                    node,
                    "jax.jit of a lambda built inside a function: the "
                    "jit cache keys on callable identity, so every call "
                    "of the enclosing function recompiles — hoist the "
                    "jitted function to module/init scope"))
            lambdas.append((operand, statics))   # body purity-checked too
            continue
        name = call_name(operand)
        if not name:
            continue
        short = name.split(".")[-1]
        if name.startswith("self."):
            reached.setdefault(short, set()).update(statics)
            # methods jitted in __init__ are built once per object — the
            # sanctioned pattern (serving engine); no GL026
        elif qual and short in inner_defs.get(qual, set()):
            # jit of a def nested in THIS function: when the enclosing
            # function is itself a one-shot builder (make_train_step)
            # this is the factory pattern and fine — but the builder's
            # callers must cache, which the repo's Trainer does. Only a
            # jit of a nested def inside a LOOP is certainly fresh; the
            # conservative rule stays quiet here and GL026 covers
            # lambdas, the unambiguous case.
            reached.setdefault(short, set()).update(statics)
        else:
            reached.setdefault(short, set()).update(statics)
    return reached, fresh, lambdas


class _PurityChecker(ast.NodeVisitor):
    def __init__(self, mod: ParsedModule, qualname: str,
                 params: Set[str], statics: Set[str]):
        self.mod = mod
        self.qualname = qualname
        self.traced = params - statics - {"self", "cls"}
        self.findings: List[Finding] = []

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        f = self.mod.finding(rule, node, message, self.qualname)
        if f is not None:
            self.findings.append(f)

    # -- side effects -----------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node.func)
        if name == "print":
            self._emit("GL021", node,
                       "print() inside a jitted function runs at TRACE "
                       "time only (and re-runs on every recompile) — use "
                       "jax.debug.print for runtime values")
        elif name in _TIME_CALLS:
            self._emit("GL022", node,
                       f"{name}() inside a jitted function freezes one "
                       "trace-time value into the compiled program")
        elif any(name.startswith(p) for p in _HOST_RNG_PREFIXES):
            self._emit("GL023", node,
                       f"{name}() is host RNG: the draw happens once at "
                       "trace time and is baked into the program — use "
                       "jax.random with a threaded key")
        self.generic_visit(node)

    # -- state mutation ---------------------------------------------------

    def visit_Global(self, node: ast.Global) -> None:
        self._emit("GL025", node,
                   "global-variable write inside a jitted function is a "
                   "trace-time side effect (happens once per compile, "
                   "not per step)")

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self._emit("GL025", node,
                   "nonlocal write inside a jitted function is a "
                   "trace-time side effect")

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) and isinstance(
                    tgt.value, ast.Name) and tgt.value.id == "self":
                self._emit(
                    "GL025", node,
                    f"self.{tgt.attr} assignment inside a jitted method "
                    "mutates host state at trace time — return the value "
                    "instead")
        self.generic_visit(node)

    # -- traced-arg branching ---------------------------------------------

    def _test_on_traced(self, test: ast.AST) -> Optional[str]:
        # is-None / isinstance / containment checks are structure checks,
        # not value branches — pytree structure is static under jit
        if isinstance(test, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in test.ops):
            return None
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) and call_name(sub.func) in (
                    "isinstance", "len", "hasattr", "getattr"):
                return None
            if isinstance(sub, ast.Name) and sub.id in self.traced:
                return sub.id
        return None

    def visit_If(self, node: ast.If) -> None:
        name = self._test_on_traced(node.test)
        if name is not None:
            self._emit(
                "GL024", node,
                f"Python `if` on traced argument '{name}': the branch is "
                "resolved ONCE at trace time (TracerBoolConversionError "
                "or a silently frozen branch) — use jnp.where/lax.cond, "
                "or declare the argument static")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        name = self._test_on_traced(node.test)
        if name is not None:
            self._emit(
                "GL024", node,
                f"Python `while` on traced argument '{name}' cannot "
                "trace — use lax.while_loop or a static bound")
        self.generic_visit(node)


def check_module(mod: ParsedModule) -> List[Finding]:
    reached, fresh, lambdas = _collect_jit_reach(mod)
    findings: List[Finding] = []
    for node, message in fresh:
        f = mod.finding("GL026", node, message)
        if f is not None:
            findings.append(f)
    for lam, statics in lambdas:
        params = {a.arg for a in (lam.args.posonlyargs + lam.args.args
                                  + lam.args.kwonlyargs)}
        checker = _PurityChecker(mod, "<jitted lambda>", params, statics)
        checker.visit(lam.body)
        findings.extend(checker.findings)
    if not reached:
        return findings
    for qualname, _cls, fn in iter_functions(mod.tree):
        statics = reached.get(fn.name)
        if statics is None:
            continue
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        checker = _PurityChecker(mod, qualname, params, statics)
        for stmt in fn.body:
            checker.visit(stmt)
        findings.extend(checker.findings)
    return findings
