"""graft-lint: static invariant analysis + runtime sanitizers.

The repo's hardest-won properties — zero implicit host syncs in steady
state, ONE compiled program per workload, lock-safe threaded serving, a
drift-free telemetry schema — were enforced by guard tests and reviewer
vigilance. This package checks them by machine on every CI run:

  - ``hostsync``   — GL01x: implicit device->host transfers in
    registered hot paths (the step loop, the decode tick, the
    prefetcher);
  - ``jitpurity``  — GL02x: trace-impurity and recompile hazards in
    functions reaching ``jax.jit``/``pjit``/``shard_map``;
  - ``locks``      — GL03x: ``# guarded-by:`` field annotations checked
    against actual lock scopes + the cross-module lock-ordering graph;
  - ``telemetry``  — GL04x: every ``.event(...)`` call site checked
    against the ``obs/schema.py`` registry;
  - ``runner``     — baseline-aware CLI (``scripts/lint_graft.py``,
    ``python -m building_llm_from_scratch_tpu.analysis``);
  - ``runtime``    — the dynamic twins: ``LockOrderSanitizer`` (records
    real acquisition orders, catches inversions and over-threshold hold
    times) and the transfer-guard sentry proving a steady-state engine
    tick / train step performs zero implicit device->host transfers.

Stdlib-only by design: the static passes import neither jax nor numpy,
so the lint gate runs in milliseconds before the test suite spins up.
"""

from building_llm_from_scratch_tpu.analysis.base import (
    Finding,
    ParsedModule,
    RULES,
)

__all__ = ["Finding", "ParsedModule", "RULES"]
