"""building_llm_from_scratch_tpu — a TPU-native LLM training framework.

A from-scratch JAX/XLA re-design targeting the full capability surface of
the reference repo (chemphenoms/Building_LLM_from_scratch). See SURVEY.md
for the component inventory and the per-module docstrings for what each
subsystem provides.
"""

__version__ = "0.1.0"
