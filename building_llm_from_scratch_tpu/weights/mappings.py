"""State-dict -> parameter-tree conversion for every pretrained family.

Reproduces the reference's three name maps, torch-free:

  - GPT-2      (reference Models/GPT2/load_weights.py:23-108): HF ``GPT2Model``
    naming (``wte``, ``h.{b}.attn.c_attn`` ...). HF GPT-2 stores linear
    weights in Conv1D layout (in, out) — exactly this framework's layout, so
    unlike the reference (torch Linear, (out, in)) NO transpose is needed;
    the fused QKV matrix is split in thirds along the output axis, and the
    LM head is weight-tied to ``wte`` (load_weights.py:106-108).
  - LLaMA-2    (reference Models/Llama/load_weights_llama2.py:18-71): Meta
    naming (``tok_embeddings``, ``layers.{l}.attention.wq`` ...), including
    the deliberate w2/w3 swap — the checkpoint's ``feed_forward.w1`` is the
    gate, ``w3`` the up projection and ``w2`` the down projection
    (load_weights_llama2.py:55-63).
  - LLaMA-3/3.1/3.2 (reference Models/Llama/load_weights_llama3.py:19-85):
    HF naming (``model.embed_tokens``, ``self_attn.q_proj`` ...), with the
    weight-tying fallback when ``lm_head.weight`` is absent
    (load_weights_llama3.py:81-85).

All converters take a flat ``{name: np.ndarray}`` dict and return the
framework's stacked param tree (blocks stacked along a leading layer axis
for ``lax.scan``). Every tensor passes a shape check equivalent to the
reference's ``assign_check``; each leaf is placed through ``put`` —
by default a plain ``jax.device_put`` with a dtype cast, or a shard-aware
callback built from a ``MeshPlan`` so 8B-scale weights stream shard-by-shard
onto the mesh without ever being resident unsharded.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig

Params = Dict[str, Any]
StateDict = Dict[str, np.ndarray]

PathNames = Tuple[str, ...]
PutFn = Callable[[PathNames, np.ndarray], jax.Array]


def _check(name: str, arr: np.ndarray, expected: Tuple[int, ...]) -> np.ndarray:
    """Shape guard (reference assign_check, load_weights.py:13-21)."""
    if tuple(arr.shape) != tuple(expected):
        raise ValueError(
            f"Shape mismatch for '{name}': checkpoint {tuple(arr.shape)} vs "
            f"model {tuple(expected)}")
    return arr


def _get(sd: StateDict, name: str) -> np.ndarray:
    if name not in sd:
        raise KeyError(f"Checkpoint is missing tensor '{name}'")
    return np.asarray(sd[name])


def default_put(cfg: ModelConfig,
                plan: Optional[Any] = None) -> PutFn:
    """Build the leaf-placement function: cast to the model dtype and
    device_put — onto the MeshPlan's param sharding when one is given, so a
    sharded leaf is laid out across the mesh at load time."""
    dtype = cfg.jax_dtype

    def put(names: PathNames, arr: np.ndarray) -> jax.Array:
        arr = arr.astype(dtype)
        if plan is not None:
            sharding = plan._named(plan.param_spec(names, tuple(arr.shape)))
            return jax.device_put(arr, sharding)
        return jax.device_put(arr)

    return put


def _stack(layers) -> np.ndarray:
    return np.stack(layers, axis=0)


# ---------------------------------------------------------------------------
# GPT-2 (HF GPT2Model naming; reference Models/GPT2/load_weights.py:23-108)
# ---------------------------------------------------------------------------

def _get_gpt2(sd: StateDict, name: str) -> np.ndarray:
    """Fetch accepting both ``GPT2Model`` keys (``wte.weight``) and
    ``GPT2LMHeadModel`` keys (``transformer.wte.weight``). Lazy mappings
    stay lazy — only requested tensors are read."""
    if name in sd:
        return np.asarray(sd[name])
    prefixed = f"transformer.{name}"
    if prefixed in sd:
        return np.asarray(sd[prefixed])
    raise KeyError(f"Checkpoint is missing tensor '{name}'")


def convert_gpt2_state_dict(sd: StateDict, cfg: ModelConfig,
                            put: Optional[PutFn] = None,
                            plan: Optional[Any] = None) -> Params:
    """HF GPT-2 state dict -> param tree.

    Reference map (Models/GPT2/load_weights.py:23-108): wte/wpe embeddings,
    per-block fused ``c_attn`` split into Q/K/V (np.split thirds), c_proj
    out-projection, c_fc/c_proj MLP, ln_1/ln_2/ln_f norms, and the LM head
    weight-tied to ``wte``. HF Conv1D stores (in, out) so no transposes.
    """
    if not cfg.qkv_bias:
        raise ValueError(
            "GPT-2 HF checkpoints carry QKV biases; build the config with "
            "qkv_bias=True (reference build_components.py:69-70)")
    put = put or default_put(cfg, plan)
    L, D, V, T = cfg.n_layers, cfg.emb_dim, cfg.vocab_size, cfg.context_length
    F = cfg.hidden_dim

    wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
    wo, bo, up, b_up, down, b_down = [], [], [], [], [], []
    n1s, n1b, n2s, n2b = [], [], [], []
    for b in range(L):
        qkv_w = _check(f"h.{b}.attn.c_attn.weight",
                       _get_gpt2(sd, f"h.{b}.attn.c_attn.weight"), (D, 3 * D))
        q_w, k_w, v_w = np.split(qkv_w, 3, axis=-1)
        qkv_b = _check(f"h.{b}.attn.c_attn.bias",
                       _get_gpt2(sd, f"h.{b}.attn.c_attn.bias"), (3 * D,))
        q_b, k_b, v_b = np.split(qkv_b, 3, axis=-1)
        wq.append(q_w), wk.append(k_w), wv.append(v_w)
        bq.append(q_b), bk.append(k_b), bv.append(v_b)
        wo.append(_check(f"h.{b}.attn.c_proj.weight",
                         _get_gpt2(sd, f"h.{b}.attn.c_proj.weight"), (D, D)))
        bo.append(_check(f"h.{b}.attn.c_proj.bias",
                         _get_gpt2(sd, f"h.{b}.attn.c_proj.bias"), (D,)))
        up.append(_check(f"h.{b}.mlp.c_fc.weight",
                         _get_gpt2(sd, f"h.{b}.mlp.c_fc.weight"), (D, F)))
        b_up.append(_check(f"h.{b}.mlp.c_fc.bias",
                           _get_gpt2(sd, f"h.{b}.mlp.c_fc.bias"), (F,)))
        down.append(_check(f"h.{b}.mlp.c_proj.weight",
                           _get_gpt2(sd, f"h.{b}.mlp.c_proj.weight"), (F, D)))
        b_down.append(_check(f"h.{b}.mlp.c_proj.bias",
                             _get_gpt2(sd, f"h.{b}.mlp.c_proj.bias"), (D,)))
        n1s.append(_check(f"h.{b}.ln_1.weight",
                          _get_gpt2(sd, f"h.{b}.ln_1.weight"), (D,)))
        n1b.append(_check(f"h.{b}.ln_1.bias",
                          _get_gpt2(sd, f"h.{b}.ln_1.bias"), (D,)))
        n2s.append(_check(f"h.{b}.ln_2.weight",
                          _get_gpt2(sd, f"h.{b}.ln_2.weight"), (D,)))
        n2b.append(_check(f"h.{b}.ln_2.bias",
                          _get_gpt2(sd, f"h.{b}.ln_2.bias"), (D,)))

    wte = _check("wte.weight", _get_gpt2(sd, "wte.weight"), (V, D))
    params: Params = {
        "tok_emb": {"weight": put(("tok_emb", "weight"), wte)},
        "pos_emb": {"weight": put(("pos_emb", "weight"),
                                  _check("wpe.weight", _get_gpt2(sd, "wpe.weight"),
                                         (T, D)))},
        "blocks": {
            "norm1": {"scale": put(("blocks", "norm1", "scale"), _stack(n1s)),
                      "bias": put(("blocks", "norm1", "bias"), _stack(n1b))},
            "attn": {
                "wq": put(("blocks", "attn", "wq"), _stack(wq)),
                "wk": put(("blocks", "attn", "wk"), _stack(wk)),
                "wv": put(("blocks", "attn", "wv"), _stack(wv)),
                "wo": put(("blocks", "attn", "wo"), _stack(wo)),
                "bq": put(("blocks", "attn", "bq"), _stack(bq)),
                "bk": put(("blocks", "attn", "bk"), _stack(bk)),
                "bv": put(("blocks", "attn", "bv"), _stack(bv)),
                "bo": put(("blocks", "attn", "bo"), _stack(bo)),
            },
            "norm2": {"scale": put(("blocks", "norm2", "scale"), _stack(n2s)),
                      "bias": put(("blocks", "norm2", "bias"), _stack(n2b))},
            "mlp": {
                "up": put(("blocks", "mlp", "up"), _stack(up)),
                "b_up": put(("blocks", "mlp", "b_up"), _stack(b_up)),
                "down": put(("blocks", "mlp", "down"), _stack(down)),
                "b_down": put(("blocks", "mlp", "b_down"), _stack(b_down)),
            },
        },
        "final_norm": {
            "scale": put(("final_norm", "scale"),
                         _check("ln_f.weight", _get_gpt2(sd, "ln_f.weight"), (D,))),
            "bias": put(("final_norm", "bias"),
                        _check("ln_f.bias", _get_gpt2(sd, "ln_f.bias"), (D,))),
        },
        # weight-tied head (reference load_weights.py:106-108); our head is
        # (D, V) applied as x @ w, so the tied embedding transposes
        "head": {"weight": put(("head", "weight"),
                               np.ascontiguousarray(wte.T))},
    }
    return params


# ---------------------------------------------------------------------------
# LLaMA — shared block-by-name assembly for both namings
# ---------------------------------------------------------------------------

def _convert_llama(sd: StateDict, cfg: ModelConfig, names: Dict[str, str],
                   head_key: Optional[str], embed_key: str,
                   put: PutFn) -> Params:
    """Assemble a LLaMA param tree given a per-layer name template map.

    ``names`` maps the framework's leaf name to a checkpoint name template
    with ``{l}``. Checkpoint linear weights are torch Linear (out, in) and
    transpose into this framework's (in, out).
    """
    L, D, V = cfg.n_layers, cfg.emb_dim, cfg.vocab_size
    hd, Hq, Hkv, F = cfg.head_dim, cfg.n_heads, cfg.n_kv_groups, cfg.hidden_dim

    def lin(template: str, l: int, out_dim: int, in_dim: int) -> np.ndarray:
        name = template.format(l=l)
        w = _check(name, _get(sd, name), (out_dim, in_dim))
        return np.ascontiguousarray(w.T)

    wq, wk, wv, wo, gate, up, down, n1, n2 = ([] for _ in range(9))
    for l in range(L):
        wq.append(lin(names["wq"], l, Hq * hd, D))
        wk.append(lin(names["wk"], l, Hkv * hd, D))
        wv.append(lin(names["wv"], l, Hkv * hd, D))
        wo.append(lin(names["wo"], l, D, Hq * hd))
        gate.append(lin(names["gate"], l, F, D))
        up.append(lin(names["up"], l, F, D))
        down.append(lin(names["down"], l, D, F))
        n1.append(_check(names["norm1"].format(l=l),
                         _get(sd, names["norm1"].format(l=l)), (D,)))
        n2.append(_check(names["norm2"].format(l=l),
                         _get(sd, names["norm2"].format(l=l)), (D,)))

    emb = _check(embed_key, _get(sd, embed_key), (V, D))
    if head_key is not None and head_key in sd:
        head = np.ascontiguousarray(
            _check(head_key, _get(sd, head_key), (V, D)).T)
    else:
        # weight tying fallback (reference load_weights_llama3.py:81-85)
        head = np.ascontiguousarray(emb.T)

    return {
        "tok_emb": {"weight": put(("tok_emb", "weight"), emb)},
        "blocks": {
            "norm1": {"scale": put(("blocks", "norm1", "scale"), _stack(n1))},
            "attn": {
                "wq": put(("blocks", "attn", "wq"), _stack(wq)),
                "wk": put(("blocks", "attn", "wk"), _stack(wk)),
                "wv": put(("blocks", "attn", "wv"), _stack(wv)),
                "wo": put(("blocks", "attn", "wo"), _stack(wo)),
            },
            "norm2": {"scale": put(("blocks", "norm2", "scale"), _stack(n2))},
            "mlp": {
                "gate": put(("blocks", "mlp", "gate"), _stack(gate)),
                "up": put(("blocks", "mlp", "up"), _stack(up)),
                "down": put(("blocks", "mlp", "down"), _stack(down)),
            },
        },
        "final_norm": {"scale": put(("final_norm", "scale"),
                                    _check(names["final_norm"],
                                           _get(sd, names["final_norm"]),
                                           (D,)))},
        "head": {"weight": put(("head", "weight"), head)},
    }


def convert_llama_meta_state_dict(sd: StateDict, cfg: ModelConfig,
                                  put: Optional[PutFn] = None,
                                  plan: Optional[Any] = None) -> Params:
    """Meta ``consolidated.00.pth`` naming -> param tree (LLaMA-2).

    Reference map incl. the deliberate w2/w3 swap: the checkpoint's ``w1``
    feeds the gate branch, ``w3`` the up branch and ``w2`` the down
    projection (load_weights_llama2.py:50-63).
    """
    put = put or default_put(cfg, plan)
    names = {
        "wq": "layers.{l}.attention.wq.weight",
        "wk": "layers.{l}.attention.wk.weight",
        "wv": "layers.{l}.attention.wv.weight",
        "wo": "layers.{l}.attention.wo.weight",
        "gate": "layers.{l}.feed_forward.w1.weight",
        "up": "layers.{l}.feed_forward.w3.weight",     # the swap
        "down": "layers.{l}.feed_forward.w2.weight",
        "norm1": "layers.{l}.attention_norm.weight",
        "norm2": "layers.{l}.ffn_norm.weight",
        "final_norm": "norm.weight",
    }
    return _convert_llama(sd, cfg, names, head_key="output.weight",
                          embed_key="tok_embeddings.weight", put=put)


def convert_llama_hf_state_dict(sd: StateDict, cfg: ModelConfig,
                                put: Optional[PutFn] = None,
                                plan: Optional[Any] = None) -> Params:
    """HF safetensors naming -> param tree (LLaMA-3/3.1/3.2).

    Reference map (load_weights_llama3.py:19-85), incl. the weight-tying
    fallback when ``lm_head.weight`` is absent (3.2-1B ships tied).
    """
    put = put or default_put(cfg, plan)
    names = {
        "wq": "model.layers.{l}.self_attn.q_proj.weight",
        "wk": "model.layers.{l}.self_attn.k_proj.weight",
        "wv": "model.layers.{l}.self_attn.v_proj.weight",
        "wo": "model.layers.{l}.self_attn.o_proj.weight",
        "gate": "model.layers.{l}.mlp.gate_proj.weight",
        "up": "model.layers.{l}.mlp.up_proj.weight",
        "down": "model.layers.{l}.mlp.down_proj.weight",
        "norm1": "model.layers.{l}.input_layernorm.weight",
        "norm2": "model.layers.{l}.post_attention_layernorm.weight",
        "final_norm": "model.norm.weight",
    }
    return _convert_llama(sd, cfg, names, head_key="lm_head.weight",
                          embed_key="model.embed_tokens.weight", put=put)
