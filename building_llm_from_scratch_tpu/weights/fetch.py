"""Checkpoint file reading + HF-hub fetch (torch-free).

The reference's loaders pull weights with torch/transformers
(Models/GPT2/load_weights.py:120 ``GPT2Model.from_pretrained``,
load_weights_llama2.py:80-87 ``hf_hub_download`` + ``torch.load``,
load_weights_llama3.py:96-124 safetensors shards). This module reads the
same artifacts with NO torch in the path:

  - ``read_safetensors``: a from-scratch safetensors parser (the format is
    an 8-byte little-endian header length, a JSON tensor table, then raw
    bytes); bf16 maps to ``ml_dtypes.bfloat16`` so LLaMA shards load as
    genuine bf16 numpy arrays.
  - ``read_torch_checkpoint``: a minimal torch-free reader for torch's
    zip-serialized ``.pth`` files (Meta's ``consolidated.00.pth``): a custom
    Unpickler resolves storage persistent-ids to raw byte buffers inside the
    zip and rebuilds strided numpy views — no torch import.
  - ``load_hf_weights``: the reference's per-family download tables
    (hf_mapping load_weights.py:6-11; repo/filename sets
    load_weights_llama2.py:80-84, load_weights_llama3.py:96-124) with
    cache-if-exists semantics, merged shards, and conversion through
    weights/mappings.py onto an optional MeshPlan sharding.
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.utils.logging import setup_logger
from building_llm_from_scratch_tpu.weights.mappings import (
    convert_gpt2_state_dict,
    convert_llama_hf_state_dict,
    convert_llama_meta_state_dict,
)

logger = setup_logger(__name__)

StateDict = Dict[str, np.ndarray]


def _bfloat16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# ---------------------------------------------------------------------------
# safetensors (format spec: https://github.com/huggingface/safetensors)
# ---------------------------------------------------------------------------

_SAFETENSORS_DTYPES = {
    "F64": np.dtype(np.float64),
    "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16),
    "I64": np.dtype(np.int64),
    "I32": np.dtype(np.int32),
    "I16": np.dtype(np.int16),
    "I8": np.dtype(np.int8),
    "U8": np.dtype(np.uint8),
    "BOOL": np.dtype(np.bool_),
}


def _st_dtype(tag: str) -> np.dtype:
    return _bfloat16() if tag == "BF16" else _SAFETENSORS_DTYPES[tag]


class LazyStateDict:
    """Mapping over one or more safetensors files that reads tensors
    per-name on access (seek + read of just that tensor's bytes).

    This is what makes 8B-scale loading stream shard-by-shard: the
    converters pull each tensor once, stack it into the param tree and
    device_put it onto the mesh — the full checkpoint is never resident in
    host RAM at once (SURVEY §7 "Hard parts").
    """

    def __init__(self, paths):
        self._entries: Dict[str, Tuple[str, str, list, int, int]] = {}
        for path in paths:
            with open(path, "rb") as f:
                (header_len,) = struct.unpack("<Q", f.read(8))
                header = json.loads(f.read(header_len))
                data_start = 8 + header_len
            for name, meta in header.items():
                if name == "__metadata__":
                    continue
                begin, end = meta["data_offsets"]
                self._entries[name] = (path, meta["dtype"], meta["shape"],
                                       data_start + begin, end - begin)

    def __contains__(self, name) -> bool:
        return name in self._entries

    def __iter__(self):
        return iter(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    def keys(self):
        return self._entries.keys()

    def __getitem__(self, name: str) -> np.ndarray:
        path, dtag, shape, offset, nbytes = self._entries[name]
        with open(path, "rb") as f:
            f.seek(offset)
            raw = f.read(nbytes)
        return np.frombuffer(raw, dtype=_st_dtype(dtag)).reshape(shape)


def read_safetensors(path: str) -> "LazyStateDict":
    """Open one safetensors file as a lazy {name: np.ndarray} mapping."""
    return LazyStateDict([path])


# ---------------------------------------------------------------------------
# torch .pth (zip) reader — no torch import
# ---------------------------------------------------------------------------

_TORCH_STORAGE_DTYPES = {
    "FloatStorage": np.dtype(np.float32),
    "DoubleStorage": np.dtype(np.float64),
    "HalfStorage": np.dtype(np.float16),
    "LongStorage": np.dtype(np.int64),
    "IntStorage": np.dtype(np.int32),
    "ShortStorage": np.dtype(np.int16),
    "CharStorage": np.dtype(np.int8),
    "ByteStorage": np.dtype(np.uint8),
    "BoolStorage": np.dtype(np.bool_),
}


class _StorageRef:
    __slots__ = ("dtype", "key")

    def __init__(self, dtype: np.dtype, key: str):
        self.dtype = dtype
        self.key = key


class _FakeClass:
    """Stand-in for any torch class referenced by the pickle (storage type
    tags, OrderedDict subclasses, dtype singletons)."""

    def __init__(self, module: str, name: str):
        self.module = module
        self.name = name

    def __call__(self, *a, **k):          # e.g. collections.OrderedDict()
        return {}


def _rebuild_tensor_v2(storage: Tuple[_StorageRef, "zipfile.ZipFile", str],
                       storage_offset: int, size, stride, *unused):
    ref, zf, prefix = storage
    raw = zf.read(f"{prefix}/data/{ref.key}")
    flat = np.frombuffer(raw, dtype=ref.dtype)
    if not size:
        return np.asarray(flat[storage_offset])     # 0-dim array, not scalar
    return np.lib.stride_tricks.as_strided(
        flat[storage_offset:],
        shape=tuple(size),
        strides=tuple(s * ref.dtype.itemsize for s in stride),
    ).copy()


class _TorchUnpickler(pickle.Unpickler):
    def __init__(self, f, zf: "zipfile.ZipFile", prefix: str):
        super().__init__(f)
        self._zf = zf
        self._prefix = prefix

    def find_class(self, module: str, name: str):
        if name == "_rebuild_tensor_v2":
            return _rebuild_tensor_v2
        if module.startswith("torch") and name.endswith("Storage"):
            return _FakeClass(module, name)
        if module == "collections" and name == "OrderedDict":
            import collections

            return collections.OrderedDict
        return _FakeClass(module, name)

    def persistent_load(self, pid):
        # ('storage', <StorageType>, key, location, numel)
        assert pid[0] == "storage", f"unknown persistent id {pid!r}"
        storage_type = pid[1]
        name = getattr(storage_type, "name", str(storage_type))
        if name == "BFloat16Storage":
            dtype = _bfloat16()
        else:
            dtype = _TORCH_STORAGE_DTYPES.get(name)
            if dtype is None:
                raise ValueError(f"Unsupported torch storage type {name}")
        return (_StorageRef(dtype, str(pid[2])), self._zf, self._prefix)


def read_torch_checkpoint(path: str) -> StateDict:
    """Read a torch zip-serialized checkpoint (e.g. Meta's
    ``consolidated.00.pth``) into {name: np.ndarray} without torch."""
    with zipfile.ZipFile(path) as zf:
        pkl_names = [n for n in zf.namelist() if n.endswith("/data.pkl")]
        if not pkl_names:
            raise ValueError(f"{path} is not a torch zip checkpoint")
        prefix = pkl_names[0][: -len("/data.pkl")]
        with zf.open(pkl_names[0]) as f:
            obj = _TorchUnpickler(f, zf, prefix).load()
    if not isinstance(obj, dict):
        raise ValueError(f"{path} did not contain a state dict")
    return {str(k): np.asarray(v) for k, v in obj.items()
            if isinstance(v, np.ndarray)}


# ---------------------------------------------------------------------------
# File dispatch + HF hub tables
# ---------------------------------------------------------------------------

def load_state_dict_file(path: str) -> StateDict:
    """Read one checkpoint file by extension."""
    if path.endswith(".safetensors"):
        return read_safetensors(path)
    if path.endswith((".pth", ".pt", ".bin")):
        return read_torch_checkpoint(path)
    if path.endswith(".npz"):
        return dict(np.load(path))
    raise ValueError(f"Unknown checkpoint format: {path}")


# Reference hf_mapping (Models/GPT2/load_weights.py:6-11).
HF_GPT2_REPOS = {
    "124M": "openai-community/gpt2",
    "355M": "openai-community/gpt2-medium",
    "774M": "openai-community/gpt2-large",
    "1.5B": "openai-community/gpt2-xl",
}

# Reference repo/file sets (load_weights_llama2.py:80-84,
# load_weights_llama3.py:96-124).
HF_LLAMA_FILES: Dict[str, Tuple[str, List[str], str]] = {
    "llama2": ("meta-llama/Llama-2-7b", ["consolidated.00.pth"], "meta"),
    "llama3": ("meta-llama/Meta-Llama-3-8B",
               [f"model-0000{i}-of-00004.safetensors" for i in range(1, 5)],
               "hf"),
    "llama3_1": ("meta-llama/Llama-3.1-8B",
                 [f"model-0000{i}-of-00004.safetensors" for i in range(1, 5)],
                 "hf"),
    "llama3_2": ("meta-llama/Llama-3.2-1B", ["model.safetensors"], "hf"),
}


def _resolve_files(repo_id: str, filenames: List[str],
                   weights_dir: Optional[str], cache_dir: str) -> List[str]:
    """Local-first file resolution with cache-if-exists semantics.

    Hub downloads get a bounded retry (3 attempts, exponential backoff +
    jitter — utils/retry.py): transient network failures on shared hub
    infrastructure must not kill a pod-wide job at startup, while 404/gated
    errors re-raise immediately."""
    if weights_dir is not None:
        paths = [os.path.join(weights_dir, f) for f in filenames]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                f"--weights_dir is missing checkpoint files: {missing}")
        return paths
    from huggingface_hub import hf_hub_download

    from building_llm_from_scratch_tpu.obs.metrics import emit_event
    from building_llm_from_scratch_tpu.utils.retry import with_retries

    t0 = time.perf_counter()
    t0_wall = time.time()
    paths = [with_retries(
                lambda f=f: hf_hub_download(repo_id=repo_id, filename=f,
                                            cache_dir=cache_dir),
                describe=f"download {repo_id}/{f}")
             for f in filenames]
    # bytes = what actually crossed the network THIS call: files whose
    # mtime predates the call were cache hits, and counting them would
    # make a warm-cache relaunch look like a multi-GB download
    fetched = [p for p in paths if os.path.exists(p)
               and os.path.getmtime(p) >= t0_wall - 1.0]
    emit_event("hf_fetch", repo=repo_id, files=filenames,
               bytes=sum(os.path.getsize(p) for p in fetched),
               cached=len(paths) - len(fetched),
               seconds=round(time.perf_counter() - t0, 3))
    return paths


def _repo_files(model: str, num_params: str) -> Tuple[str, List[str], str]:
    """(repo_id, filenames, format) for a model family+size — the single
    source of truth shared by download and convert paths."""
    if model == "GPT2":
        if num_params not in HF_GPT2_REPOS:
            raise ValueError(
                f"No GPT-2 model exists for size '{num_params}'. "
                f"Options: {list(HF_GPT2_REPOS)}")
        return HF_GPT2_REPOS[num_params], ["model.safetensors"], "gpt2"
    if model not in HF_LLAMA_FILES:
        raise ValueError(f"No pretrained weights mapping for model '{model}'")
    return HF_LLAMA_FILES[model]


def download_hf_weights(model: str, num_params: str,
                        cache_dir: str = "hf_checkpoints") -> List[str]:
    """Download-only: populate the local HF cache, no conversion.

    Multi-host processes must call conversion (``load_hf_weights``) TOGETHER
    — its ``device_put`` onto multi-host shardings is a collective. The
    coordinator runs this local-only download before the barrier; everyone
    converts after it (round-2 ADVICE medium #2).
    """
    repo, filenames, _ = _repo_files(model, num_params)
    return _resolve_files(repo, filenames, None, cache_dir)


def load_hf_weights(model: str, num_params: str, cfg: ModelConfig,
                    plan: Optional[Any] = None,
                    weights_dir: Optional[str] = None,
                    cache_dir: str = "hf_checkpoints") -> Dict[str, Any]:
    """Fetch + convert pretrained weights for any supported family.

    Mirrors the reference's three ``load_hf_weights`` entry points in one
    dispatcher. ``weights_dir`` points at already-downloaded files (offline
    runs); otherwise files come from HF hub with cache-if-exists. ``plan``
    places each converted leaf straight onto its mesh sharding.
    """
    repo_id, filenames, fmt = _repo_files(model, num_params)
    paths = _resolve_files(repo_id, filenames, weights_dir, cache_dir)
    if fmt == "gpt2":
        sd = load_state_dict_file(paths[0])
        logger.info("Loaded %d tensors for GPT2-%s", len(sd), num_params)
        return convert_gpt2_state_dict(sd, cfg, plan=plan)
    if all(p.endswith(".safetensors") for p in paths):
        # lazy multi-shard view (load_weights_llama3.py:96-116 merges dicts
        # eagerly; here each tensor streams off disk only when converted)
        sd: StateDict = LazyStateDict(paths)
    else:
        sd = {}
        for p in paths:
            sd.update(load_state_dict_file(p))
    logger.info("Loaded %d tensors for %s-%s", len(sd), model, num_params)
    if fmt == "meta":
        return convert_llama_meta_state_dict(sd, cfg, plan=plan)
    return convert_llama_hf_state_dict(sd, cfg, plan=plan)
