"""Pretrained-weight loading (torch-free).

The reference loads pretrained weights for every model family through three
torch-based loaders (Models/GPT2/load_weights.py:110,
Models/Llama/load_weights_llama2.py:74, Models/Llama/load_weights_llama3.py:88).
Here the same name maps are reproduced as pure numpy -> jax conversions:
state dicts come from safetensors/npz/pickle files read WITHOUT torch, and
each converted leaf is ``jax.device_put`` directly onto its target sharding
so large models never materialize unsharded on one chip (SURVEY.md §7
"Hard parts": 8B-scale weight loading).
"""

from building_llm_from_scratch_tpu.weights.mappings import (
    convert_gpt2_state_dict,
    convert_llama_hf_state_dict,
    convert_llama_meta_state_dict,
)
from building_llm_from_scratch_tpu.weights.fetch import (
    HF_GPT2_REPOS,
    HF_LLAMA_FILES,
    download_hf_weights,
    load_hf_weights,
    load_state_dict_file,
)

__all__ = [
    "convert_gpt2_state_dict",
    "convert_llama_hf_state_dict",
    "convert_llama_meta_state_dict",
    "HF_GPT2_REPOS",
    "HF_LLAMA_FILES",
    "download_hf_weights",
    "load_hf_weights",
    "load_state_dict_file",
]
