"""The run orchestrator (reference main.py:37-193).

One entry point drives the whole framework:

  seed -> distributed init -> build components (config + params [+ HF
  weights] [+ LoRA] + tokenizer + MeshPlan + precision policy) -> discover
  training files -> build loader -> Trainer [-> resume] -> warm-up sample
  -> train/finetune -> plot losses.pdf + peak-HBM log -> final export.

TPU-first differences from the reference:
  - no ``mp.spawn``/NCCL rendezvous (main.py:22-29,185-193): on TPU pods
    each host runs this same command and ``jax.distributed.initialize``
    auto-discovers peers; parallelism is the MeshPlan, not process wiring;
  - run artifacts (losses.pdf, peak memory, final export) are written by
    the coordinator process (the reference's ``rank == 0`` gating);
  - ``--resume_from`` restores params + optimizer state + step — a path
    the reference lacks entirely (SURVEY §5) — and ``--resume auto``
    (default) discovers the latest valid checkpoint in ``--output_dir``
    so a preempted job relaunches with its original command; SIGTERM/
    SIGINT checkpoint at the next step boundary and exit 0
    (training/resilience.py);
  - ``--profile`` captures a jax.profiler trace of the first steps — with
    named spans and per-step annotations since the obs/ round;
  - observability (obs/): ``--metrics_jsonl`` structured telemetry
    (header + metrics + health + events; scripts/summarize_metrics.py
    renders it), ``--log_every`` throughput/MFU/memory cadence decoupled
    from eval, ``--stall_timeout`` per-host hung-step flight recorder,
    per-layer-group training health + AOT compile/recompile telemetry
    (obs/health.py, obs/compile.py), ``--compile_cache_dir`` persistent
    XLA compilation cache.

Usage:  python -m building_llm_from_scratch_tpu --data_dir ... [flags]
"""

from __future__ import annotations

import os

import numpy as np

from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.build_components import build_components
from building_llm_from_scratch_tpu.data.instruct import InstructLoader
from building_llm_from_scratch_tpu.obs import (
    StallDetector,
    configure_metrics,
    emit_event,
    run_metadata,
)
from building_llm_from_scratch_tpu.data.pretrain import PretrainLoader
from building_llm_from_scratch_tpu.parallel import (
    initialize_distributed,
    is_coordinator,
    sync_global_devices,
)
from building_llm_from_scratch_tpu.training.resilience import (
    GracefulStopper,
    LossWatchdog,
    resolve_resume_agreed,
)
from building_llm_from_scratch_tpu.training.trainer import Trainer
from building_llm_from_scratch_tpu.utils.io import discover_training_files
from building_llm_from_scratch_tpu.utils.logging import setup_logger
from building_llm_from_scratch_tpu.utils.memory import log_device_memory
from building_llm_from_scratch_tpu.utils.plotting import plot_losses
from building_llm_from_scratch_tpu.utils.seeding import (
    configure_default_prng,
    set_seed,
)

logger = setup_logger("main")


def main(args):
    """Run one job from parsed args: training/finetuning (returns the
    Trainer with its loss history) or --mode serve (returns the
    DecodeEngine with its serve stats) for callers/tests."""
    import jax

    # 1. distributed runtime + reproducibility (reference main.py:49-58)
    initialize_distributed()
    configure_default_prng()
    set_seed(args.seed)

    # 2. observability sink first (--metrics_jsonl; a no-op sink when
    #    unset, so emit_event callers never care): configured BEFORE the
    #    component build so fetch/retry events are captured — they buffer
    #    until the run-metadata header lands below. Then components
    #    (reference main.py:63).
    metric_logger = configure_metrics(args.metrics_jsonl)
    if args.compile_cache_dir:
        # BEFORE any compile (the component build device_puts and the
        # first train step both lower programs): a relaunched preempted
        # job skips its multi-minute XLA compiles entirely
        from building_llm_from_scratch_tpu.obs import enable_persistent_cache

        enable_persistent_cache(args.compile_cache_dir)
    comps = build_components(args)
    cfg = comps.cfg
    metric_logger.write_header(
        **run_metadata(args=args, cfg=cfg, plan=comps.plan))

    # serve mode: the continuous-batching decode engine (serving/) owns
    # its own run loop — warmup + frontends on the components built above,
    # no trainer
    if getattr(args, "mode", "train") == "serve":
        from building_llm_from_scratch_tpu.serving.frontend import run_serve

        return run_serve(args, comps, metric_logger)

    # finetune_fleet mode: fused multi-LoRA training — k tenants' jobs
    # through ONE base forward/backward, per-job artifact export at each
    # job's own completion (training/lora_fusion.py)
    if getattr(args, "mode", "train") == "finetune_fleet":
        from building_llm_from_scratch_tpu.training.lora_fusion import (
            run_finetune_fleet,
        )

        if is_coordinator():
            os.makedirs(args.output_dir, exist_ok=True)
        return run_finetune_fleet(args, comps, metric_logger)

    # constructed here, STARTED just before training inside the
    # try/finally below: starting now would leak the watcher thread if
    # loader/trainer setup raises, and start() is what arms the
    # first-step-hang timer — arming should not charge setup time
    stall = (StallDetector(args.stall_timeout)
             if args.stall_timeout > 0 else None)

    # 3. training files (reference main.py:68-81)
    txt_files, json_files = discover_training_files(args.data_dir)
    files = json_files if args.finetune else txt_files
    if not files:
        raise FileNotFoundError(
            "No training files found in specified directory.")
    if is_coordinator():
        logger.info("Total training files detected: %d", len(files))

    # 4. loader (reference main.py:86-111)
    # pp maps the STAGE axis over hosts (parallel/pipeline.py): every host
    # runs the same data columns for its stage, so the loader must yield
    # IDENTICAL batches on every process — per-process row sharding is for
    # the dp/fsdp/zero1/tp modes, where hosts own disjoint batch rows
    pp_multihost = (args.shard_mode == "pp")
    loader_kwargs = dict(
        tokenizer=comps.tokenizer,
        batch_size=args.batch_size,
        max_length=cfg.context_length,
        train_ratio=0.9,
        process_index=0 if pp_multihost else jax.process_index(),
        process_count=1 if pp_multihost else jax.process_count(),
        seed=args.seed,
    )
    if args.finetune:
        # pad id comes from the model config — fixing the reference's
        # hardcoded GPT-2 pad id 50256 (defect §2.3 #8)
        loader = InstructLoader(pad_token_id=cfg.eos_id,
                                dataset_name=args.dataset, **loader_kwargs)
    else:
        loader = PretrainLoader(stride=cfg.context_length,
                                token_cache_dir=args.tokenizer_cache_dir,
                                **loader_kwargs)

    # 5. output dir (reference main.py:116-117)
    if is_coordinator():
        os.makedirs(args.output_dir, exist_ok=True)
    sync_global_devices("output_dir")

    # 5b. fault tolerance: auto-resume discovery (coordinator-resolved and
    #     shared via the output dir so every host restores the SAME
    #     checkpoint), loss watchdog, and the graceful-stop signal handler
    # predicate: a fleet (--mode finetune_fleet) checkpoint in the same
    # output_dir shares the model_pg_ prefix but cannot restore into the
    # trainer state — auto-discovery skips it instead of dying mid-load
    resume_from = resolve_resume_agreed(
        getattr(args, "resume", "auto"), args.resume_from,
        args.output_dir, predicate=lambda meta: not meta.get("fleet"))
    watchdog = None
    if getattr(args, "watchdog", "on") == "on" and not (
            comps.policy is not None and comps.policy.name == "fp16"):
        watchdog = LossWatchdog(spike_factor=args.loss_spike_factor,
                                window=args.watchdog_window)
    stopper = GracefulStopper()

    # 6. trainer (reference main.py:122-138); the warm-up sample
    #    (main.py:143-145) runs inside the trainer once state exists
    trainer = Trainer(
        cfg, comps.params, comps.tokenizer, loader,
        output_dir=args.output_dir,
        peak_lr=args.lr, initial_lr=args.initial_lr, min_lr=args.min_lr,
        warmup_steps=args.warmup_steps,
        eval_freq=args.eval_freq, eval_iters=5,
        print_sample_iter=args.print_sample_iter,
        save_ckpt_freq=args.save_ckpt_freq,
        lora_params=comps.lora_params,
        lora_alpha=args.lora_alpha if args.use_lora else None,
        lora_rank=args.lora_rank if args.use_lora else None,
        policy=comps.policy, plan=comps.plan, seed=args.seed,
        grad_accum=args.grad_accum,
        resume_from=resume_from,
        warmup_sample=True,
        profile_dir=(os.path.join(args.output_dir, "profile")
                     if args.profile else None),
        profile_steps=args.profile_steps,
        keep_ckpts=args.keep_ckpts,
        watchdog=watchdog,
        stopper=stopper,
        log_every=args.log_every,
        stall=stall,
        compile_cache_dir=args.compile_cache_dir,
        prefetch=args.prefetch,
        async_ckpt=(args.async_ckpt == "on"),
    )

    # 7. train / finetune (reference main.py:150-157) under the graceful-
    #    stop handler: SIGTERM (preemption) / SIGINT checkpoint at the next
    #    step boundary and fall through here with trainer.preempted set
    try:
        if stall is not None:
            stall.start()
        with stopper:
            if args.finetune:
                trainer.finetune_model(files, n_epochs=args.n_epochs)
            else:
                trainer.train_model(files, n_epochs=args.n_epochs)
    finally:
        if stall is not None:
            stall.stop()

    if trainer.preempted:
        # the interrupted checkpoint is on disk; skip the final export so
        # the process exits 0 within the preemption grace window — the
        # relaunch picks the run back up via --resume auto
        logger.warning(
            "Run preempted at step %d; interrupted checkpoint written. "
            "Relaunch the same command to resume (--resume auto).",
            trainer.global_step)
        sync_global_devices("run_end")
        return trainer

    # 8. plot + peak memory on the coordinator (reference main.py:162-166)
    if is_coordinator():
        if trainer.train_losses:
            epochs_seen = np.linspace(0, args.n_epochs,
                                      len(trainer.train_losses))
            plot_losses(epochs_seen, trainer.track_tokens_seen,
                        trainer.train_losses, trainer.val_losses,
                        args.output_dir)
        logger.info("Training complete. Final model saved.")
        log_device_memory(logger, prefix="Peak device memory — ")

    # 9. final checkpoint + single-file export (reference main.py:171-172)
    trainer.save_checkpoint("final")
    trainer.export_final("model_pg_final.npz")
    if getattr(args, "save_adapter", None):
        # standalone LoRA artifact for multi-tenant serving
        # (--serve_adapters); export_final above stays the MERGED
        # single-tenant export
        trainer.export_adapter(args.save_adapter)
    emit_event("run_complete", step=trainer.global_step,
               tokens_seen=trainer.tokens_seen,
               final_train_loss=(trainer.train_losses[-1]
                                 if trainer.train_losses else None))

    # 10. barrier before exit (reference main.py:177-179)
    sync_global_devices("run_end")
    return trainer


def run(argv=None):
    """Console entry: parse flags, run."""
    return main(get_args(argv))


if __name__ == "__main__":
    run()
