"""Instruction-finetuning data pipeline (Alpaca format) with loss masking.

Parity with the reference:
  - Alpaca prompt template                 (datautils/dataset_instruction_finetune.py:6-25)
  - Phi-style template variant             (:28-42)
  - pre-tokenized prompt+response with
    recorded instruction length            (:45-76)
  - collator: append eos, pad, shift,
    mask all-but-first pad and the
    instruction prefix with ignore_index   (datautils/dataloader_instruction_finetune.py:10-50)

TPU-first difference: instead of emitting -100 sentinel targets for a
dynamic batch_max_length (a new XLA program per batch shape), we emit
fixed-shape (B, max_length) inputs/targets plus a float ``loss_weight`` mask
(1.0 where the reference would supervise, 0.0 where it writes -100). A
weighted-mean cross entropy over these weights is mathematically identical
to torch F.cross_entropy's default mean over non-ignored positions.

The reference's pad-id defect (§2.3 #8: hardcoded GPT-2 eos 50256 even for
LLaMA) is fixed by taking pad/eos ids from the model config.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence, Tuple

import numpy as np


def format_input(entry: Dict[str, str]) -> str:
    """Alpaca prompt template (reference dataset_instruction_finetune.py:6-25)."""
    instruction_text = (
        "Below is an instruction that describes a task. "
        "Write a response that appropriately completes the request."
        f"\n\n### Instruction:\n{entry['instruction']}"
    )
    input_text = f"\n\n### Input:\n{entry['input']}" if entry.get("input") else ""
    return instruction_text + input_text


def format_input_phi(entry: Dict[str, str]) -> str:
    """Phi-style template (reference dataset_instruction_finetune.py:28-42)."""
    instruction_text = f"<|user|>\n{entry['instruction']}"
    input_text = f"\n{entry['input']}" if entry.get("input") else ""
    return instruction_text + input_text


class InstructionDataset:
    """Pre-tokenize prompt+response per record, remembering the prompt length
    so the collator can mask it (reference dataset_instruction_finetune.py:45-76).
    """

    def __init__(self, data: Sequence[Dict[str, str]], tokenizer,
                 style: str = "alpaca"):
        fmt = format_input if style == "alpaca" else format_input_phi
        resp_prefix = ("\n\n### Response:\n" if style == "alpaca"
                       else "\n<|assistant|>:\n")
        self.data = list(data)
        self.encoded_texts: List[List[int]] = []
        self.instruction_lengths: List[int] = []
        for entry in self.data:
            prompt = fmt(entry)
            full_text = prompt + resp_prefix + entry["output"]
            self.encoded_texts.append(tokenizer.encode(full_text))
            self.instruction_lengths.append(len(tokenizer.encode(prompt)))

    def __getitem__(self, index: int) -> Tuple[int, List[int]]:
        return self.instruction_lengths[index], self.encoded_texts[index]

    def __len__(self) -> int:
        return len(self.data)


def collate_batch(batch: Sequence[Tuple[int, List[int]]], *,
                  pad_token_id: int, allowed_max_length: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Fixed-shape collate: (inputs, targets, loss_weight), each
    (B, allowed_max_length).

    Semantics per row (reference dataloader_instruction_finetune.py:21-50):
      seq     = tokens + [eos-as-pad]; then pad to a fixed length
      inputs  = seq[:-1]; targets = seq[1:]
      weights = 0 where targets is padding (except the FIRST pad, which
                supervises the eos), 0 over the instruction prefix
                (targets[:instr_len-1]), 1 elsewhere; rows truncate to
                allowed_max_length.
    """
    T = allowed_max_length
    B = len(batch)
    inputs = np.full((B, T), pad_token_id, np.int32)
    targets = np.full((B, T), pad_token_id, np.int32)
    weights = np.zeros((B, T), np.float32)
    for i, (instr_len, item) in enumerate(batch):
        seq = list(item) + [pad_token_id]
        # pad to T+1 so inputs/targets both reach length T after shifting
        seq = (seq + [pad_token_id] * (T + 1 - len(seq)))[: T + 1]
        row_in = np.asarray(seq[:-1], np.int32)
        row_tg = np.asarray(seq[1:], np.int32)
        w = np.ones(T, np.float32)
        pad_pos = np.nonzero(row_tg == pad_token_id)[0]
        # mask all pad targets except the first (the supervised eos) —
        # note: like the reference, this also masks genuine in-sequence
        # occurrences of the pad id beyond the first
        if len(pad_pos) > 1:
            w[pad_pos[1:]] = 0.0
        w[: max(0, instr_len - 1)] = 0.0
        inputs[i], targets[i], weights[i] = row_in, row_tg, w
    return inputs, targets, weights


class InstructLoader:
    """Loader for instruction finetuning (reference DataloaderIF,
    dataloader_instruction_finetune.py:53-134): 90/10 record split, shuffled
    fixed-shape batches, per-process sharding."""

    def __init__(self, tokenizer, batch_size: int, max_length: int,
                 pad_token_id: int, dataset_name: str = "alpaca",
                 train_ratio: float = 0.90, process_index: int = 0,
                 process_count: int = 1, seed: int = 123):
        if dataset_name.lower() not in ("alpaca",):
            raise ValueError(f"Dataset '{dataset_name}' is not supported.")
        self.tokenizer = tokenizer
        self.batch_size = batch_size
        self.max_length = max_length
        self.pad_token_id = pad_token_id
        self.train_ratio = train_ratio
        self.process_index = process_index
        self.process_count = process_count
        self.seed = seed

    def create_datasets(self, records: Sequence[Dict[str, str]]
                        ) -> Tuple[InstructionDataset, InstructionDataset]:
        split = int(self.train_ratio * len(records))
        return (InstructionDataset(records[:split], self.tokenizer),
                InstructionDataset(records[split:], self.tokenizer))

    def batches(self, dataset: InstructionDataset, *, shuffle: bool = True,
                epoch: int = 0
                ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        n = len(dataset)
        order = np.arange(n)
        if shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        global_bs = self.batch_size * self.process_count
        for b in range(n // global_bs):
            sl = order[b * global_bs:(b + 1) * global_bs]
            mine = sl[self.process_index::self.process_count]
            yield collate_batch([dataset[j] for j in mine],
                                pad_token_id=self.pad_token_id,
                                allowed_max_length=self.max_length)

    def num_batches(self, dataset: InstructionDataset) -> int:
        return len(dataset) // (self.batch_size * self.process_count)

    def get_total_steps_epoch(self, files: List[str], read_fn=None) -> int:
        """Reference dataloader_instruction_finetune.py:123-134."""
        from building_llm_from_scratch_tpu.utils.io import read_json_file

        read_fn = read_fn or read_json_file
        total = 0
        for path in files:
            records = read_fn(path)
            train, _ = self.create_datasets(records)
            total += self.num_batches(train)
        return total
