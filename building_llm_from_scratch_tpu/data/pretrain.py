"""Pretraining data pipeline: sliding-window causal-LM batches.

Parity with the reference:
  - tokenize whole text once w/ eos allowed   (datautils/dataset.py:26)
  - sliding windows of max_length w/ stride,
    targets = inputs shifted by one           (datautils/dataset.py:29-34)
  - 90/10 char-level train/val split          (datautils/dataloader.py:66-85)
  - per-epoch reshuffle (set_epoch analog)    (train.py:169-170)
  - total-steps pre-pass over all files       (datautils/dataloader.py:87-103)

TPU-first differences: batches are fixed-shape numpy arrays (drop_last
always, so every jit'd step sees one shape); sharding across data-parallel
processes is an index stride over the global batch stream (replacing torch's
DistributedSampler), handled by the caller via ``process_index``/
``process_count``.

Host-overlap round additions:
  - ``make_windows`` returns ZERO-COPY ``sliding_window_view`` views over
    the token array instead of materializing an (N, T) gather-index array
    plus full window copies: resident host memory per corpus file is one
    token array (1x), not windows + tokens (~2x+), and the per-batch copy
    happens at yield time via fancy indexing in ``batches``.
  - ``TokenCache``: a per-(file, tokenizer, max_length, stride,
    train_ratio) token-id cache so the total-steps pre-pass and every
    subsequent epoch reuse ONE tokenization per file instead of re-reading
    and re-encoding the whole corpus each time. In-memory always; with a
    ``cache_dir`` the ids also persist as ``.npz`` across relaunches
    (``--tokenizer_cache_dir``), keyed by file identity (path, mtime,
    size) so an edited corpus re-tokenizes. The train/val ids are cached
    as the PAIR produced by the char-level split — BPE is not
    concatenation-stable, so caching the full text's ids and re-splitting
    token-side would change batches.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from building_llm_from_scratch_tpu.obs.metrics import emit_event
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


def make_windows(token_ids: np.ndarray, max_length: int,
                 stride: int) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding windows: inputs (N, T) and shifted targets (N, T).

    Reference: datautils/dataset.py:29-34 (windows of ``max_length`` every
    ``stride`` tokens; partial trailing windows dropped).

    Both returned arrays are read-only **views** over ``token_ids``
    (``np.lib.stride_tricks.sliding_window_view``): no index array, no
    window copies — resident memory is the token array alone. Consumers
    that batch by fancy indexing (``inputs[rows]``) get a fresh writable
    copy of just that batch, which is exactly the copy-at-yield-time
    contract the loader wants.
    """
    token_ids = np.ascontiguousarray(token_ids, dtype=np.int32)
    n = len(token_ids) - max_length          # need max_length+1 tokens per row
    if n <= 0:
        return (np.zeros((0, max_length), np.int32),
                np.zeros((0, max_length), np.int32))
    # windows of max_length+1 every `stride`, then split into the
    # input/target halves — two overlapping views, zero copies
    win = np.lib.stride_tricks.sliding_window_view(
        token_ids, max_length + 1)[:n:stride]
    return win[:, :-1], win[:, 1:]


class PretrainDataset:
    """Tokenize once, window lazily (reference DatasetPT, datautils/dataset.py:6)."""

    def __init__(self, text: Optional[str], tokenizer, max_length: int,
                 stride: int, token_ids: Optional[np.ndarray] = None):
        if token_ids is None:
            ids = tokenizer.encode(text, allowed_special={"<|endoftext|>"})
            token_ids = np.asarray(ids, dtype=np.int32)
        self.token_ids = np.asarray(token_ids, dtype=np.int32)
        self.inputs, self.targets = make_windows(self.token_ids, max_length,
                                                 stride)

    @classmethod
    def from_token_ids(cls, token_ids: np.ndarray, max_length: int,
                       stride: int) -> "PretrainDataset":
        """Build from already-tokenized ids (the TokenCache hit path)."""
        return cls(None, None, max_length, stride, token_ids=token_ids)

    def __len__(self) -> int:
        return len(self.inputs)


def _num_windows(n_tokens: int, max_length: int, stride: int) -> int:
    """len(PretrainDataset) without building it: window count of
    ``make_windows`` over ``n_tokens`` tokens."""
    n = n_tokens - max_length
    return 0 if n <= 0 else len(range(0, n, stride))


class TokenCache:
    """Tokenize-once cache for the pretrain path.

    One entry per (file identity, tokenizer, max_length, stride,
    train_ratio): the (train_ids, val_ids) pair the char-level split
    produces. ``max_length``/``stride`` don't change tokenization, but
    they key the entry anyway so a cache_dir shared across runs with
    different windowing never aliases by accident. File identity is
    (abspath, mtime_ns, size) — an edited corpus misses and re-encodes.
    """

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir
        self._mem: dict = {}

    #: Probe text for the tokenizer fingerprint: mixed case, digits,
    #: punctuation and whitespace so two different vocab files (same class,
    #: same vocab_size — e.g. two sentencepiece models) encode it
    #: differently with overwhelming probability.
    _PROBE = "The 3 quick brown foxes JUMPED over 42 lazy dogs!?\n\t'"

    @classmethod
    def _tokenizer_id(cls, tokenizer) -> str:
        # class name + vocab_size alone alias across tokenizer ASSETS (two
        # sp/BPE models with equal vocab sizes): fingerprint an actual
        # encoding so a shared --tokenizer_cache_dir never serves ids from
        # the wrong vocabulary. Probed once per tokenizer instance.
        fp = getattr(tokenizer, "_token_cache_fp", None)
        if fp is None:
            try:
                ids = tokenizer.encode(cls._PROBE)
                fp = hashlib.sha256(
                    np.asarray(ids, np.int64).tobytes()).hexdigest()[:12]
            except Exception:  # exotic encode() signature: fall back to
                fp = "noprobe"  # class+vocab keying only
            try:
                tokenizer._token_cache_fp = fp
            except Exception:   # __slots__ etc.: re-probe per call
                pass
        return (f"{type(tokenizer).__name__}"
                f"-v{getattr(tokenizer, 'vocab_size', '')}-{fp}")

    def _key(self, path: str, tokenizer, max_length: int, stride: int,
             train_ratio: float, eos_text: str) -> tuple:
        st = os.stat(path)
        return (os.path.abspath(path), st.st_mtime_ns, st.st_size,
                self._tokenizer_id(tokenizer), int(max_length), int(stride),
                round(float(train_ratio), 6), eos_text)

    def _disk_path(self, key: tuple) -> Optional[str]:
        if self.cache_dir is None:
            return None
        digest = hashlib.sha256(repr(key).encode()).hexdigest()[:32]
        return os.path.join(self.cache_dir, f"tok_{digest}.npz")

    def get(self, path: str, tokenizer, max_length: int, stride: int,
            train_ratio: float, eos_text: str, encode_fn
            ) -> Tuple[np.ndarray, np.ndarray]:
        """(train_ids, val_ids) for ``path``, tokenizing at most once.

        ``encode_fn(path) -> (train_ids, val_ids)`` runs only on a miss
        (the loader passes its read+split+encode closure).
        """
        try:
            key = self._key(path, tokenizer, max_length, stride, train_ratio,
                            eos_text)
        except OSError:
            # path not stat-able (synthetic read_fn feeds): no identity to
            # key on, so skip caching rather than alias entries
            return encode_fn(path)
        hit = self._mem.get(key)
        if hit is not None:
            return hit
        disk = self._disk_path(key)
        if disk is not None and os.path.isfile(disk):
            try:
                with np.load(disk) as z:
                    pair = (np.asarray(z["train"], np.int32),
                            np.asarray(z["val"], np.int32))
                self._mem[key] = pair
                emit_event("tokenize_cache", file=os.path.basename(path),
                           source="disk", tokens=int(pair[0].size
                                                     + pair[1].size))
                return pair
            except Exception as e:   # corrupt cache file: re-tokenize
                logger.warning("Token cache %s unreadable (%s); "
                               "re-tokenizing.", disk, e)
        t0 = time.perf_counter()
        pair = encode_fn(path)
        pair = (np.asarray(pair[0], np.int32), np.asarray(pair[1], np.int32))
        self._mem[key] = pair
        emit_event("tokenize_cache", file=os.path.basename(path),
                   source="encoded", tokens=int(pair[0].size + pair[1].size),
                   seconds=round(time.perf_counter() - t0, 4))
        if disk is not None:
            try:
                os.makedirs(self.cache_dir, exist_ok=True)
                tmp = disk + ".tmp"
                np.savez(tmp, train=pair[0], val=pair[1])
                # np.savez appends .npz to paths without it
                os.replace(tmp if os.path.exists(tmp) else tmp + ".npz",
                           disk)
            except OSError as e:     # cache write failure must not kill a run
                logger.warning("Token cache write to %s failed (%s).",
                               disk, e)
        return pair


class PretrainLoader:
    """Batched loader over one or more raw-text corpora.

    Reference DataloaderPT (datautils/dataloader.py:9): 90/10 char split,
    shuffled fixed-shape batches, per-process sharding for data parallelism.
    """

    def __init__(self, tokenizer, batch_size: int, max_length: int,
                 stride: Optional[int] = None, train_ratio: float = 0.90,
                 process_index: int = 0, process_count: int = 1,
                 seed: int = 123, token_cache_dir: Optional[str] = None):
        self.tokenizer = tokenizer
        self.batch_size = batch_size
        self.max_length = max_length
        self.stride = stride or max_length
        self.train_ratio = train_ratio
        self.process_index = process_index
        self.process_count = process_count
        self.seed = seed
        self.token_cache = TokenCache(token_cache_dir)

    def split_text(self, text: str) -> Tuple[str, str]:
        """Char-level 90/10 split (reference dataloader.py:70)."""
        split_idx = int(self.train_ratio * len(text))
        return text[:split_idx], text[split_idx:]

    def create_datasets(self, raw_text: str
                        ) -> Tuple[PretrainDataset, PretrainDataset]:
        train_text, val_text = self.split_text(raw_text)
        train = PretrainDataset(train_text, self.tokenizer, self.max_length,
                                self.stride)
        val = PretrainDataset(val_text, self.tokenizer, self.max_length,
                              self.stride)
        return train, val

    def _file_token_ids(self, path: str, eos_text: str, read_fn=None
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """(train_ids, val_ids) for one corpus file + trailing eos,
        through the tokenize-once cache."""
        from building_llm_from_scratch_tpu.utils.io import read_text_file

        read_fn = read_fn or read_text_file

        def encode(p: str) -> Tuple[np.ndarray, np.ndarray]:
            text = read_fn(p) + f" {eos_text} "
            train_text, val_text = self.split_text(text)
            enc = lambda t: np.asarray(
                self.tokenizer.encode(t,
                                      allowed_special={"<|endoftext|>"}),
                np.int32)
            return enc(train_text), enc(val_text)

        return self.token_cache.get(path, self.tokenizer, self.max_length,
                                    self.stride, self.train_ratio, eos_text,
                                    encode)

    def create_datasets_for_file(self, path: str, eos_text: str,
                                 read_fn=None
                                 ) -> Tuple[PretrainDataset, PretrainDataset]:
        """Datasets for one corpus file (+ the `` {eos_text} `` suffix the
        trainer appends, reference train.py:164-165), tokenizing each file
        at most once per run — epoch 2+ and the total-steps pre-pass are
        cache hits, not a re-read + re-encode of the whole corpus."""
        train_ids, val_ids = self._file_token_ids(path, eos_text, read_fn)
        return (PretrainDataset.from_token_ids(train_ids, self.max_length,
                                               self.stride),
                PretrainDataset.from_token_ids(val_ids, self.max_length,
                                               self.stride))

    def batches(self, dataset: PretrainDataset, *, shuffle: bool = True,
                epoch: int = 0) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield fixed-shape (inputs, targets) batches of this process's shard.

        Shuffling is deterministic in (seed, epoch) on every process — the
        ``sampler.set_epoch`` pattern (reference train.py:169-170) — and each
        process takes a strided slice of the global batch order.

        ``dataset.inputs``/``.targets`` are zero-copy window views; the
        fancy-indexed gather below is where (and only where) each batch's
        rows materialize.
        """
        n = len(dataset)
        order = np.arange(n)
        if shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        global_bs = self.batch_size * self.process_count
        n_batches = self._num_global_batches(n)
        for b in range(n_batches):
            sl = order[b * global_bs:(b + 1) * global_bs]
            mine = sl[self.process_index::self.process_count]
            yield dataset.inputs[mine], dataset.targets[mine]

    def _num_global_batches(self, n_windows: int) -> int:
        """drop_last batch count: full global batches only (fixed XLA
        shapes). THE single home of the windows->steps formula — iterate,
        num_batches and get_total_steps_epoch must all agree or the cosine
        schedule horizon diverges from the steps actually taken."""
        return n_windows // (self.batch_size * self.process_count)

    def num_batches(self, dataset: PretrainDataset) -> int:
        return self._num_global_batches(len(dataset))

    def get_total_steps_epoch(self, files: List[str],
                              eos_text: str = "<|endoftext|>",
                              read_fn=None) -> int:
        """Count total optimizer steps per epoch across all corpus files.

        The reference re-reads and re-tokenizes every file up front
        (dataloader.py:87-103) to drive the cosine schedule; this pre-pass
        now also WARMS the tokenize-once cache, so the training epochs that
        follow reuse its encodings instead of paying them again.
        """
        total = 0
        for path in files:
            train_ids, _val_ids = self._file_token_ids(path, eos_text,
                                                       read_fn)
            n_windows = _num_windows(len(train_ids), self.max_length,
                                     self.stride)
            total += self._num_global_batches(n_windows)
        return total
