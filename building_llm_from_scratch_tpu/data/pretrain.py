"""Pretraining data pipeline: sliding-window causal-LM batches.

Parity with the reference:
  - tokenize whole text once w/ eos allowed   (datautils/dataset.py:26)
  - sliding windows of max_length w/ stride,
    targets = inputs shifted by one           (datautils/dataset.py:29-34)
  - 90/10 char-level train/val split          (datautils/dataloader.py:66-85)
  - per-epoch reshuffle (set_epoch analog)    (train.py:169-170)
  - total-steps pre-pass over all files       (datautils/dataloader.py:87-103)

TPU-first differences: batches are fixed-shape numpy arrays (drop_last
always, so every jit'd step sees one shape); sharding across data-parallel
processes is an index stride over the global batch stream (replacing torch's
DistributedSampler), handled by the caller via ``process_index``/
``process_count``.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np


def make_windows(token_ids: np.ndarray, max_length: int,
                 stride: int) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize sliding windows: inputs (N, T) and shifted targets (N, T).

    Reference: datautils/dataset.py:29-34 (windows of ``max_length`` every
    ``stride`` tokens; partial trailing windows dropped).
    """
    token_ids = np.asarray(token_ids, dtype=np.int32)
    n = len(token_ids) - max_length          # need max_length+1 tokens per row
    if n <= 0:
        return (np.zeros((0, max_length), np.int32),
                np.zeros((0, max_length), np.int32))
    starts = np.arange(0, n, stride)
    idx = starts[:, None] + np.arange(max_length)[None, :]
    return token_ids[idx], token_ids[idx + 1]


class PretrainDataset:
    """Tokenize once, window lazily (reference DatasetPT, datautils/dataset.py:6)."""

    def __init__(self, text: str, tokenizer, max_length: int, stride: int):
        ids = tokenizer.encode(text, allowed_special={"<|endoftext|>"})
        self.token_ids = np.asarray(ids, dtype=np.int32)
        self.inputs, self.targets = make_windows(self.token_ids, max_length,
                                                 stride)

    def __len__(self) -> int:
        return len(self.inputs)


class PretrainLoader:
    """Batched loader over one or more raw-text corpora.

    Reference DataloaderPT (datautils/dataloader.py:9): 90/10 char split,
    shuffled fixed-shape batches, per-process sharding for data parallelism.
    """

    def __init__(self, tokenizer, batch_size: int, max_length: int,
                 stride: Optional[int] = None, train_ratio: float = 0.90,
                 process_index: int = 0, process_count: int = 1,
                 seed: int = 123):
        self.tokenizer = tokenizer
        self.batch_size = batch_size
        self.max_length = max_length
        self.stride = stride or max_length
        self.train_ratio = train_ratio
        self.process_index = process_index
        self.process_count = process_count
        self.seed = seed

    def split_text(self, text: str) -> Tuple[str, str]:
        """Char-level 90/10 split (reference dataloader.py:70)."""
        split_idx = int(self.train_ratio * len(text))
        return text[:split_idx], text[split_idx:]

    def create_datasets(self, raw_text: str
                        ) -> Tuple[PretrainDataset, PretrainDataset]:
        train_text, val_text = self.split_text(raw_text)
        train = PretrainDataset(train_text, self.tokenizer, self.max_length,
                                self.stride)
        val = PretrainDataset(val_text, self.tokenizer, self.max_length,
                              self.stride)
        return train, val

    def batches(self, dataset: PretrainDataset, *, shuffle: bool = True,
                epoch: int = 0) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield fixed-shape (inputs, targets) batches of this process's shard.

        Shuffling is deterministic in (seed, epoch) on every process — the
        ``sampler.set_epoch`` pattern (reference train.py:169-170) — and each
        process takes a strided slice of the global batch order.
        """
        n = len(dataset)
        order = np.arange(n)
        if shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        # drop_last semantics: only full global batches (fixed XLA shapes)
        global_bs = self.batch_size * self.process_count
        n_batches = n // global_bs
        for b in range(n_batches):
            sl = order[b * global_bs:(b + 1) * global_bs]
            mine = sl[self.process_index::self.process_count]
            yield dataset.inputs[mine], dataset.targets[mine]

    def num_batches(self, dataset: PretrainDataset) -> int:
        return len(dataset) // (self.batch_size * self.process_count)

    def get_total_steps_epoch(self, files: List[str],
                              eos_text: str = "<|endoftext|>",
                              read_fn=None) -> int:
        """Count total optimizer steps per epoch across all corpus files.

        Reference re-reads and re-tokenizes every file up front
        (dataloader.py:87-103) to drive the cosine schedule; so do we,
        including the trailing `` {eos_text} `` the trainer appends per file
        (reference train.py:164-165).
        """
        from building_llm_from_scratch_tpu.utils.io import read_text_file

        read_fn = read_fn or read_text_file
        total = 0
        for path in files:
            text = read_fn(path) + f" {eos_text} "
            train, _val = self.create_datasets(text)
            total += self.num_batches(train)
        return total
